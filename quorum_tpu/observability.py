"""Observability: request tracing, latency histograms, log channels, profiling.

Parity with the reference's two-channel logging (SURVEY.md §5.5):
the ``aggregation`` logger records individual backend responses, aggregator
prompts, and final combined output; :func:`setup_aggregation_log` attaches the
``logs/aggregation.log`` file handler the reference configured at import time
(/root/reference/src/quorum/oai_proxy.py:17-37) — here it is explicit and
lazy, so importing the package has no filesystem side effects.

Beyond parity (the reference had static ``chatcmpl-parallel*`` ids, no timing,
and no metrics at all), this module is the instrumentation spine every layer
records into:

  - :class:`Histogram` / :class:`MetricsRegistry` — Prometheus histogram
    families (``_bucket``/``_sum``/``_count`` exposition) exported on
    ``/metrics``: request duration, TTFT, inter-token gap, queue wait,
    prefill, decode-chunk. Pure stdlib, thread-safe, O(buckets) memory.
  - :class:`RequestTrace` — the request-scoped span recorder: every request
    gets one trace (id surfaced in ``X-Request-Id``) that the server,
    strategies, backends, and the engine scheduler append spans to
    (queue-wait → prefill → decode → aggregate → sse-flush), plus wire-level
    TTFT and per-token flush timings. Supersedes the round-1 ``PhaseTimer``
    (kept as an alias — the API is a superset).
  - :class:`TraceStore` — bounded ring buffer of completed traces plus the
    in-flight set, served as JSON from ``GET /debug/traces``.
  - :func:`validate_exposition` — a promtool-style pure-Python checker for
    the full ``/metrics`` text (``make metrics-check``).

TPU profiling: when ``QUORUM_TPU_PROFILE_DIR`` is set, :func:`maybe_profile`
wraps a request in ``jax.profiler.trace`` so device timelines land in
TensorBoard-readable traces — the TPU-native analog of a CPU profiler.
:func:`profile_process` is the on-demand variant behind
``POST /debug/profile?seconds=N`` (single-flight — the jax profiler is
process-global and cannot nest; concurrent requests get 409).

The Prometheus primitive types (Histogram/Counter/Gauge/MetricsRegistry)
and :func:`validate_exposition` moved to ``quorum_tpu.telemetry.metrics``
when the telemetry package grew the flight recorder / latency-model / SLO
subsystems (ISSUE 12) — re-exported here so every existing import keeps
working; the REGISTERED families stay in this module.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Iterator

from quorum_tpu.telemetry.metrics import (  # noqa: F401  (re-exports)
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    _esc_label,
    _fmt_float,
    _fmt_labels,
    _split_labels,
    validate_exposition,
)
from quorum_tpu.telemetry.recorder import RECORDER

logger = logging.getLogger(__name__)
aggregation_logger = logging.getLogger("aggregation")

_configured_paths: set[Path] = set()


def setup_aggregation_log(log_dir: str | os.PathLike = "logs") -> Path:
    """Attach the ``logs/aggregation.log`` file handler (idempotent per path —
    a later call with a *different* directory attaches an additional handler
    rather than silently keeping only the first location).

    Mirrors the reference's channel: dir auto-created, a test write performed
    so misconfiguration fails loudly at startup, INFO level, not propagated to
    the root logger's console output.
    """
    path = (Path(log_dir) / "aggregation.log").resolve()
    if path in _configured_paths:
        return path
    path.parent.mkdir(parents=True, exist_ok=True)
    handler = logging.FileHandler(path)
    handler.setFormatter(
        logging.Formatter("%(asctime)s - %(name)s - %(levelname)s - %(message)s")
    )
    aggregation_logger.addHandler(handler)
    aggregation_logger.setLevel(logging.INFO)
    aggregation_logger.propagate = False
    aggregation_logger.info("Aggregation logging initialized")  # test write
    _configured_paths.add(path)
    return path


# ---- histogram metrics -----------------------------------------------------
# (Primitive types live in quorum_tpu/telemetry/metrics.py; this module
# registers the serving families on the process-wide registry below.)


METRICS = MetricsRegistry()

# The canonical serving-latency families (ISSUE 1 acceptance set + the
# engine-phase pair the scheduler records). All in seconds.
REQUEST_DURATION = METRICS.histogram(
    "quorum_tpu_request_duration_seconds",
    "End-to-end request wall time (headers in to last byte out).")
TTFT = METRICS.histogram(
    "quorum_tpu_ttft_seconds",
    "Time to first content byte on the SSE wire.")
INTER_TOKEN = METRICS.histogram(
    "quorum_tpu_inter_token_seconds",
    "Gap between consecutive content flushes on the SSE wire.",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0))
QUEUE_WAIT = METRICS.histogram(
    "quorum_tpu_queue_wait_seconds",
    "Engine admission-queue wait (submit to slot claim).")
PREFILL = METRICS.histogram(
    "quorum_tpu_prefill_seconds",
    "Prompt prefill wall time (admission start to cache-complete; chunked "
    "admissions include interleaved decode turns).")
DECODE_CHUNK = METRICS.histogram(
    "quorum_tpu_decode_chunk_seconds",
    "One blocking decode-chunk reap (fetch + delivery) of the scheduler "
    "loop; pipelined chunks' in-flight wait is excluded.",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0))
# Depth of the decode-dispatch ring right now (engine/engine.py: chunks
# dispatched but not yet read; 0 when the pipeline is drained). Last-writer-
# wins across engines sharing the process.
PIPELINE_DEPTH = METRICS.gauge(
    "quorum_tpu_decode_pipeline_inflight",
    "Decode chunks currently in flight on the device (dispatch ring depth).")
# Megachunk decode (decode_loop=C, engine/engine.py): chunk segments ONE
# dispatch actually produced tokens for — 1 per dispatch when unfused, up
# to C when the device rolled chunk-to-chunk inside one program, 0 when a
# dispatch's rows had all finished on device before it ran. The C× win is
# this histogram's mean against decode_chunks_total staying ~flat.
DECODE_LOOP_CHUNKS = METRICS.histogram(
    "quorum_tpu_decode_loop_chunks",
    "Decode chunk segments covered by one device dispatch (decode_loop "
    "megachunk fusion; per-chunk n_valid counts the segments that "
    "produced tokens).",
    buckets=(1, 2, 4, 8, 16, 32, 64))

# Disaggregated prefill/decode serving (tpu://…&disagg=P+D — docs/
# tpu_backends.md): admission prefill runs on its own device group and a
# completed admission's KV prefix hands off device→device into the claimed
# decode-group slot (quorum_tpu/cache/kv_transfer.py). The handoff pair
# counts every KV byte that crosses the group boundary; the per-group
# occupancy gauges are the split view of the old single-mesh busy_slots.
KV_HANDOFF_BYTES = METRICS.counter(
    "quorum_tpu_kv_handoff_bytes_total",
    "KV cache bytes handed off between device groups (prefill-group "
    "staging -> decode-group slot), labelled route= direct (same-layout "
    "device->device put), reshard (either side partitioned: per-group tp= "
    "or an sp-sharded staging cache, re-laid-out on the fly), host-bounce "
    "(the explicit d2h+h2d fallback), or resident (zero-drain same-mesh "
    "injection: 0 bytes cross any boundary).")
KV_HANDOFF_SECONDS = METRICS.histogram(
    "quorum_tpu_kv_handoff_seconds",
    "One chunk-granular KV handoff between device groups (slice dispatch "
    "to landed-on-target), blocking on the prefill scheduler thread; "
    "route= labels as on quorum_tpu_kv_handoff_bytes_total.",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0))
# Paged KV slot memory (tpu://…&kv_pages=1, docs/tpu_backends.md): the
# dense [n_slots, max_seq] rectangle becomes a refcounted page pool + a
# per-row page table. Pool occupancy is the capacity story (rows admit
# while pages remain, not while worst-case rectangles remain); the alias/
# COW pair is the prefix-reuse economics — a tier-0 hit installs page
# REFERENCES (zero KV bytes moved), and only a partially-reused boundary
# page pays a one-page copy-on-write.
KV_PAGES_ALLOCATED = METRICS.gauge(
    "quorum_tpu_kv_pages_allocated",
    "KV pool pages currently referenced by a live or retained chain "
    "(kv_pages=1 engines; 0/absent on dense layouts). Last-writer-wins "
    "across engines sharing the process, like the other engine gauges.")
KV_PAGES_FREE = METRICS.gauge(
    "quorum_tpu_kv_pages_free",
    "KV pool pages on the free list (kv_pages=1 engines). "
    "free + allocated == kv_pool_pages.")
KV_PAGE_ALIAS_HITS = METRICS.counter(
    "quorum_tpu_kv_page_alias_hits_total",
    "Tier-0 prefix hits served by page ALIASING under kv_pages=1: the "
    "admission installed refcounted references to the donor's pages "
    "instead of copying KV bytes (kv_handoff_bytes stays 0 for these).")
KV_PAGE_COW_COPIES = METRICS.counter(
    "quorum_tpu_kv_page_cow_copies_total",
    "Copy-on-write boundary-page copies under kv_pages=1: a reused "
    "prefix ended mid-page, so the partially-shared page was copied "
    "(one page) before the new tenant's suffix writes. Full pages "
    "alias by reference and never pay this.")
DECODE_STAGE_OCCUPANCY = METRICS.gauge(
    "quorum_tpu_decode_stage_occupancy",
    "Active decode rows per pipeline-staged row group (pp>1 engines: "
    "group g's rows are stage g's microbatch slot in the staged ring — "
    "docs/scaling.md). Bare sample stays 0 on unstaged engines; "
    "last-writer-wins across engines sharing the process.")
PREFILL_GROUP_ACTIVE = METRICS.gauge(
    "quorum_tpu_prefill_group_active",
    "In-flight chunked admissions occupying the prefill device group "
    "right now (last-writer-wins across engines sharing the process).")
DECODE_GROUP_ACTIVE = METRICS.gauge(
    "quorum_tpu_decode_group_active",
    "Busy decode-group slots right now (last-writer-wins across engines "
    "sharing the process).")

# Zero-drain continuous batching (tpu://…&zero_drain=1 — docs/
# tpu_backends.md): staged in-flight row injection on colocated engines.
# Admissions prefill into a same-mesh staging cache and the new row's KV
# injects into its claimed slot at a reap boundary while the
# decode_pipeline=K × decode_loop=C ring holds the other rows' in-flight
# state — the structural admission-pressure clamp (C=1/K=1) is retired.
ADMISSION_OVERLAP = METRICS.counter(
    "quorum_tpu_admission_overlap_total",
    "Staged-injection admissions that registered onto a live ring "
    "(in-flight dispatches or active resident rows the admission never "
    "drained or clamped). Structurally 0 on drain-based colocated "
    "engines, whose admissions never ride the injection queue.")
ADMISSION_STALL_SECONDS = METRICS.counter(
    "quorum_tpu_admission_stall_seconds_total",
    "Wall time the decode dispatch ring spent clamped to depth 1 for an "
    "admission (the drain-based coupling). Structurally 0 under "
    "zero_drain=1 and under disagg=P+D, where admission pressure never "
    "clamps the ring.")

# Tiered KV prefix store (quorum_tpu/cache/prefix_store.py + the engine's
# snapshot/restore hooks, docs/prefix_cache.md): host-RAM retention of
# decoded KV prefixes beyond the resident slots. Process-wide families —
# the per-engine split is in the quorum_tpu_engine_prefix_store_* block.
PREFIX_STORE_HITS = METRICS.counter(
    "quorum_tpu_prefix_store_hits_total",
    "Admissions whose prompt prefix was restored from the host prefix "
    "store (the store's match beat the slot-resident LCP).")
PREFIX_STORE_RESTORED_TOKENS = METRICS.counter(
    "quorum_tpu_prefix_store_restored_tokens_total",
    "Prompt tokens restored host->device instead of being re-prefilled.")
PREFIX_STORE_EVICTIONS = METRICS.counter(
    "quorum_tpu_prefix_store_evictions_total",
    "KV chunks evicted from the host prefix store (byte-budget LRU).")
PREFIX_STORE_BYTES = METRICS.gauge(
    "quorum_tpu_prefix_store_bytes",
    "Bytes of KV prefix data held in the host store right now "
    "(last-writer-wins across engines sharing the process).")
PREFIX_STORE_RESTORE = METRICS.histogram(
    "quorum_tpu_prefix_store_restore_seconds",
    "Host->device restore of a matched KV prefix into a claimed slot "
    "(transfer + cache write, blocking on the scheduler thread).",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0))

# Fault-contained serving (docs/robustness.md): request deadlines, HTTP
# backend retry, and the engine failure breaker. Per-engine breakdowns
# (rebuilds_total, breaker_state, deadline_exceeded_total) live in the
# quorum_tpu_engine_* block each engine's metrics() feeds.
# Constrained decoding (quorum_tpu/constrain/ + the engine's on-device
# DFA threading — docs/structured_output.md).
CONSTRAINED_REQUESTS = METRICS.counter(
    "quorum_tpu_constrained_requests_total",
    "Requests served under a response_format grammar (json_object / "
    "json_schema / regex).")
CONSTRAIN_MASKED_TOKENS = METRICS.counter(
    "quorum_tpu_constrain_masked_tokens_total",
    "Vocabulary entries masked to -inf by the on-device grammar DFA, "
    "summed over every decode step of every constrained row.")
CONSTRAIN_CACHE_HITS = METRICS.counter(
    "quorum_tpu_constrain_cache_hits_total",
    "Grammar compilations served from the (grammar, tokenizer) cache.")
CONSTRAIN_CACHE_MISSES = METRICS.counter(
    "quorum_tpu_constrain_cache_misses_total",
    "Grammar compilations that had to run (cache miss).")
CONSTRAIN_COMPILE = METRICS.histogram(
    "quorum_tpu_constrain_compile_seconds",
    "Grammar -> token-DFA compile time (regex/schema lowering, byte-DFA "
    "construction, token lifting) on a cache miss.",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0))

# Speculative decoding (engine._verify_core / _spec_loop_fn — grammar-
# aware, row-wise gated, ring-resident; docs/tpu_backends.md): turn and
# token accounting plus the per-turn acceptance histogram the bench's
# acceptance-rate number is the ratio form of.
SPEC_TURNS = METRICS.counter(
    "quorum_tpu_spec_turns_total",
    "Speculative verify turns executed (one per verify dispatch; a fused "
    "draft-model dispatch counts each executed turn of its on-device "
    "scan).")
SPEC_DRAFT_TOKENS = METRICS.counter(
    "quorum_tpu_spec_draft_tokens_total",
    "Real (non-sentinel) draft tokens proposed to verify turns, summed "
    "over rows — prompt-lookup continuations or draft-model tokens.")
SPEC_ACCEPTED_TOKENS = METRICS.counter(
    "quorum_tpu_spec_accepted_tokens_total",
    "Draft tokens accepted by verification and delivered to a consumer "
    "(the turn's own first sampled token is the model's step, not a "
    "draft — it never counts).")
SPEC_ACCEPTANCE = METRICS.histogram(
    "quorum_tpu_spec_accepted_per_turn",
    "Accepted draft tokens per row per executed verify turn (0 = only "
    "the model's own token emitted; the bucket spread IS the acceptance "
    "profile speculation's tok/s win depends on).",
    buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))

# Recompile sentinel (quorum_tpu/analysis/compile_watch.py, docs/
# static_analysis.md): XLA compiles observed AFTER the process served its
# first completed request. First-of-shape traffic still legitimately ticks
# it (the first constrained request, a new history bucket, a second
# engine); what indicates program-key drift — a shape-family leak, an
# unhashable key component — is SUSTAINED growth under steady traffic,
# which is what to alert on. The runtime half of the qlint recompile-budget
# rules and the compile_budget.json contract.
RECOMPILES = METRICS.counter(
    "quorum_tpu_recompiles_total",
    "XLA compilations observed after the first served request. Expected "
    "to tick on first-of-shape traffic; sustained growth under steady "
    "traffic indicates program-key drift (docs/static_analysis.md).")

DEADLINE_EXCEEDED = METRICS.counter(
    "quorum_tpu_deadline_exceeded_total",
    "Requests that ran past their deadline, by stage: queue = shed before "
    "admission (503 + Retry-After), prefill/decode = cancelled after "
    "admission (504), backend = an HTTP/device hop outlived its wait.")
BACKEND_RETRIES = METRICS.counter(
    "quorum_tpu_backend_retries_total",
    "HTTP backend attempts retried after a connect error or 5xx "
    "(opt-in per-backend retries= config knob), by backend.")

# Multi-replica router tier (quorum_tpu/router/, docs/scaling.md): the
# standalone prefix-affinity router process records its placement,
# failover, and prefix-migration accounting on these families; they expose
# on the ROUTER's /metrics (the same process-wide registry — on a serving
# replica they simply read 0).
ROUTER_REQUESTS = METRICS.counter(
    "quorum_tpu_router_requests_total",
    "Requests the router placed, by replica and outcome (ok = a 2xx/4xx "
    "relay, failover = this replica failed pre-stream and the request "
    "moved on, error = the relayed terminal failure).")
ROUTER_AFFINITY_HITS = METRICS.counter(
    "quorum_tpu_router_affinity_hits_total",
    "Requests served by the replica their conversation key hashes to "
    "(the bounded-load consistent-hash primary) — where the KV prefix "
    "from earlier turns lives.")
ROUTER_AFFINITY_MISSES = METRICS.counter(
    "quorum_tpu_router_affinity_misses_total",
    "Requests served AWAY from their affinity primary: bounded-load "
    "spill, failover, the primary out of the ring, or policy=random.")
ROUTER_FAILOVERS = METRICS.counter(
    "quorum_tpu_router_failovers_total",
    "Pre-first-byte upstream failures that moved a request to the next "
    "ring candidate, by the replica that failed.")
ROUTER_MIGRATED_BYTES = METRICS.counter(
    "quorum_tpu_router_migrated_bytes_total",
    "Serialized KV prefix-chunk bytes moved between replicas by the "
    "router's rotation migration (GET/PUT /debug/prefix/chunks).")
ROUTER_MIGRATED_CHAINS = METRICS.counter(
    "quorum_tpu_router_migrated_chains_total",
    "Prefix chunk chains moved between replicas by rotation migration.")
ROUTER_STREAM_RESUMES = METRICS.counter(
    "quorum_tpu_router_stream_resumes_total",
    "Mid-stream resume outcomes (docs/robustness.md 'Zero-loss streams'): "
    "resumed = the journaled stream spliced onto a sibling replica "
    "token-exactly; divergence = the sibling's replay byte-check failed "
    "and the stream degraded to the error-chunk contract; failed = a "
    "resume attempt died pre-commit and the next candidate was tried; "
    "exhausted = no candidate/deadline remained; unresumable = the "
    "journal could not cover the stream (no token-id metadata, bound "
    "overflow, or the finish chunk already relayed).")

# Native quorum serving (quorum_tpu/quorum/, docs/quorum.md — ISSUE 20):
# shared-prefix member dedup on stacked engines, the in-engine aggregation
# hop, and the router's cross-cell quorum fan-out with member-kill
# degradation.
QUORUM_DEDUP_TOKENS = METRICS.counter(
    "quorum_tpu_quorum_dedup_tokens_total",
    "Prefill tokens NOT recomputed by shared-prefix member dedup "
    "(quorum_dedup=1 on a members=M engine): a member-complete admission "
    "group with identical prompts prefills ONCE and broadcasts into the "
    "[M, ...] stacked cache, saving (M-1) x n_prompt tokens per group.")
QUORUM_DEGRADED = METRICS.counter(
    "quorum_tpu_quorum_degraded_total",
    "Quorum members dropped mid-request while the quorum was SERVED from "
    "the survivors (never failed), by reason: member_failed = a member "
    "leg died pre-first-byte on every candidate; stream_broken = a "
    "member's live stream died and token-exact resume was exhausted; "
    "resume_diverged = the replay guard refused the member's resume; "
    "no_content = a member completed empty.")
QUORUM_REQUESTS = METRICS.counter(
    "quorum_tpu_quorum_requests_total",
    "Router-tier quorum fan-outs (the quorum= body knob), by outcome: "
    "full = every member contributed, degraded = served from a strict "
    "subset of members, failed = no member produced content.")
AGGREGATE_DEGRADED = METRICS.counter(
    "quorum_tpu_aggregate_degraded_total",
    "Aggregate-strategy combines that fell back to the separator-join of "
    "the member outputs instead of a real LLM aggregation, by reason: "
    "no_aggregator = none configured, no_credentials = the aggregator "
    "required auth no header provided, error = the aggregator call "
    "failed or returned non-2xx, empty = it returned no content. The "
    "first underlying error rides the X-Quorum-Aggregate-Error response "
    "header (docs/quorum.md).")

# Fleet observability plane (ISSUE 16, docs/observability.md "Fleet
# plane"): cross-tier trace propagation, per-replica telemetry absorption,
# and burn-aware placement. Registered process-wide like the other router
# families — a serving replica reads them at zero.
ROUTER_REPLICA_BURN = METRICS.gauge(
    "quorum_tpu_router_replica_burn",
    "Last absorbed SLO burn rate per replica and class (the router's "
    "/ready poller pulls each replica's GET /debug/telemetry; stale "
    "telemetry keeps the last reading but stops driving demotion).")
ROUTER_BURN_DEMOTIONS = METRICS.counter(
    "quorum_tpu_router_burn_demotions_total",
    "Placements in which a replica was demoted to the candidate tail "
    "because its interactive-class burn rate exceeded the router's "
    "threshold (per-request reorder like bounded-load spill — membership "
    "untouched, fail-open when telemetry is stale).")
TELEMETRY_POLL_SECONDS = METRICS.histogram(
    "quorum_tpu_telemetry_poll_seconds",
    "One replica telemetry pull (GET /debug/telemetry inside the router's "
    "/ready poll sweep), request to parsed snapshot.",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0))
TRACE_PROPAGATED = METRICS.counter(
    "quorum_tpu_trace_propagated_total",
    "Requests stamped with a W3C trace-id, by source: client = an "
    "incoming traceparent was honored, router/server = this tier minted "
    "one (none arrived), engine = an engine-direct submission self-minted "
    "its flight-recorder correlation id.")

# Engine flight recorder + per-family device-time attribution + SLO
# accounting (quorum_tpu/telemetry/, docs/observability.md — ISSUE 12).
# Decode-ring dispatches attribute dispatch→ready time (issue stamp to the
# payload's non-blocking is_ready probe / fetch completion — zero new
# blocking syncs) to their compile_budget.json program family; admission-
# path programs (seg/register/hslice/hput/...) attribute the dispatch wall
# observed at their call sites. Buckets reach below the serving ladder:
# one tiny-chunk dispatch is sub-millisecond on a warm TPU.
DISPATCH_DEVICE_SECONDS = METRICS.histogram(
    "quorum_tpu_dispatch_device_seconds",
    "Per-dispatch device time by compile_budget.json program family "
    "(decode-ring families: dispatch to payload-ready; admission-path "
    "families: dispatch wall at the call site).",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
# SLO accounting (quorum_tpu/telemetry/slo.py): requests classify by
# deadline headroom into interactive/batch and score one good-or-breached
# observation per stage (ttft / inter_token / deadline) at teardown. The
# burn rate (breached/observed over a sliding window) rides /health.
SLO_GOOD = METRICS.counter(
    "quorum_tpu_slo_good_total",
    "Requests that met the stage's objective for their SLO class "
    "(class=interactive|batch, stage=ttft|inter_token|deadline).")
SLO_BREACHED = METRICS.counter(
    "quorum_tpu_slo_breached_total",
    "Requests that breached the stage's objective for their SLO class "
    "(class=interactive|batch, stage=ttft|inter_token|deadline).")
# QoS scheduler (quorum_tpu/sched/, docs/scheduling.md): mid-decode
# preemptions by VICTIM class, the generated tokens parked at preemption
# (regenerated deterministically on resume), and the pending-queue depth
# per priority class (refreshed each scheduler turn).
PREEMPTIONS = METRICS.counter(
    "quorum_tpu_preemptions_total",
    "Mid-decode preemptions by victim class (class=batch|background): a "
    "lower-class row parked at a reap boundary so a higher-class "
    "admission could take its slot (qos=1 engines only).")
PREEMPTED_TOKENS = METRICS.counter(
    "quorum_tpu_preempted_tokens_total",
    "Generated tokens parked at preemption — already delivered to their "
    "consumers, regenerated token-for-token on resume (the replay the "
    "engine byte-checks against the delivered stream).")
SCHED_QUEUE_DEPTH = METRICS.gauge(
    "quorum_tpu_sched_queue_depth",
    "Pending admissions by priority class "
    "(class=interactive|batch|background).")
# Flight-recorder self-accounting: current ring depth (refreshed on
# /metrics scrapes) and events overwritten by the bounded ring.
FLIGHT_RECORDER_EVENTS = METRICS.gauge(
    "quorum_tpu_flight_recorder_events",
    "Events currently held in the engine flight recorder's bounded ring "
    "(GET /debug/engine/timeline; QUORUM_TPU_FLIGHT_EVENTS caps it).")
FLIGHT_RECORDER_DROPPED = METRICS.counter(
    "quorum_tpu_flight_recorder_dropped_total",
    "Flight-recorder events overwritten by the bounded ring (the oldest "
    "event falls off when a new one lands on a full ring).")
# On-demand/per-request jax profiling: requests that proceeded UNTRACED
# because the process-global profiler was already busy (maybe_profile's
# silent skip, made visible — ISSUE 12 satellite).
PROFILE_SKIPPED = METRICS.counter(
    "quorum_tpu_profile_skipped_total",
    "Requests that ran unprofiled because the process-global jax "
    "profiler was busy with another trace (QUORUM_TPU_PROFILE_DIR "
    "per-request tracing, or a POST /debug/profile in flight).")

# The bounded ring's overwrite hook (the recorder itself imports nothing
# from this module — the wiring lives on this side of the boundary).
RECORDER.on_drop = FLIGHT_RECORDER_DROPPED.inc


# ---- request-scoped tracing ------------------------------------------------

# Span budget per trace: a pathological 100k-token generation must not grow
# an unbounded span list; past the cap only the drop counter advances.
MAX_SPANS = 512
# Wire flush-timing budget per trace (ttft + the first N inter-token gaps).
MAX_TOKEN_TIMES = 2048


class Span:
    """One timed phase inside a request. ``start``/``end`` are seconds
    relative to the trace's origin; ``meta`` carries small tags (backend,
    bucket, occupancy...)."""

    __slots__ = ("name", "start", "end", "meta")

    def __init__(self, name: str, start: float, end: float | None = None,
                 meta: dict | None = None):
        self.name = name
        self.start = start
        self.end = end
        self.meta = meta or {}

    @property
    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "start_s": round(self.start, 6),
            "end_s": None if self.end is None else round(self.end, 6),
            "duration_ms": (None if self.end is None
                            else round((self.end - self.start) * 1000, 3)),
        }
        if self.meta:
            out["meta"] = self.meta
        return out


class RequestTrace:
    """Span recorder for ONE request, appended to from any thread.

    The server creates it per request; the engine scheduler, strategies, and
    the SSE wire wrapper record into it through :func:`current_trace` /
    direct references. Also the :class:`PhaseTimer` replacement: ``phase()``
    (context manager), ``phases`` (name → accumulated seconds), ``total``
    and ``log()`` keep the round-1 API."""

    def __init__(self, request_id: str, mode: str = "",
                 trace_id: str = "", span_id: str = ""):
        self.request_id = request_id
        # W3C trace-context identity (telemetry/tracecontext.py): the
        # 32-hex trace-id names this request across router, replica, and
        # engine tiers (the flight-recorder rid), the 16-hex span-id names
        # THIS server hop. Empty on untraced callers (engine-direct tests,
        # non-chat endpoints) — the engine then self-mints.
        self.trace_id = trace_id
        self.span_id = span_id
        self._t0 = time.perf_counter()
        self.started_at = time.time()
        self._lock = threading.Lock()
        self.spans: list[Span] = []
        self.dropped_spans = 0
        self.meta: dict = {"mode": mode} if mode else {}
        self.ttft: float | None = None
        self.token_times: list[float] = []  # wire flush times, rel. seconds
        self.n_tokens = 0        # content flushes, NOT capped like the list
        # Worst gap between consecutive content flushes, tracked UNCAPPED
        # (the token_times list stops at MAX_TOKEN_TIMES — a stall past
        # the cap must still be visible to the SLO inter_token scorer).
        self.max_token_gap: float | None = None
        self._last_token_t: float | None = None
        self.n_flushes = 0
        self.status: int | None = None
        self.duration: float | None = None  # set by finish()

    # -- clocks --------------------------------------------------------------

    def now(self) -> float:
        """Seconds since this trace began (the span timebase)."""
        return time.perf_counter() - self._t0

    def rel(self, perf_t: float) -> float:
        """A ``time.perf_counter()`` stamp → this trace's timebase."""
        return perf_t - self._t0

    # -- spans ---------------------------------------------------------------

    def add_span(self, name: str, start: float, end: float | None = None,
                 **meta: Any) -> Span:
        """Record a span with trace-relative times (see :meth:`rel`).

        Completed traces are immutable: a timed-out request's still-running
        device loop keeps calling in for minutes after the trace was
        published to /debug/traces — those late spans are counted in
        ``dropped_spans``, never appended (the returned detached span keeps
        callers' ``span.end = ...`` stamping harmless)."""
        span = Span(name, start, end, meta or None)
        with self._lock:
            if self.duration is not None or len(self.spans) >= MAX_SPANS:
                self.dropped_spans += 1
            else:
                self.spans.append(span)
        return span

    def add_span_abs(self, name: str, start_perf: float, end_perf: float,
                     **meta: Any) -> Span:
        """Record a span from two ``time.perf_counter()`` stamps."""
        return self.add_span(name, self.rel(start_perf), self.rel(end_perf),
                             **meta)

    @contextlib.contextmanager
    def span(self, name: str, **meta: Any) -> Iterator[Span]:
        s = self.add_span(name, self.now(), **meta)
        try:
            yield s
        finally:
            s.end = self.now()

    # -- wire timing ---------------------------------------------------------

    def mark_flush(self, content: "bool | int") -> None:
        """One SSE write hit the wire; ``content`` counts the token-bearing
        frames it carried (role chunks and [DONE] don't set TTFT; a
        coalesced write ships several content frames in one flush — bools
        are accepted for the uncoalesced single-frame case)."""
        t = self.now()
        count = int(content)
        with self._lock:
            if self.duration is not None:
                return  # completed traces are immutable (see add_span)
            self.n_flushes += 1
            if count <= 0:
                return
            if self.ttft is None:
                self.ttft = t
                TTFT.observe(t)
            else:
                # Gap from the LAST content flush, tracked independently of
                # the capped token_times list — past the cap each gap must
                # still measure one flush, not the distance back to entry
                # MAX_TOKEN_TIMES. One observation per FLUSH: frames inside
                # a coalesced write arrived together, a zero gap per extra
                # frame would fake wire latency the client never saw.
                gap = t - self._last_token_t
                INTER_TOKEN.observe(gap)
                if self.max_token_gap is None or gap > self.max_token_gap:
                    self.max_token_gap = gap
            self._last_token_t = t
            self.n_tokens += count
            # All of a coalesced flush's tokens hit the wire at t.
            for _ in range(count):
                if len(self.token_times) >= MAX_TOKEN_TIMES:
                    break
                self.token_times.append(t)

    # -- lifecycle -----------------------------------------------------------

    def finish(self, status: int | None = None) -> None:
        """Close the trace: stamp status + total duration, observe the
        request-duration histogram, close any still-open spans (a client
        disconnect can abandon one mid-phase). Idempotent."""
        with self._lock:
            if self.duration is not None:
                return
            self.duration = self.now()
            if status is not None:
                self.status = status
            for s in self.spans:
                if s.end is None:
                    s.end = self.duration
        # Status-class label: a flood of fast-failing 4xxs must not read as
        # serving latency collapsing on a dashboard's unlabeled p50.
        klass = (f"{self.status // 100}xx" if self.status is not None
                 else "unknown")
        REQUEST_DURATION.observe(self.duration, status=klass)

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            spans = sorted(self.spans, key=lambda s: s.start)
            out = {
                "request_id": self.request_id,
                "started_at": self.started_at,
                "in_flight": self.duration is None,
                "status": self.status,
                "duration_ms": (None if self.duration is None
                                else round(self.duration * 1000, 3)),
                "ttft_ms": (None if self.ttft is None
                            else round(self.ttft * 1000, 3)),
                "tokens": self.n_tokens,
                "sse_flushes": self.n_flushes,
                "token_times_ms": [round(t * 1000, 3)
                                   for t in self.token_times],
                "spans": [s.to_dict() for s in spans],
                "dropped_spans": self.dropped_spans,
            }
            if self.trace_id:
                out["trace_id"] = self.trace_id
                out["span_id"] = self.span_id
            if self.meta:
                out["meta"] = dict(self.meta)
        return out

    def summary(self) -> dict:
        """The /debug/traces list row: the scalar fields only — built
        directly, NOT via to_dict(), so listing a full ring never
        materializes (and discards) thousands of span/timing dicts under
        live traces' locks."""
        with self._lock:
            return {
                "request_id": self.request_id,
                "started_at": self.started_at,
                "in_flight": self.duration is None,
                "status": self.status,
                "duration_ms": (None if self.duration is None
                                else round(self.duration * 1000, 3)),
                "ttft_ms": (None if self.ttft is None
                            else round(self.ttft * 1000, 3)),
                "tokens": self.n_tokens,
                "sse_flushes": self.n_flushes,
                "dropped_spans": self.dropped_spans,
                **({"trace_id": self.trace_id} if self.trace_id else {}),
                **({"meta": dict(self.meta)} if self.meta else {}),
            }

    # -- PhaseTimer compatibility -------------------------------------------

    @property
    def phases(self) -> dict[str, float]:
        """Accumulated seconds per span name (closed spans only)."""
        with self._lock:
            out: dict[str, float] = {}
            for s in self.spans:
                if s.end is not None:
                    out[s.name] = out.get(s.name, 0.0) + (s.end - s.start)
        return out

    phase = span  # with timer.phase("fanout"): ... (round-1 API)

    @property
    def total(self) -> float:
        return self.duration if self.duration is not None else self.now()

    def log(self, mode: str, **extra: Any) -> None:
        """One structured summary line per request (the round-1
        ``PhaseTimer.log`` extended with ttft/tokens/queue visibility)."""
        detail = " ".join(f"{k}={v}" for k, v in extra.items())
        phases = " ".join(f"{k}={v * 1000:.1f}ms"
                          for k, v in self.phases.items())
        wire = ""
        if self.ttft is not None:
            wire = f"ttft={self.ttft * 1000:.1f}ms tokens={self.n_tokens}"
        logger.info(
            "request %s mode=%s total=%.1fms %s %s %s",
            self.request_id, mode, self.total * 1000, phases, wire, detail,
        )


PhaseTimer = RequestTrace  # round-1 name; the API is a superset


class TraceStore:
    """In-flight traces plus a bounded ring of completed ones."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = int(os.environ.get("QUORUM_TPU_TRACE_CAPACITY", "256"))
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._inflight: dict[str, RequestTrace] = {}
        self._completed: deque[RequestTrace] = deque(maxlen=self.capacity)

    def start(self, trace: RequestTrace) -> RequestTrace:
        with self._lock:
            self._inflight[trace.request_id] = trace
        return trace

    def complete(self, trace: RequestTrace) -> None:
        with self._lock:
            self._inflight.pop(trace.request_id, None)
            self._completed.append(trace)

    def get(self, request_id: str) -> RequestTrace | None:
        with self._lock:
            t = self._inflight.get(request_id)
            if t is not None:
                return t
            for t in self._completed:
                if t.request_id == request_id:
                    return t
        return None

    def snapshot(self, limit: int | None = None) -> dict:
        """Summaries of every in-flight trace plus completed ones newest
        first — the whole ring by default (it is already bounded by
        ``capacity``); ``limit`` trims the listing further."""
        with self._lock:
            inflight = list(self._inflight.values())
            completed = list(self._completed)
        completed.reverse()  # newest first
        rows = inflight + completed
        if limit is not None:
            rows = rows[:limit]
        return {
            "capacity": self.capacity,
            "in_flight": len(inflight),
            "completed": len(completed),
            "traces": [t.summary() for t in rows],
        }

    def reset(self) -> None:
        with self._lock:
            self._inflight.clear()
            self._completed.clear()


TRACES = TraceStore()

_current_trace: contextvars.ContextVar[RequestTrace | None] = \
    contextvars.ContextVar("quorum_tpu_trace", default=None)


def current_trace() -> RequestTrace | None:
    """The trace of the request this task/thread is serving, if any."""
    return _current_trace.get()


@contextlib.contextmanager
def use_trace(trace: RequestTrace | None) -> Iterator[RequestTrace | None]:
    """Bind ``trace`` as the current trace for this context (None is a
    no-op bind, so callers can pass through an optional trace)."""
    token = _current_trace.set(trace)
    try:
        yield trace
    finally:
        _current_trace.reset(token)


@contextlib.contextmanager
def trace_span(trace: RequestTrace | None, name: str, **meta: Any):
    """``trace.span(...)`` tolerant of ``trace is None``."""
    if trace is None:
        yield None
        return
    with trace.span(name, **meta) as s:
        yield s


def finish_request_trace(trace: RequestTrace, status: int | None = None,
                         mode: str = "") -> None:
    """Request teardown: close the trace, move it to the completed ring,
    score its SLO class (when the server tagged one — telemetry/slo.py),
    and emit the one structured per-request summary line."""
    trace.finish(status=status)
    TRACES.complete(trace)
    if trace.meta.get("slo"):
        from quorum_tpu.telemetry.slo import SLO

        try:
            SLO.score_trace(trace)
        except Exception:
            logger.exception("SLO scoring failed for %s", trace.request_id)
    trace.log(mode or trace.meta.get("mode", ""), status=trace.status)


_profile_lock = threading.Lock()


@contextlib.contextmanager
def maybe_profile(request_id: str):
    """jax.profiler device trace for this request when QUORUM_TPU_PROFILE_DIR
    is set; no-op (and no jax import) otherwise.

    The jax profiler is process-global and cannot nest: when another request
    is already being traced, this one proceeds untraced — visibly: the skip
    ticks ``quorum_tpu_profile_skipped_total`` and records a
    ``profile-skipped`` flight-recorder event, so dropped profiles no longer
    vanish into a DEBUG line (ISSUE 12 satellite)."""
    profile_dir = os.environ.get("QUORUM_TPU_PROFILE_DIR", "")
    if not profile_dir:
        yield
        return
    if not _profile_lock.acquire(blocking=False):
        logger.debug("profiler busy — request %s runs untraced", request_id)
        PROFILE_SKIPPED.inc()
        RECORDER.record("profile-skipped", rid=request_id, loop="server")
        yield
        return
    try:
        import jax

        with jax.profiler.trace(os.path.join(profile_dir, request_id)):
            yield
    finally:
        _profile_lock.release()


class ProfilerBusy(RuntimeError):
    """The process-global jax profiler is already tracing (surface as 409)."""


def profile_process(seconds: float, profile_dir: str | None = None) -> str:
    """On-demand whole-process device profile (``POST /debug/profile``):
    run ``jax.profiler.trace`` over everything the process dispatches for
    ``seconds``, blocking the calling thread (the route runs it in an
    executor). Returns the trace directory.

    Single-flight behind the same lock as :func:`maybe_profile` — the jax
    profiler cannot nest — raising :class:`ProfilerBusy` instead of
    queueing: a profile of "the next N seconds, later" is not the profile
    the operator asked for."""
    if not _profile_lock.acquire(blocking=False):
        PROFILE_SKIPPED.inc()
        RECORDER.record("profile-skipped", rid="on-demand", loop="server")
        raise ProfilerBusy("jax profiler busy with another trace")
    try:
        import jax

        base = (profile_dir or os.environ.get("QUORUM_TPU_PROFILE_DIR", "")
                or os.path.join("profiles", "ondemand"))
        out = os.path.join(base, time.strftime("%Y%m%d-%H%M%S"))
        RECORDER.record("profile-start", rid="on-demand", loop="server",
                        seconds=seconds, dir=out)
        with jax.profiler.trace(out):
            time.sleep(max(0.0, float(seconds)))
        return out
    finally:
        _profile_lock.release()
