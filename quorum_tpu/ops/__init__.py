"""TPU-friendly primitive ops for the model runtime.

Everything here is shape-static, jit-traceable, and written so XLA can fuse
elementwise work into the surrounding matmuls (MXU) — see SURVEY.md §7.
"""

from quorum_tpu.ops.norms import layernorm, rmsnorm
from quorum_tpu.ops.rotary import apply_rope, rope_cos_sin
from quorum_tpu.ops.attention import attention, decode_attention
from quorum_tpu.ops.sampling import sample_token

__all__ = [
    "layernorm",
    "rmsnorm",
    "apply_rope",
    "rope_cos_sin",
    "attention",
    "decode_attention",
    "sample_token",
]
