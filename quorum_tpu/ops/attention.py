"""Causal attention with grouped-query (GQA) support, XLA-native reference path.

Layout: q [B, H, S, hd]; k/v [B, K, S_kv, hd] with H = K * G query groups.
GQA is expressed by reshaping q to [B, K, G, S, hd] and contracting against
the shared K/V heads — no materialized repeat_kv copies (which would burn HBM
bandwidth); the grouping lives in the einsum and XLA tiles it onto the MXU.

Softmax runs in float32 regardless of activation dtype. The Pallas
flash-attention kernel (quorum_tpu.ops.flash_attention) replaces the prefill
path on real TPUs; this module is the always-available fallback and the
numerical ground truth the kernel is tested against.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -2.0**30  # large-but-finite: keeps masked softmax NaN-free in bf16/f32


def _group_heads(q: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    b, h, s, d = q.shape
    return q.reshape(b, n_kv, h // n_kv, s, d)


def attention(
    q: jnp.ndarray,  # [B, H, S, hd]
    k: jnp.ndarray,  # [B, K, S_kv, hd]
    v: jnp.ndarray,  # [B, K, S_kv, hd]
    mask: jnp.ndarray | None = None,  # broadcastable to [B, 1, 1, S, S_kv], bool (True=keep)
) -> jnp.ndarray:
    """Full attention over the given K/V. Returns [B, H, S, hd]."""
    n_kv = k.shape[1]
    qg = _group_heads(q, n_kv)  # [B, K, G, S, hd]
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bkgsd,bktd->bkgst", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bkgst,bktd->bkgsd", probs.astype(v.dtype), v)
    b, k_, g, s, d = out.shape
    return out.reshape(b, k_ * g, s, d)


def causal_mask(s_q: int, s_kv: int, q_offset: jnp.ndarray | int = 0,
                window: int = 0) -> jnp.ndarray:
    """[1, 1, 1, s_q, s_kv] boolean causal mask; query i sits at absolute
    position q_offset + i. ``window`` > 0 adds sliding-window attention
    (mistral): key j visible to query at position p iff p-window < j <= p."""
    qi = jnp.arange(s_q)[:, None] + q_offset
    ki = jnp.arange(s_kv)[None, :]
    keep = ki <= qi
    if window and window > 0:
        keep = keep & (ki > qi - window)
    return keep[None, None, None, :, :]


def prefill_attention(q, k, v, lengths: jnp.ndarray | None = None,
                      window: int = 0) -> jnp.ndarray:
    """Causal self-attention over a [B, ·, S, hd] prompt block.

    ``lengths`` ([B]) masks out right-padding so batched prompts of unequal
    length share one compiled program (static shapes — SURVEY.md §7);
    ``window`` > 0 adds the sliding-window constraint.
    """
    mask = causal_mask(q.shape[2], k.shape[2], window=window)
    if lengths is not None:
        valid = (jnp.arange(k.shape[2])[None, :] < lengths[:, None])  # [B, S_kv]
        mask = mask & valid[:, None, None, None, :]
    return attention(q, k, v, mask)


def decode_attention(
    q: jnp.ndarray,  # [B, H, 1, hd]
    k_cache: jnp.ndarray,  # [B, K, max_seq, hd]
    v_cache: jnp.ndarray,
    length: jnp.ndarray,  # [B] or scalar: #valid cache entries (incl. current token)
    window: int = 0,
) -> jnp.ndarray:
    """One decode step against the KV cache (static max_seq, masked by
    length; ``window`` > 0 restricts to the last ``window`` positions)."""
    length = jnp.asarray(length)
    if length.ndim == 0:
        length = length[None]
    ki = jnp.arange(k_cache.shape[2])[None, :]
    valid = ki < length[:, None]  # [B, max_seq]
    if window and window > 0:
        valid = valid & (ki >= length[:, None] - window)
    mask = valid[:, None, None, None, :]
    return attention(q, k_cache, v_cache, mask)


def quantize_rows(x: jnp.ndarray, axis: int = -1) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization along ``axis``: returns ``(q8, scale)``
    with ``x ≈ q8 * scale`` (scale keeps the reduced dim, size 1).
    Deliberately the same amax/127 formulation — including the 1e-30
    all-zero-row floor — as models/quant.py's weight/activation quantizers
    (kept separate only because ops/ must not import models/); a change to
    the formulation belongs in both places. Used by the int8 KV cache's
    write path and its dynamic query/probability quantization."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-30) / 127.0
    q8 = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return q8, s


def decode_attention_q8(
    q: jnp.ndarray,        # [B, H, 1, hd] bf16/f32
    k8: jnp.ndarray,       # [B, K, T, hd] int8 cache
    k_scale: jnp.ndarray,  # [B, K, T] f32: k ≈ k8 * k_scale[..., None]
    v8: jnp.ndarray,       # [B, K, T, hd] int8 cache
    v_scale: jnp.ndarray,  # [B, K, T] f32
    length: jnp.ndarray,   # [B] or scalar
    window: int = 0,
) -> jnp.ndarray:
    """One decode step against an int8-quantized KV cache, with the
    contractions run NATIVELY in int8 (int8×int8→int32 on the MXU) — never
    dequantize-into-dot, which materializes a bf16 copy in HBM and made
    int8 *slower* than bf16 for weights (PERF.md §2, the measured-first
    rule this module inherits).

    The per-token scales factor cleanly out of both dots:
      q·kᵀ: k's scale indexes the OUTPUT position t → logits · ks[t].
      p·v:  v's scale indexes the CONTRACTION position t → fold vs[t] into
            the probabilities BEFORE quantizing them over t.
    q (one row per head) and p (one row per query) are dynamically
    quantized amax/127, like activations in models/quant.qeinsum."""
    b, h, s, d = q.shape
    n_kv = k8.shape[1]
    qg = _group_heads(q, n_kv)                        # [B, K, G, 1, hd]
    q8, qs = quantize_rows(qg, axis=-1)               # qs [B, K, G, 1, 1]
    logits_i = jnp.einsum(
        "bkgsd,bktd->bkgst", q8, k8, preferred_element_type=jnp.int32)
    scale = d ** -0.5
    logits = (logits_i.astype(jnp.float32) * qs
              * k_scale[:, :, None, None, :]) * scale  # [B, K, G, 1, T]
    length = jnp.asarray(length)
    if length.ndim == 0:
        length = length[None]
    ki = jnp.arange(k8.shape[2])[None, :]
    valid = ki < length[:, None]  # [B, T]
    if window and window > 0:
        valid = valid & (ki >= length[:, None] - window)
    logits = jnp.where(valid[:, None, None, None, :], logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    pv = probs * v_scale[:, :, None, None, :]          # fold v's scale in
    p8, ps = quantize_rows(pv, axis=-1)                # ps [B, K, G, 1, 1]
    out_i = jnp.einsum(
        "bkgst,bktd->bkgsd", p8, v8, preferred_element_type=jnp.int32)
    out = out_i.astype(jnp.float32) * ps               # [B, K, G, 1, hd]
    return out.reshape(b, h, s, d).astype(q.dtype)
