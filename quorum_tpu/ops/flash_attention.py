"""Pallas flash attention for the prefill path (TPU kernel).

Blockwise causal attention with online softmax — O(S) VMEM instead of
materializing the [S, S] score matrix in HBM, the standard memory-bandwidth
win for long-prompt prefill on TPU. Design per /opt/skills/guides/
pallas_guide.md:

  - grid = (batch, q_heads, q_blocks); each program owns one [BLOCK_Q, hd]
    query tile in VMEM and streams K/V tiles of the matching **KV head**
    (GQA is pure index mapping — head h reads kv head h//group — so no
    repeat_kv copies exist anywhere);
  - the KV loop trip count is the causal frontier ``ceil((iq+1)·BQ / BK)``:
    blocks strictly above the diagonal are never read from HBM at all;
  - online softmax carries (m, l, acc) in f32 through a ``fori_loop``; both
    matmuls run on the MXU with f32 accumulation;
  - right-padding is masked via the per-row ``lengths`` so bucketed batches
    share one compiled program (same contract as ops.attention).

`flash_prefill_attention` falls back to the XLA-native reference path
(quorum_tpu.ops.attention) off-TPU or for shapes the kernel doesn't cover;
tests run the kernel in interpreter mode on CPU against that reference.
The reference proxy has no attention at all (models are remote HTTP calls,
/root/reference/src/quorum/oai_proxy.py:182-192) — this kernel exists for the
tpu:// backends' performance, not behavioral parity.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_kernel(
    len_ref,   # SMEM [B, 1] — valid lengths, indexed by program_id(0)
    q_ref,     # VMEM [1, 1, BQ, hd]
    k_ref,     # VMEM [1, 1, S_kv, hd] (the matching KV head)
    v_ref,     # VMEM [1, 1, S_kv, hd]
    o_ref,     # VMEM [1, 1, BQ, hd]
    *,
    scale: float,
    block_k: int,
):
    iq = pl.program_id(2)
    bq = q_ref.shape[2]
    hd = q_ref.shape[3]
    length = len_ref[pl.program_id(0), 0]
    q_start = iq * bq

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
    row_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    # Causal frontier: KV columns ≥ (iq+1)·BQ can never be attended to by
    # this query tile — skip those blocks entirely (dynamic trip count).
    n_blocks = pl.cdiv((iq + 1) * bq, block_k)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        col_ids = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1
        )
        keep = (col_ids <= row_ids) & (col_ids < length)
        logits = jnp.where(keep, logits, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.dot(p, v_blk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, hd), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    # Fully-masked rows (right-padding past `length`) have l == 0; their
    # output is irrelevant downstream but must not be NaN.
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def _flash_call(
    q, k, v, lengths, *, block_q: int, block_k: int, interpret: bool
):
    b, h, s_q, hd = q.shape
    n_kv = k.shape[1]
    s_kv = k.shape[2]
    group = h // n_kv
    grid = (b, h, s_q // block_q)

    kernel = functools.partial(
        _flash_kernel, scale=hd**-0.5, block_k=block_k
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            # Scalars live 2D in SMEM (pallas guide); the whole [B, 1] array
            # is one block (Mosaic requires block dims divisible by (8, 128)
            # OR equal to the array dims — per-row (1, 1) blocks are not).
            pl.BlockSpec((b, 1), lambda ib, ih, iq: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, hd), lambda ib, ih, iq: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, s_kv, hd), lambda ib, ih, iq: (ib, ih // group, 0, 0)),
            pl.BlockSpec((1, 1, s_kv, hd), lambda ib, ih, iq: (ib, ih // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hd), lambda ib, ih, iq: (ib, ih, iq, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(lengths.reshape(b, 1), q, k, v)


def flash_supported(q_shape: tuple, k_shape: tuple, block_q: int, block_k: int) -> bool:
    b, h, s_q, hd = q_shape
    n_kv, s_kv = k_shape[1], k_shape[2]
    return (
        s_q % block_q == 0
        and s_kv % block_k == 0
        and s_q >= block_q
        and h % n_kv == 0
        and hd % 8 == 0
    )


def flash_enabled() -> bool:
    """Kernel path on TPU unless QUORUM_TPU_FLASH=0; off-TPU the XLA
    reference path runs (interpret mode is for tests only — too slow to
    serve with)."""
    flag = os.environ.get("QUORUM_TPU_FLASH", "1")
    return flag != "0" and jax.default_backend() == "tpu"


def flash_prefill_attention(
    q: jnp.ndarray,        # [B, H, S, hd]
    k: jnp.ndarray,        # [B, K, S_kv, hd]
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B] valid prompt lengths
    *,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal, length-masked prefill attention; flash kernel when supported,
    XLA-native reference otherwise. Returns [B, H, S, hd]."""
    # Clamp tiles to the sequence (buckets are powers of two, so they divide).
    block_q = min(block_q, q.shape[2])
    block_k = min(block_k, k.shape[2])
    if (interpret or flash_enabled()) and flash_supported(
        q.shape, k.shape, block_q, block_k
    ):
        return _flash_call(
            q, k, v, jnp.asarray(lengths, jnp.int32),
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
    from quorum_tpu.ops.attention import prefill_attention

    return prefill_attention(q, k, v, lengths)
