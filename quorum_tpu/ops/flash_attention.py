"""Pallas flash attention for the prefill path (TPU kernel).

Blockwise causal attention with online softmax — O(BLOCK) VMEM instead of
materializing the [S, S] score matrix, the standard memory-bandwidth win for
long-prompt prefill on TPU:

  - grid = (batch, q_heads, q_blocks, kv_blocks); the kv dimension is
    innermost, so each program sees one [BLOCK_Q, hd] query tile and one
    [BLOCK_K, hd] K/V tile in VMEM — K/V is *streamed tile by tile*, never
    resident whole, so VMEM stays bounded at any sequence length;
  - GQA is pure index mapping — query head h reads kv head h//group — so no
    repeat_kv copies exist anywhere;
  - online-softmax state (m, l, acc) lives in f32 VMEM scratch carried across
    the kv grid steps (TPU grids run sequentially per core, so scratch
    persists); it is initialized at the first kv block of each query tile and
    the normalized output is written at the last;
  - KV tiles entirely above the causal diagonal skip their compute via
    ``pl.when`` (their DMA still happens — BlockSpec fetches are
    unconditional; acceptable: attention compute, not HBM traffic, dominates
    at the tile sizes used);
  - right-padding is masked via per-row ``lengths`` so bucketed batches share
    one compiled program (same contract as quorum_tpu.ops.attention).

`flash_prefill_attention` falls back to the XLA-native reference path
(quorum_tpu.ops.attention) off-TPU or for unsupported shapes; tests run the
kernel in interpreter mode on CPU against that reference. The reference proxy
has no attention at all (models are remote HTTP calls,
/root/reference/src/quorum/oai_proxy.py:182-192) — this kernel exists for the
tpu:// backends' performance, not behavioral parity.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# 512-tiles measured ~22% faster than XLA's fused attention at 16k tokens on
# v5e (84.8 vs 108.8 ms; 128-tiles were on par) — grid overhead amortizes and
# the MXU gets deeper contractions. Tiles clamp to the sequence for short
# prompts.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _flash_kernel(
    len_ref,   # SMEM [B, 1] — valid lengths, indexed by program_id(0)
    q_ref,     # VMEM [1, 1, BQ, hd]
    k_ref,     # VMEM [1, 1, BK, hd] (tile of the matching KV head)
    v_ref,     # VMEM [1, 1, BK, hd]
    o_ref,     # VMEM [1, 1, BQ, hd]
    m_scr,     # VMEM [BQ, 1] f32 — running row max
    l_scr,     # VMEM [BQ, 1] f32 — running row normalizer
    acc_scr,   # VMEM [BQ, hd] f32 — running weighted-V accumulator
    *,
    scale: float,
    block_k: int,
    window: int,
):
    iq, ik = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)
    bq = q_ref.shape[2]
    length = len_ref[pl.program_id(0), 0]
    q_start = iq * bq
    k_start = ik * block_k

    @pl.when(ik == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr[:, :], NEG_INF)
        l_scr[:, :] = jnp.zeros_like(l_scr[:, :])
        acc_scr[:, :] = jnp.zeros_like(acc_scr[:, :])

    live = k_start <= q_start + bq - 1  # tile intersects the causal region
    if window > 0:
        # …and is not entirely left of every query's sliding window —
        # recovers SWA's O(S·W) compute (the DMA still streams; masked
        # tiles skip the matmuls/softmax, the dominant cost at these tile
        # sizes).
        live = live & (k_start + block_k > q_start - window + 1)

    @pl.when(live)
    def _update():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK]
        row_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        col_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        keep = (col_ids <= row_ids) & (col_ids < length)
        if window > 0:  # sliding-window attention (static; mistral)
            keep = keep & (col_ids > row_ids - window)
        logits = jnp.where(keep, logits, NEG_INF)

        m_prev = m_scr[:, :]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:, :] = m_new
        l_scr[:, :] = corr * l_scr[:, :] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:, :] = corr * acc_scr[:, :] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )

    @pl.when(ik == n_k - 1)
    def _finalize():
        # Fully-masked rows (all logits NEG_INF with m == NEG_INF) accumulate
        # p = exp(0) = 1 per column, so they produce a finite mean-of-V —
        # garbage but NaN-free, and never read downstream (right-padding).
        out = acc_scr[:, :] / jnp.maximum(l_scr[:, :], 1e-30)
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret", "window")
)
def _flash_call(
    q, k, v, lengths, *, block_q: int, block_k: int, interpret: bool,
    window: int = 0,
):
    b, h, s_q, hd = q.shape
    n_kv = k.shape[1]
    s_kv = k.shape[2]
    group = h // n_kv
    grid = (b, h, s_q // block_q, s_kv // block_k)

    kernel = functools.partial(_flash_kernel, scale=hd**-0.5, block_k=block_k,
                               window=window)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            # Scalars live 2D in SMEM; the whole [B, 1] array is one block
            # (Mosaic wants block dims divisible by (8, 128) OR equal to the
            # array dims).
            pl.BlockSpec((b, 1), lambda ib, ih, iq, ik: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, hd),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_k, hd),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
            pl.BlockSpec((1, 1, block_k, hd),
                         lambda ib, ih, iq, ik: (ib, ih // group, ik, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hd), lambda ib, ih, iq, ik: (ib, ih, iq, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, hd), jnp.float32),
        ],
        interpret=interpret,
    )(lengths.reshape(b, 1), q, k, v)


def flash_supported(q_shape: tuple, k_shape: tuple, block_q: int, block_k: int) -> bool:
    b, h, s_q, hd = q_shape
    n_kv, s_kv = k_shape[1], k_shape[2]
    return (
        s_q % block_q == 0
        and s_kv % block_k == 0
        and s_q >= block_q
        and h % n_kv == 0
        and hd % 8 == 0
    )


def flash_enabled() -> bool:
    """Kernel path on TPU unless QUORUM_TPU_FLASH=0; off-TPU the XLA
    reference path runs (interpret mode is for tests only — too slow to
    serve with)."""
    flag = os.environ.get("QUORUM_TPU_FLASH", "1")
    return flag != "0" and jax.default_backend() == "tpu"


def flash_prefill_attention(
    q: jnp.ndarray,        # [B, H, S, hd]
    k: jnp.ndarray,        # [B, K, S_kv, hd]
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B] valid prompt lengths
    *,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
    window: int = 0,
) -> jnp.ndarray:
    """Causal, length-masked prefill attention (``window`` > 0 adds the
    sliding-window constraint); flash kernel when supported, XLA-native
    reference otherwise. Returns [B, H, S, hd]."""
    # Clamp tiles to the sequence (buckets are powers of two, so they divide).
    block_q = min(block_q, q.shape[2])
    block_k = min(block_k, k.shape[2])
    if (interpret or flash_enabled()) and flash_supported(
        q.shape, k.shape, block_q, block_k
    ):
        return _flash_call(
            q, k, v, jnp.asarray(lengths, jnp.int32),
            block_q=block_q, block_k=block_k, interpret=interpret,
            window=window,
        )
    from quorum_tpu.ops.attention import prefill_attention

    return prefill_attention(q, k, v, lengths, window=window)
