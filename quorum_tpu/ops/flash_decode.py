"""Pallas decode attention over the slot KV cache (TPU kernel).

The engine's decode step attends each co-batched row against
``cache[:, :, :history]`` where ``history`` is one power-of-two bucket ≥ the
LONGEST active row (models/transformer.decode_step). That bucketing already
removed the full-``max_seq`` scan (PERF.md §2), but every row still streams
the whole shared bucket: co-batch a 4k-context chat with a 100-token one and
the short row pays the long row's cache traffic. Decode is HBM-bandwidth-
bound, so those wasted bytes are wasted time.

This kernel makes cache reads PER-ROW exact:

  - grid = (batch, kv_heads, kv_tiles) with the per-row valid lengths as a
    scalar-prefetch argument, so the K/V BlockSpec index maps can clamp the
    tile index to each row's own last live tile. Pallas's pipeline skips the
    DMA when consecutive grid steps map a block to the same index — tiles
    past a row's length are never fetched from HBM, giving per-row early
    exit without data-dependent grid shapes;
  - compute for those clamped (repeated) tiles is skipped via ``pl.when``;
  - all G = H/K query heads of one KV head process together in one program
    ([G, hd] × [hd, BLOCK_K] contractions — tiny M dim, irrelevant: decode
    is bandwidth-bound, the MXU is idle either way);
  - online softmax (m, l, acc) in f32 VMEM scratch across kv tiles, exactly
    the flash_attention recipe (TPU grids run sequentially per core).

Functional contract: identical to ops.attention.decode_attention (the
masked-dense reference path) — pinned by tests/test_flash_decode.py in
interpret mode on CPU. Off by default (measured-first policy, PERF.md §5):
``QUORUM_TPU_FLASH_DECODE=1`` enables it on TPU; the win case is skewed
co-batched context lengths, and the first on-chip session should measure
before promoting the default. No reference equivalent: the reference proxy
has no attention at all (/root/reference/src/quorum/oai_proxy.py:182-192).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Small default tile: decode histories start at the 128 bucket, and the
# per-row DMA skip gets finer-grained with smaller tiles. 256×128×2B×2 (k+v)
# = 128 KiB of VMEM traffic per step — far below the ~16 MiB budget.
DEFAULT_BLOCK_K = 256


def _decode_kernel(
    len_ref,   # SMEM [B] scalar-prefetch — valid cache entries per row
    q_ref,     # VMEM [1, 1, G, hd]
    k_ref,     # VMEM [1, 1, BK, hd] (tile of this row's KV head)
    v_ref,     # VMEM [1, 1, BK, hd]
    o_ref,     # VMEM [1, 1, G, hd]
    m_scr,     # VMEM [G, 1] f32 — running row max
    l_scr,     # VMEM [G, 1] f32 — running row normalizer
    acc_scr,   # VMEM [G, hd] f32 — running weighted-V accumulator
    *,
    scale: float,
    block_k: int,
    window: int,
):
    ib, it = pl.program_id(0), pl.program_id(2)
    n_t = pl.num_programs(2)
    length = len_ref[ib]
    k_start = it * block_k

    @pl.when(it == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr[:, :], NEG_INF)
        l_scr[:, :] = jnp.zeros_like(l_scr[:, :])
        acc_scr[:, :] = jnp.zeros_like(acc_scr[:, :])

    live = k_start < length  # tile holds live cache entries for THIS row
    if window > 0:
        # …within this row's sliding window (queries sit at length-1; keys
        # ≥ length-window are visible).
        live = live & (k_start + block_k > length - window)

    @pl.when(live)
    def _update():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale      # [G, hd]
        k_blk = k_ref[0, 0, :, :].astype(jnp.float32)          # [BK, hd]
        v_blk = v_ref[0, 0, :, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, BK]
        g = q.shape[0]
        col_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (g, block_k), 1)
        keep = col_ids < length
        if window > 0:  # sliding-window attention (static; mistral)
            keep = keep & (col_ids >= length - window)
        logits = jnp.where(keep, logits, NEG_INF)

        m_prev = m_scr[:, :]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:, :] = m_new
        l_scr[:, :] = corr * l_scr[:, :] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:, :] = corr * acc_scr[:, :] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )

    @pl.when(it == n_t - 1)
    def _finalize():
        # length ≥ 1 always (the row holds at least the current token), so
        # l > 0 for live rows; the floor only guards dead padding rows.
        out = acc_scr[:, :] / jnp.maximum(l_scr[:, :], 1e-30)
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret", "window"))
def _decode_call(q, k_cache, v_cache, lengths, *, block_k: int,
                 interpret: bool, window: int = 0):
    b, h, _, hd = q.shape
    n_kv, t = k_cache.shape[1], k_cache.shape[2]
    group = h // n_kv
    n_tiles = t // block_k
    qg = q.reshape(b, n_kv, group, hd)

    def last_live_tile(ib, lens):
        # Last tile holding live entries for row ib; lengths ≥ 1 always.
        return (lens[ib] - 1) // block_k

    def first_live_tile(ib, lens):
        # With a sliding window, tiles entirely below length-window hold
        # nothing visible — clamp from below too, so their DMAs are also
        # skipped (repeated index → no copy).
        if window <= 0:
            return 0
        return jnp.maximum(lens[ib] - window, 0) // block_k

    def kv_index(ib, ik, it, lens):
        return (ib, ik,
                jnp.clip(it, first_live_tile(ib, lens),
                         last_live_tile(ib, lens)), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_kv, n_tiles),
        in_specs=[
            pl.BlockSpec((1, 1, group, hd),
                         lambda ib, ik, it, lens: (ib, ik, 0, 0)),
            # Clamp the tile index into the row's live range: repeated
            # indices on clamped grid steps skip the HBM→VMEM copy entirely
            # (compute for them is skipped by pl.when in the kernel).
            pl.BlockSpec((1, 1, block_k, hd), kv_index),
            pl.BlockSpec((1, 1, block_k, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, group, hd),
                               lambda ib, ik, it, lens: (ib, ik, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=hd**-0.5, block_k=block_k,
                          window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, k_cache, v_cache)
    return out.reshape(b, h, 1, hd)


def flash_decode_supported(q_shape: tuple, k_shape: tuple, block_k: int) -> bool:
    b, h, s_q, hd = q_shape
    n_kv, t = k_shape[1], k_shape[2]
    return (
        s_q == 1
        and h % n_kv == 0
        and t % block_k == 0
        and t >= block_k
        and hd % 8 == 0
    )


def flash_decode_mode() -> str:
    """'' (off — the default), 'tpu' (QUORUM_TPU_FLASH_DECODE=1 on a real
    TPU), or 'interpret' (=interpret: the kernel anywhere via the Pallas
    interpreter — engine-level CPU tests only, far too slow to serve with).
    Off by default: the masked-dense path stays until the kernel is
    measured on real silicon (PERF.md §5's measured-first policy).

    Read at TRACE time: the engine caches its jitted decode programs, so
    flipping the env var inside a live process gives a mix of old and new
    programs. A/B runs must use separate processes (the bench's phase
    subprocesses already do)."""
    flag = os.environ.get("QUORUM_TPU_FLASH_DECODE", "0")
    if flag == "1" and jax.default_backend() == "tpu":
        return "tpu"
    if flag == "interpret":
        return "interpret"
    return ""


def flash_decode_enabled() -> bool:
    return bool(flash_decode_mode())


def parse_flash_decode(raw: str) -> str:
    """Validate a ``flash_decode=`` config value → "0" | "1" | "interpret".

    Strict at config time (a typo must not silently mean "off"): accepts
    the boolean spellings plus the Pallas-interpreter mode used by CPU
    engine tests."""
    val = str(raw).strip().lower()
    if val in ("0", "false", "no", "off", ""):
        return "0"
    if val in ("1", "true", "yes", "on"):
        return "1"
    if val == "interpret":
        return "interpret"
    raise ValueError(
        f"invalid flash_decode={raw!r} (use 0/1, true/false, yes/no, or "
        "interpret)")


def resolve_flash_decode(knob: str | None) -> str:
    """Effective flash-decode mode for ONE engine: '' (masked-dense),
    'tpu', or 'interpret'.

    Precedence: the ``QUORUM_TPU_FLASH_DECODE`` env var, when set, wins
    over the per-backend ``flash_decode=`` URL knob — the process-wide
    override the on-chip A/B scripts flip (they must beat a config file
    they don't control); otherwise the knob drives it, so two backends in
    one process can run the §5 flash A/B against each other. ``knob`` is
    None/'' when the URL never set it (falls back to the env gate's
    default-off). Resolved ONCE at engine construction — programs are
    cached per engine, so a mid-life flip could never take effect anyway
    (the same trace-time caveat as :func:`flash_decode_mode`)."""
    env = os.environ.get("QUORUM_TPU_FLASH_DECODE")
    if env is not None:
        # The env value takes the same spellings the URL knob does — an
        # operator's FLASH_DECODE=on must not silently measure the
        # masked-dense path in the kernel arm of an A/B. Unparseable
        # values are a LOUD off (never a crash at engine construction:
        # one typo'd env var must not brick every engine in the process).
        try:
            val = parse_flash_decode(env)
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "ignoring invalid QUORUM_TPU_FLASH_DECODE=%r "
                "(use 0/1 or interpret) — flash decode forced OFF", env)
            val = "0"
    else:
        val = knob or "0"
    if val == "1":
        return "tpu" if jax.default_backend() == "tpu" else ""
    if val == "interpret":
        return "interpret"
    return ""


def flash_decode_attention(
    q: jnp.ndarray,        # [B, H, 1, hd]
    k_cache: jnp.ndarray,  # [B, K, T, hd]
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,  # [B] or scalar: #valid cache entries (incl. current)
    *,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
    window: int = 0,
) -> jnp.ndarray:
    """Per-row-exact decode attention; Pallas kernel when supported, the
    masked-dense reference (ops.attention.decode_attention) otherwise."""
    lengths = jnp.asarray(lengths)
    if lengths.ndim == 0:
        lengths = jnp.broadcast_to(lengths[None], (q.shape[0],))
    block_k = min(block_k, k_cache.shape[2])
    if (interpret or flash_decode_enabled()) and flash_decode_supported(
        q.shape, k_cache.shape, block_k
    ):
        return _decode_call(q, k_cache, v_cache, lengths,
                            block_k=block_k, interpret=interpret,
                            window=window)
    from quorum_tpu.ops.attention import decode_attention

    return decode_attention(q, k_cache, v_cache, lengths, window=window)
