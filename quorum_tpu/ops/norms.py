"""Normalization layers. Computed in float32, cast back to the input dtype —
the standard mixed-precision discipline for bf16 activations on TPU."""

from __future__ import annotations

import jax.numpy as jnp


def rmsnorm(x: jnp.ndarray, w: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm (Llama/Mistral family)."""
    xf = x.astype(jnp.float32)
    scale = jnp.reciprocal(jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps))
    return (xf * scale).astype(x.dtype) * w


def layernorm(
    x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray | None, eps: float = 1e-5
) -> jnp.ndarray:
    """LayerNorm (GPT-2 family)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = ((xf - mu) * jnp.reciprocal(jnp.sqrt(var + eps))).astype(x.dtype) * w
    return y + b if b is not None else y
