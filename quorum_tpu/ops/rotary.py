"""Rotary position embeddings (RoPE), precomputed-table style.

The cos/sin tables are computed once per model (static max_seq) and gathered
by position inside jit — no trig in the decode hot loop, and positions are
data (not shapes), so one compiled program serves every request length.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(
    max_seq: int, head_dim: int, theta: float = 10000.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Tables of shape [max_seq, head_dim//2] in float32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,  # [..., seq, head_dim]
    cos: jnp.ndarray,  # [max_seq, head_dim//2]
    sin: jnp.ndarray,
    positions: jnp.ndarray,  # [seq] absolute positions
) -> jnp.ndarray:
    """Rotate pairs (x[..., :d/2], x[..., d/2:]) — the Llama/NeoX convention."""
    c = cos[positions][None, None, :, :]  # [1, 1, seq, d/2]
    s = sin[positions][None, None, :, :]
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate([xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1)
    return out.astype(x.dtype)
