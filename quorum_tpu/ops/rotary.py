"""Rotary position embeddings (RoPE), precomputed-table style.

The cos/sin tables are computed once per model (static max_seq) and gathered
by position inside jit — no trig in the decode hot loop, and positions are
data (not shapes), so one compiled program serves every request length.
"""

from __future__ import annotations

import jax.numpy as jnp


def _base_inv_freq(head_dim: int, theta: float) -> jnp.ndarray:
    return 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))


def scaled_rope_inv_freq(
    head_dim: int,
    theta: float,
    factor: float,
    low_freq_factor: float,
    high_freq_factor: float,
    original_max_seq: int,
) -> jnp.ndarray:
    """Llama-3.1 frequency scaling (the published 3.1/3.2 recipe).

    Banded by wavelength against the ORIGINAL training context:
    wavelengths longer than ``original_max_seq / low_freq_factor`` divide
    their frequency by ``factor`` (the pure long-range stretch), those
    shorter than ``original_max_seq / high_freq_factor`` are untouched
    (local syntax must not smear), and the band between interpolates
    linearly in ``original_max_seq / wavelength``. Pinned bit-for-bit
    against transformers' rope_scaling={"rope_type": "llama3"} in
    tests/test_hf_loader.py."""
    inv_freq = _base_inv_freq(head_dim, theta)
    wavelen = 2.0 * jnp.pi / inv_freq
    low_wavelen = original_max_seq / low_freq_factor
    high_wavelen = original_max_seq / high_freq_factor
    smooth = (original_max_seq / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    mid = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    out = jnp.where(wavelen > low_wavelen, inv_freq / factor, mid)
    return jnp.where(wavelen < high_wavelen, inv_freq, out)


def rope_cos_sin(
    max_seq: int, head_dim: int, theta: float = 10000.0,
    inv_freq: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Tables of shape [max_seq, head_dim//2] in float32."""
    if inv_freq is None:
        inv_freq = _base_inv_freq(head_dim, theta)
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def rope_cos_sin_for(spec) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Spec-driven tables: plain RoPE, or llama3-scaled frequencies when
    the spec carries ``rope_scaling="llama3"``."""
    inv_freq = None
    if getattr(spec, "rope_scaling", "") == "llama3":
        inv_freq = scaled_rope_inv_freq(
            spec.head_dim, spec.rope_theta, spec.rope_scaling_factor,
            spec.rope_low_freq_factor, spec.rope_high_freq_factor,
            spec.rope_original_max_seq)
    return rope_cos_sin(spec.max_seq, spec.head_dim, spec.rope_theta,
                        inv_freq=inv_freq)


def apply_rope(
    x: jnp.ndarray,  # [..., seq, head_dim]
    cos: jnp.ndarray,  # [max_seq, head_dim//2]
    sin: jnp.ndarray,
    positions: jnp.ndarray,  # [seq] absolute positions
) -> jnp.ndarray:
    """Rotate pairs (x[..., :d/2], x[..., d/2:]) — the Llama/NeoX convention."""
    c = cos[positions][None, None, :, :]  # [1, 1, seq, d/2]
    s = sin[positions][None, None, :, :]
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate([xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1)
    return out.astype(x.dtype)
