"""On-device token sampling: greedy, temperature, top-k, top-p.

All branches are trace-time-static (the sampler config is Python), so each
configuration compiles to one fixed XLA program — no data-dependent control
flow in the decode loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplerConfig:
    temperature: float = 1.0
    top_k: int = 0        # 0 = disabled
    top_p: float = 1.0    # 1.0 = disabled

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def sample_token(
    logits: jnp.ndarray,  # [B, V] float
    key: jax.Array,
    cfg: SamplerConfig,
) -> jnp.ndarray:
    """Returns [B] int32 token ids."""
    logits = logits.astype(jnp.float32)
    if cfg.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits / cfg.temperature

    if cfg.top_k > 0 and cfg.top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, cfg.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always >= 1 token)
        keep_sorted = cum - probs < cfg.top_p
        kth = jnp.sum(keep_sorted, axis=-1, keepdims=True)  # #kept per row
        cutoff = jnp.take_along_axis(sorted_logits, kth - 1, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
