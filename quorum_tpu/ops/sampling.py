"""On-device token sampling: greedy, temperature, top-k, top-p.

Two entry points:

- :func:`sample_token` — sampler knobs are trace-time-static Python (one
  compiled program per config). Used by single-stream callers and tests.
- :func:`sample_token_rows` — sampler knobs are per-row *arrays*, so one
  compiled program serves every (temperature, top_p, top_k) combination.
  This is what the continuous-batching engine uses: requests with different
  sampler settings share one batched decode program instead of one program
  per config (the round-1 design needed an LRU cache of compiled programs
  keyed by SamplerConfig — VERDICT.md weakness 4).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplerConfig:
    temperature: float = 1.0
    top_k: int = 0        # 0 = disabled
    top_p: float = 1.0    # 1.0 = disabled

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def sample_token(
    logits: jnp.ndarray,  # [B, V] float
    key: jax.Array,
    cfg: SamplerConfig,
) -> jnp.ndarray:
    """Returns [B] int32 token ids."""
    logits = logits.astype(jnp.float32)
    if cfg.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits / cfg.temperature

    if cfg.top_k > 0 and cfg.top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, cfg.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always >= 1 token)
        keep_sorted = cum - probs < cfg.top_p
        kth = jnp.sum(keep_sorted, axis=-1, keepdims=True)  # #kept per row
        cutoff = jnp.take_along_axis(sorted_logits, kth - 1, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def apply_token_mask(logits: jnp.ndarray, allow: jnp.ndarray) -> jnp.ndarray:
    """Grammar/constraint masking: disallowed entries drop to −inf BEFORE
    the sampler, so the existing cutoff machinery (temperature, top-k,
    top-p — all downstream of the mask) composes unchanged. −inf survives
    ``jax.random.categorical``'s Gumbel-argmax, which is what makes masking
    a would-be-sampled-anyway token a strict no-op under greedy and pure
    temperature sampling: the restricted argmax equals the unrestricted
    one whenever the unrestricted winner is allowed (the
    constrained-vs-unconstrained determinism pin in
    tests/test_constrained_decoding.py). With top-k/top-p active the
    cutoffs are computed over the MASKED distribution, so near-threshold
    samples can differ from the unconstrained run even when the winner
    itself was never masked."""
    return jnp.where(allow, logits, -jnp.inf)


def sample_token_rows(
    logits: jnp.ndarray,       # [S, V] float
    keys: jnp.ndarray,         # [S, 2] uint32 — one PRNG key per row
    temperature: jnp.ndarray,  # [S] float; <= 0 → greedy for that row
    top_p: jnp.ndarray,        # [S] float; 1.0 → disabled
    top_k: jnp.ndarray,        # [S] int32; 0 → disabled
) -> jnp.ndarray:
    """Per-row sampling with per-row knobs; returns [S] int32 token ids.

    Row-independent by construction (each row's output depends only on that
    row's logits/key/knobs), which is what lets the engine co-batch unrelated
    requests in one decode program without cross-request interference.

    Matches :func:`sample_token` semantics per row: temperature scaling, then
    the top-k and top-p cutoffs compose (a token survives only if it passes
    both), always keeping >= 1 token.
    """
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    def apply_cutoffs(scaled):
        # One descending sort serves both cutoffs (temp > 0 preserves order).
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]

        k = jnp.where(top_k > 0, top_k, vocab)
        kth = jnp.take_along_axis(
            sorted_desc, jnp.clip(k - 1, 0, vocab - 1)[:, None], axis=-1
        )  # [S,1] — smallest logit still inside the row's top-k

        # top-p composes AFTER top-k (sample_token parity): the cumulative
        # mass is taken over the top-k-filtered, renormalized distribution —
        # positions beyond k are masked out before the softmax.
        col = jnp.arange(vocab)[None, :]
        in_k = col < k[:, None]
        probs = jax.nn.softmax(jnp.where(in_k, sorted_desc, -jnp.inf), axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p[:, None]      # smallest prefix with mass >= top_p
        nkeep = jnp.sum(keep, axis=-1, keepdims=True)  # always >= 1
        cutoff_p = jnp.take_along_axis(sorted_desc, nkeep - 1, axis=-1)
        return jnp.where(scaled < jnp.maximum(kth, cutoff_p), -jnp.inf, scaled)

    # The cutoffs need an O(V log V) sort per step; skip it at runtime when no
    # row restricts the distribution (the default request). lax.cond compiles
    # both branches but executes one.
    any_cutoff = jnp.any((top_p < 1.0) | (top_k > 0))
    masked = jax.lax.cond(any_cutoff, apply_cutoffs, lambda s: s, scaled)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
