"""Device-mesh and sharding utilities (TPU-first, GSPMD).

The reference has no distributed-ML parallelism at all (SURVEY.md §2.9 —
its only "parallelism" is asyncio request fan-out). This package is the
TPU-native substrate the new framework's model runtime is built on: a named
:class:`jax.sharding.Mesh` over the slice, PartitionSpec rules for model
parameters / activations / KV caches, and helpers shared by the engine,
the ring-attention path, and the multi-chip dry run.
"""

from quorum_tpu.parallel.mesh import MeshConfig, best_mesh, make_mesh
from quorum_tpu.parallel.pipeline import (
    make_pp_train_step,
    pipeline_forward_logits,
    pp_train_init,
    shard_pytree_pp,
)
from quorum_tpu.parallel.sharding import (
    logical_to_sharding,
    param_partition_specs,
    shard_pytree,
)

__all__ = [
    "MeshConfig",
    "best_mesh",
    "make_mesh",
    "make_pp_train_step",
    "pipeline_forward_logits",
    "pp_train_init",
    "shard_pytree_pp",
    "logical_to_sharding",
    "param_partition_specs",
    "shard_pytree",
]
