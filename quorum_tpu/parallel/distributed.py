"""Multi-host bootstrap and hybrid ICI/DCN meshes.

The reference's only "communication backend" is per-request HTTP
(/root/reference/src/quorum/oai_proxy.py:185-192 — no NCCL/MPI of any
kind, SURVEY.md §5.8). The TPU-native equivalent is jax's distributed
runtime: every host in a multi-host deployment runs the SAME program,
``jax.distributed.initialize`` wires the hosts into one JAX process group,
and XLA collectives ride

  - **ICI** within a slice (the high-bandwidth inter-chip interconnect), and
  - **DCN** between slices/hosts (the data-center network).

The scaling-book recipe for laying a mesh over that topology: put the
*highest-traffic* axes (tp all-reduces every layer; sp rings every
attention; pp hands off every microbatch tick) on ICI, and keep only the
*lowest-traffic* axis — dp, which communicates once per training step
(gradient all-reduce) and never during serving forward passes — on DCN.
:func:`hybrid_mesh` encodes exactly that split via
``mesh_utils.create_hybrid_device_mesh``.

Single-host processes (tests, the bench chip, CPU meshes) take the same
code path: ``initialize()`` no-ops and ``hybrid_mesh`` degrades to the
plain :func:`quorum_tpu.parallel.mesh.make_mesh` layout, so nothing in the
engine/trainer branches on deployment size.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh

from quorum_tpu.parallel.mesh import MESH_AXES, MeshConfig, make_mesh

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host JAX process group; returns True if distributed.

    Arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID``); on TPU pods jax can also infer all three from the
    TPU metadata, so calling this with no arguments is correct there.
    Single-process runs (no coordinator configured, one process) skip
    initialization entirely — the same binary serves a laptop CPU, one
    bench chip, and a pod.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and (num_processes or 1) <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "joined distributed runtime: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )
    return True


def hybrid_mesh(cfg: MeshConfig, *, dcn_dp: int = 1) -> Mesh:
    """A ``(dp, pp, sp, tp)`` mesh whose dp axis spans slices over DCN.

    ``cfg`` describes the per-slice (ICI) shape; ``dcn_dp`` multiplies the
    dp axis across slices — the global mesh is
    ``(dcn_dp · cfg.dp, cfg.pp, cfg.sp, cfg.tp)`` with device placement
    chosen so every pp/sp/tp neighbor hop stays on ICI and only the
    once-per-step dp gradient all-reduce crosses DCN.

    With one slice (``dcn_dp == 1``) this is exactly ``make_mesh(cfg)`` —
    tests and the single-chip bench exercise the same call.
    """
    if dcn_dp <= 1:
        return make_mesh(cfg)
    from jax.experimental import mesh_utils

    ici_shape = (cfg.dp, cfg.pp, cfg.sp, cfg.tp)
    dcn_shape = (dcn_dp, 1, 1, 1)
    devices = jax.devices()
    # Multi-slice TPU deployments granulate DCN by slice; runs whose
    # devices all share one slice id (CPU multi-process runs —
    # tests/distributed_worker.py — and single-slice multi-host pods)
    # granulate by process instead, the only boundary DCN traffic crosses
    # there.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    by_process = len(slice_ids) <= 1
    devices = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=devices,
        process_is_granule=by_process)
    return Mesh(devices, MESH_AXES)


def local_data_shard(global_batch: int) -> tuple[int, int]:
    """(start, size) of this host's slice of a dp-sharded global batch —
    the per-host input feeding convention for multi-host training: each
    process feeds only the rows its local devices own, and
    ``jax.make_array_from_process_local_data`` assembles the global array.
    """
    n = jax.process_count()
    i = jax.process_index()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} must divide over "
                         f"{n} processes")
    per = global_batch // n
    return i * per, per


def assemble_global_batch(local_tokens: np.ndarray, mesh: Mesh,
                          global_batch: int):
    """Build the global [B, T] token array from this host's local rows.

    On one process this is a plain device_put; on many, each host
    contributes its :func:`local_data_shard` rows and jax assembles the
    sharded global array without any host ever materializing all of it.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quorum_tpu.parallel.mesh import AXIS_DP

    sharding = NamedSharding(mesh, P(AXIS_DP, None))
    if jax.process_count() == 1:
        return jax.device_put(local_tokens, sharding)
    t = local_tokens.shape[-1]
    return jax.make_array_from_process_local_data(
        sharding, local_tokens, (global_batch, t))
