"""Mesh construction over the TPU slice.

Axis conventions (used consistently across the framework):

  ``dp``  data parallel      — batch dimension of activations and KV caches
  ``pp``  pipeline parallel  — transformer layer *stages*; microbatches flow
                               stage→stage over ppermute (parallel/pipeline.py)
  ``sp``  sequence parallel  — sequence blocks for ring attention / long context
  ``tp``  tensor parallel    — attention heads, MLP hidden, vocab shards;
                               doubles as ``ep`` (expert parallel) for MoE —
                               experts are sharded over the same axis so dense
                               and MoE layers share one mesh.

All communication happens as XLA collectives over these axes (psum /
all_gather / ppermute inserted by GSPMD or written explicitly in shard_map),
riding ICI within a slice. There is no NCCL/MPI analog to port — the
reference's only communication backend is HTTP (SURVEY.md §5.8).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_TP = "tp"
MESH_AXES = (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP)


@dataclass(frozen=True)
class MeshConfig:
    """Requested mesh shape. Any axis left at 1 is effectively disabled."""

    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a ``(dp, pp, sp, tp)`` mesh over ``devices`` (default: all local).

    The tp axis is placed innermost so tensor-parallel collectives (the
    highest-traffic ones: all-reduce after attention/MLP) map onto
    nearest-neighbour ICI links; pp sits next-outermost so stage hand-offs
    (one activation ppermute per microbatch tick) are also neighbor hops.
    """
    if devices is None:
        devices = jax.devices()
    cfg = cfg or MeshConfig(tp=len(devices))
    if cfg.n_devices > len(devices):
        raise ValueError(
            f"mesh {cfg} needs {cfg.n_devices} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[: cfg.n_devices]).reshape(
        cfg.dp, cfg.pp, cfg.sp, cfg.tp)
    return Mesh(arr, MESH_AXES)


def best_mesh(n_devices: int | None = None, *, want_dp: bool = False) -> Mesh:
    """A sensible default mesh: all devices on tp, or split dp×tp if asked."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if want_dp and n % 2 == 0 and n > 1:
        return make_mesh(MeshConfig(dp=2, tp=n // 2), devices)
    return make_mesh(MeshConfig(tp=n), devices)


def single_device_mesh() -> Mesh:
    """A 1×1×1 mesh — lets all code paths be mesh-agnostic."""
    return make_mesh(MeshConfig(), jax.devices()[:1])


def parse_disagg(raw: str) -> tuple[int, int]:
    """``"4+4"`` → ``(n_prefill, n_decode)``. Strict: the knob is structural
    (it decides device-group placement for the engine's lifetime), so a typo
    must fail at config time, not silently colocate. URL query parsing
    decodes ``+`` to a space, so a bare space separator is accepted too
    (``disagg=4+4`` in config.yaml arrives here as ``"4 4"``)."""
    import re

    m = re.fullmatch(r"(\d+)[+ ](\d+)", str(raw).strip())
    if not m:
        raise ValueError(
            f"invalid disagg={raw!r} (expected P+D device counts, e.g. 4+4)")
    n_p, n_d = int(m.group(1)), int(m.group(2))
    if n_p < 1 or n_d < 1:
        raise ValueError(
            f"invalid disagg={raw!r} (both device groups need >= 1 device)")
    return n_p, n_d


def group_mesh_configs(n_prefill: int, n_decode: int, *,
                       tp: int | None = None, sp: int = 1,
                       pp: int = 1) -> tuple[MeshConfig, MeshConfig]:
    """Per-group mesh shapes for ``disagg=P+D`` with intra-group sharding.

    ``tp`` shards weights/KV within BOTH groups (``None`` = each group's
    whole device count, the pre-sharding default); ``sp`` scales the
    PREFILL group with sequence parallelism (100k+-token admission
    contexts, staging KV sharded over sequence); ``pp`` stages the DECODE
    group's layers into a pipeline (models bigger than one group's HBM —
    parallel/pipeline.py's staged decode). Every invalid combination
    fails here with the reason, at config time — never at first
    dispatch."""
    if sp < 1 or pp < 1 or (tp is not None and tp < 1):
        raise ValueError(
            f"invalid sharding knobs tp={tp} sp={sp} pp={pp} beside "
            "disagg= (each must be >= 1)")
    tp_p = tp if tp is not None else n_prefill // sp
    tp_d = tp if tp is not None else n_decode // pp
    if tp_p < 1 or sp * tp_p != n_prefill:
        raise ValueError(
            f"prefill group of disagg={n_prefill}+{n_decode} does not "
            f"factor as sp={sp} x tp={tp_p} ({sp * max(tp_p, 0)} != "
            f"{n_prefill} devices) — pick tp/sp whose product is the "
            "prefill group size, or resize the group")
    if tp_d < 1 or pp * tp_d != n_decode:
        raise ValueError(
            f"decode group of disagg={n_prefill}+{n_decode} does not "
            f"factor as pp={pp} x tp={tp_d} ({pp * max(tp_d, 0)} != "
            f"{n_decode} devices) — pick tp/pp whose product is the "
            "decode group size, or resize the group")
    if pp > 1 and tp_d > 1:
        raise ValueError(
            f"pipeline-staged decode runs tp=1 within each stage "
            f"(pp={pp} with tp={tp_d} in the decode group): make pp the "
            "whole decode group, or drop one knob")
    return MeshConfig(sp=sp, tp=tp_p), MeshConfig(pp=pp, tp=tp_d)


def disagg_meshes(n_prefill: int, n_decode: int, devices=None, *,
                  tp: int | None = None, sp: int = 1,
                  pp: int = 1) -> tuple[Mesh, Mesh]:
    """Two DISJOINT device-group meshes for disaggregated serving
    (``tpu://…&disagg=P+D``): the first ``n_prefill`` devices become the
    prefill group's mesh, the next ``n_decode`` the decode group's.

    MPMD-style placement ("Scaling Deep Learning Training with MPMD Pipeline
    Parallelism", PAPERS.md): admission prefill programs compile and run on
    the first mesh, the decode ring on the second, and a completed
    admission's KV prefix hands off device→device between them
    (quorum_tpu/cache/kv_transfer.py). With no sharding knobs tp is the
    only axis per group (the pre-sharding default — byte-for-byte the old
    layout); ``tp=``/``sp=``/``pp=`` pick the intra-group factorization
    (:func:`group_mesh_configs`). Either way the highest-traffic
    collectives stay nearest-neighbour inside each group, and the
    inter-group hop is the explicit KV handoff — never a GSPMD collective
    spanning both (the handoff reshards on the fly when the two groups'
    layouts differ)."""
    if devices is None:
        devices = jax.devices()
    need = n_prefill + n_decode
    if need > len(devices):
        raise ValueError(
            f"disagg={n_prefill}+{n_decode} needs {need} devices, have "
            f"{len(devices)}")
    pre_cfg, dec_cfg = group_mesh_configs(n_prefill, n_decode,
                                          tp=tp, sp=sp, pp=pp)
    prefill = make_mesh(pre_cfg, devices[:n_prefill])
    decode = make_mesh(dec_cfg, devices[n_prefill:n_prefill + n_decode])
    return prefill, decode
