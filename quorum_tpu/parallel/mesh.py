"""Mesh construction over the TPU slice.

Axis conventions (used consistently across the framework):

  ``dp``  data parallel      — batch dimension of activations and KV caches
  ``pp``  pipeline parallel  — transformer layer *stages*; microbatches flow
                               stage→stage over ppermute (parallel/pipeline.py)
  ``sp``  sequence parallel  — sequence blocks for ring attention / long context
  ``tp``  tensor parallel    — attention heads, MLP hidden, vocab shards;
                               doubles as ``ep`` (expert parallel) for MoE —
                               experts are sharded over the same axis so dense
                               and MoE layers share one mesh.

All communication happens as XLA collectives over these axes (psum /
all_gather / ppermute inserted by GSPMD or written explicitly in shard_map),
riding ICI within a slice. There is no NCCL/MPI analog to port — the
reference's only communication backend is HTTP (SURVEY.md §5.8).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_TP = "tp"
MESH_AXES = (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP)


@dataclass(frozen=True)
class MeshConfig:
    """Requested mesh shape. Any axis left at 1 is effectively disabled."""

    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a ``(dp, pp, sp, tp)`` mesh over ``devices`` (default: all local).

    The tp axis is placed innermost so tensor-parallel collectives (the
    highest-traffic ones: all-reduce after attention/MLP) map onto
    nearest-neighbour ICI links; pp sits next-outermost so stage hand-offs
    (one activation ppermute per microbatch tick) are also neighbor hops.
    """
    if devices is None:
        devices = jax.devices()
    cfg = cfg or MeshConfig(tp=len(devices))
    if cfg.n_devices > len(devices):
        raise ValueError(
            f"mesh {cfg} needs {cfg.n_devices} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[: cfg.n_devices]).reshape(
        cfg.dp, cfg.pp, cfg.sp, cfg.tp)
    return Mesh(arr, MESH_AXES)


def best_mesh(n_devices: int | None = None, *, want_dp: bool = False) -> Mesh:
    """A sensible default mesh: all devices on tp, or split dp×tp if asked."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if want_dp and n % 2 == 0 and n > 1:
        return make_mesh(MeshConfig(dp=2, tp=n // 2), devices)
    return make_mesh(MeshConfig(tp=n), devices)


def single_device_mesh() -> Mesh:
    """A 1×1×1 mesh — lets all code paths be mesh-agnostic."""
    return make_mesh(MeshConfig(), jax.devices()[:1])
