"""Pipeline parallelism: transformer layer stages over the ``pp`` mesh axis.

GPipe-style schedule, TPU-first (scaling-book pipelining recipe): the stacked
layer pytree ``params["blocks"]`` (leading ``n_layers`` dim) is sharded over
``pp`` — each device holds a contiguous stage of ``L/pp`` layers — and the
batch is split into M microbatches that flow stage→stage. Everything runs
under one ``shard_map`` over the mesh:

  tick t ∈ [0, M + pp - 1):   stage s runs its layers on microbatch (t - s),
                              then hands its activation to stage s+1 with ONE
                              ``lax.ppermute`` (nearest-neighbor ICI hop —
                              the pp axis is placed next to tp in the mesh).

The bubble is the standard (pp-1)/(M+pp-1) fraction — idle ticks still
execute (static shapes; their writes are masked), which is what keeps the
whole schedule a single compiled XLA program: no host round-trips between
ticks, no per-stage dispatch.

Embedding, final norm, and unembed run *outside* the shard_map under plain
GSPMD (they are not layer-staged). Composes with dp (microbatches shard
their batch dim over dp); tp/sp compose at the GSPMD level only, so the
manual pipeline path requires tp == sp == 1 — the mesh for pp training is
``dp × pp`` (checked at call time).

Everything is differentiable (``ppermute``/``scan``/``psum`` have transpose
rules), so :func:`make_pp_train_step` trains through the pipeline.

The reference has no distributed execution of any kind (its only
"communication backend" is HTTP, SURVEY.md §5.8); this is north-star
multi-chip functionality, driver-validated via ``dryrun_multichip``.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from quorum_tpu.models.model_config import ModelSpec
from quorum_tpu.ops.attention import attention, causal_mask
from quorum_tpu.ops.rotary import rope_cos_sin_for
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP

# NOTE: quorum_tpu.models.transformer is imported lazily inside functions —
# the transformer itself imports quorum_tpu.parallel (ring attention), so a
# module-level import here would be circular.


def _pvary(tree, axes: tuple[str, ...]):
    """Mark freshly-created arrays device-varying over ``axes`` (shard_map's
    vma typing requires scan carries to match their varying outputs)."""
    if not axes:
        return tree
    try:
        return jax.lax.pcast(tree, axes, to="varying")
    except (AttributeError, TypeError):
        try:  # older jax spells it pvary
            return jax.lax.pvary(tree, axes)
        except AttributeError:
            return tree  # pre-vma jax (< 0.5): no manual-varying typing


def _check_pp_mesh(mesh: Mesh, spec: ModelSpec) -> int:
    npp = mesh.shape[AXIS_PP]
    if mesh.shape[AXIS_TP] != 1 or mesh.shape[AXIS_SP] != 1:
        raise ValueError(
            "the pipelined path composes with dp only; build the mesh as "
            f"dp×pp (got tp={mesh.shape[AXIS_TP]}, sp={mesh.shape[AXIS_SP]})"
        )
    if spec.n_layers % npp:
        raise ValueError(
            f"n_layers={spec.n_layers} must divide into pp={npp} stages"
        )
    return npp


def pp_param_shardings(mesh: Mesh, params) -> dict:
    """Placement for the pipelined model: every stacked-layer leaf sharded
    over ``pp`` on its leading (layers) axis; everything else replicated
    (embeddings/norms live outside the staged region)."""
    staged = NamedSharding(mesh, P(AXIS_PP))
    rep = NamedSharding(mesh, P())
    out = dict(params)
    out["blocks"] = jax.tree.map(lambda _: staged, params["blocks"])
    for k, v in params.items():
        if k != "blocks":
            out[k] = jax.tree.map(lambda _: rep, v)
    return out


def shard_pytree_pp(mesh: Mesh, params) -> dict:
    """Place params for pipelining (see :func:`pp_param_shardings`)."""
    return jax.tree.map(jax.device_put, dict(params),
                        pp_param_shardings(mesh, params))


def _pipeline_blocks(blocks, xs, spec: ModelSpec, mesh: Mesh, remat: bool):
    """Run the staged layers over microbatches ``xs`` [M, mb, T, D]."""
    npp = mesh.shape[AXIS_PP]
    n_micro, mb, t_len, _ = xs.shape
    baxis = AXIS_DP if mb % mesh.shape[AXIS_DP] == 0 else None
    positions = jnp.arange(t_len)
    mask = causal_mask(t_len, t_len, window=spec.sliding_window)

    from quorum_tpu.models.transformer import _layer_body

    def local(blocks_local, xs_local):
        s = lax.axis_index(AXIS_PP)
        cos, sin = rope_cos_sin_for(spec)

        def stage(x):
            def body(c, blk):
                return _layer_body(
                    c, blk, spec, positions, cos, sin,
                    lambda q, k, v: attention(q, k, v, mask),
                )
            if remat:
                body = jax.checkpoint(body)
            x, _ = lax.scan(body, x, blocks_local)
            return x

        fwd_perm = [(i, i + 1) for i in range(npp - 1)]

        def tick(carry, t):
            cur, outbuf = carry
            # stage 0 injects microbatch t from the input queue; every other
            # stage consumes what its predecessor ppermuted last tick.
            m_in = jnp.clip(t, 0, n_micro - 1)
            x_in = lax.dynamic_index_in_dim(xs_local, m_in, 0, keepdims=False)
            y = stage(jnp.where(s == 0, x_in, cur))
            # the last stage commits microbatch t-(pp-1) to the output buffer
            m_out = t - (npp - 1)
            valid = (m_out >= 0) & (s == npp - 1)
            m_c = jnp.clip(m_out, 0, n_micro - 1)
            old = lax.dynamic_index_in_dim(outbuf, m_c, 0, keepdims=True)
            outbuf = lax.dynamic_update_slice_in_dim(
                outbuf, jnp.where(valid, y[None], old), m_c, axis=0)
            nxt = lax.ppermute(y, AXIS_PP, fwd_perm) if npp > 1 else y
            return (nxt, outbuf), None

        # derive the carries from xs_local (inherits its dp vma), then mark
        # them pp-varying — the tick body makes them so (axis_index/ppermute)
        cur0 = _pvary(xs_local[0] * 0, (AXIS_PP,))
        out0 = _pvary(xs_local * 0, (AXIS_PP,))
        (_, outbuf), _ = lax.scan(
            tick, (cur0, out0), jnp.arange(n_micro + npp - 1))
        # only the last stage wrote anything; psum replicates it back to all
        # pp ranks (every other stage's buffer is still zero)
        return lax.psum(outbuf, AXIS_PP)

    xspec = P(None, baxis, None, None)
    blocks_specs = jax.tree.map(lambda _: P(AXIS_PP), blocks)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(blocks_specs, xspec),
        out_specs=xspec,
    )
    return fn(blocks, xs)


def pipeline_forward_logits(
    params,
    spec: ModelSpec,
    tokens: jnp.ndarray,  # [B, T], B divisible by n_micro (× dp ideally)
    mesh: Mesh,
    n_micro: int = 2,
    remat: bool = False,
) -> jnp.ndarray:
    """Full-sequence logits [B, T, V], layers pipelined over ``pp``.

    Semantics match :func:`quorum_tpu.models.transformer.forward_logits`
    exactly (same math, different schedule) — pinned by
    tests/test_pipeline.py.
    """
    from quorum_tpu.models.transformer import _embed, _final_norm, _unembed

    npp = _check_pp_mesh(mesh, spec)
    b, t_len = tokens.shape
    if b % n_micro:
        raise ValueError(f"batch {b} must divide into {n_micro} microbatches")
    del npp
    positions = jnp.arange(t_len)
    x = _embed(params, spec, tokens, positions)  # [B, T, D]
    mb = b // n_micro
    xs = x.reshape(n_micro, mb, t_len, -1)
    out = _pipeline_blocks(params["blocks"], xs, spec, mesh, remat)
    x = out.reshape(b, t_len, -1)
    x = _final_norm(params, spec, x)
    return _unembed(params, spec, x)


def pp_loss_fn(params, spec: ModelSpec, tokens, mesh, n_micro: int,
               remat: bool = True):
    """Mean next-token cross-entropy through the pipeline (same contract as
    quorum_tpu.training.trainer.loss_fn)."""
    logits = pipeline_forward_logits(
        params, spec, tokens[:, :-1], mesh, n_micro, remat=remat)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    m = (targets != 0).astype(jnp.float32)
    return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)


def pp_train_init(spec: ModelSpec, mesh: Mesh, *, seed: int = 0,
                  optimizer=None):
    """Sharded TrainState with blocks staged over pp (optimizer moments
    inherit the layout through jit output propagation)."""
    from quorum_tpu.models.init import init_params
    from quorum_tpu.training.trainer import TrainState, make_optimizer

    opt = optimizer or make_optimizer()
    params = shard_pytree_pp(mesh, init_params(spec, seed))
    opt_state = jax.jit(opt.init)(params)
    rep = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda x: x if isinstance(x.sharding, NamedSharding)
        else jax.device_put(x, rep),
        opt_state,
    )
    return TrainState(params=params, opt_state=opt_state,
                      step=jax.device_put(jnp.zeros((), jnp.int32), rep))


def make_pp_train_step(spec: ModelSpec, mesh: Mesh, *, n_micro: int = 2,
                       optimizer=None, remat: bool = True):
    """One pipelined SGD step: ``step(state, tokens [B, T]) → (state, loss)``.

    Gradients flow backward through the same pipeline (ppermute/scan/psum
    transpose to the reverse schedule); AdamW updates run where each stage's
    weights live.
    """
    import optax  # lazy: serving installs don't ship the training deps

    from quorum_tpu.training.trainer import TrainState, make_optimizer

    opt = optimizer or make_optimizer()
    token_sharding = NamedSharding(mesh, P(AXIS_DP, None))

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens: jnp.ndarray):
        loss, grads = jax.value_and_grad(pp_loss_fn)(
            state.params, spec, tokens, mesh, n_micro, remat)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    def run(state, tokens):
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), token_sharding)
        return step(state, tokens)

    return run
