"""Pipeline parallelism: transformer layer stages over the ``pp`` mesh axis.

GPipe-style schedule, TPU-first (scaling-book pipelining recipe): the stacked
layer pytree ``params["blocks"]`` (leading ``n_layers`` dim) is sharded over
``pp`` — each device holds a contiguous stage of ``L/pp`` layers — and the
batch is split into M microbatches that flow stage→stage. Everything runs
under one ``shard_map`` over the mesh:

  tick t ∈ [0, M + pp - 1):   stage s runs its layers on microbatch (t - s),
                              then hands its activation to stage s+1 with ONE
                              ``lax.ppermute`` (nearest-neighbor ICI hop —
                              the pp axis is placed next to tp in the mesh).

The bubble is the standard (pp-1)/(M+pp-1) fraction — idle ticks still
execute (static shapes; their writes are masked), which is what keeps the
whole schedule a single compiled XLA program: no host round-trips between
ticks, no per-stage dispatch.

Embedding, final norm, and unembed run *outside* the shard_map under plain
GSPMD (they are not layer-staged). Composes with dp (microbatches shard
their batch dim over dp); tp/sp compose at the GSPMD level only, so the
manual pipeline path requires tp == sp == 1 — the mesh for pp training is
``dp × pp`` (checked at call time).

Everything is differentiable (``ppermute``/``scan``/``psum`` have transpose
rules), so :func:`make_pp_train_step` trains through the pipeline.

The reference has no distributed execution of any kind (its only
"communication backend" is HTTP, SURVEY.md §5.8); this is north-star
multi-chip functionality, driver-validated via ``dryrun_multichip``.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from quorum_tpu.models.model_config import ModelSpec
from quorum_tpu.ops.attention import attention, causal_mask
from quorum_tpu.ops.rotary import rope_cos_sin_for
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP

# NOTE: quorum_tpu.models.transformer is imported lazily inside functions —
# the transformer itself imports quorum_tpu.parallel (ring attention), so a
# module-level import here would be circular.


def _pvary(tree, axes: tuple[str, ...]):
    """Mark freshly-created arrays device-varying over ``axes`` (shard_map's
    vma typing requires scan carries to match their varying outputs)."""
    if not axes:
        return tree
    try:
        return jax.lax.pcast(tree, axes, to="varying")
    except (AttributeError, TypeError):
        try:  # older jax spells it pvary
            return jax.lax.pvary(tree, axes)
        except AttributeError:
            return tree  # pre-vma jax (< 0.5): no manual-varying typing


def _check_pp_mesh(mesh: Mesh, spec: ModelSpec) -> int:
    npp = mesh.shape[AXIS_PP]
    if mesh.shape[AXIS_TP] != 1 or mesh.shape[AXIS_SP] != 1:
        raise ValueError(
            "the pipelined path composes with dp only; build the mesh as "
            f"dp×pp (got tp={mesh.shape[AXIS_TP]}, sp={mesh.shape[AXIS_SP]})"
        )
    if spec.n_layers % npp:
        raise ValueError(
            f"n_layers={spec.n_layers} must divide into pp={npp} stages"
        )
    return npp


def pp_param_shardings(mesh: Mesh, params) -> dict:
    """Placement for the pipelined model: every stacked-layer leaf sharded
    over ``pp`` on its leading (layers) axis; everything else replicated
    (embeddings/norms live outside the staged region)."""
    staged = NamedSharding(mesh, P(AXIS_PP))
    rep = NamedSharding(mesh, P())
    out = dict(params)
    out["blocks"] = jax.tree.map(lambda _: staged, params["blocks"])
    for k, v in params.items():
        if k != "blocks":
            out[k] = jax.tree.map(lambda _: rep, v)
    return out


def shard_pytree_pp(mesh: Mesh, params) -> dict:
    """Place params for pipelining (see :func:`pp_param_shardings`)."""
    return jax.tree.map(jax.device_put, dict(params),
                        pp_param_shardings(mesh, params))


def _pipeline_blocks(blocks, xs, spec: ModelSpec, mesh: Mesh, remat: bool):
    """Run the staged layers over microbatches ``xs`` [M, mb, T, D]."""
    npp = mesh.shape[AXIS_PP]
    n_micro, mb, t_len, _ = xs.shape
    baxis = AXIS_DP if mb % mesh.shape[AXIS_DP] == 0 else None
    positions = jnp.arange(t_len)
    mask = causal_mask(t_len, t_len, window=spec.sliding_window)

    from quorum_tpu.models.transformer import _layer_body

    def local(blocks_local, xs_local):
        s = lax.axis_index(AXIS_PP)
        cos, sin = rope_cos_sin_for(spec)

        def stage(x):
            def body(c, blk):
                return _layer_body(
                    c, blk, spec, positions, cos, sin,
                    lambda q, k, v: attention(q, k, v, mask),
                )
            if remat:
                body = jax.checkpoint(body)
            x, _ = lax.scan(body, x, blocks_local)
            return x

        fwd_perm = [(i, i + 1) for i in range(npp - 1)]

        def tick(carry, t):
            cur, outbuf = carry
            # stage 0 injects microbatch t from the input queue; every other
            # stage consumes what its predecessor ppermuted last tick.
            m_in = jnp.clip(t, 0, n_micro - 1)
            x_in = lax.dynamic_index_in_dim(xs_local, m_in, 0, keepdims=False)
            y = stage(jnp.where(s == 0, x_in, cur))
            # the last stage commits microbatch t-(pp-1) to the output buffer
            m_out = t - (npp - 1)
            valid = (m_out >= 0) & (s == npp - 1)
            m_c = jnp.clip(m_out, 0, n_micro - 1)
            old = lax.dynamic_index_in_dim(outbuf, m_c, 0, keepdims=True)
            outbuf = lax.dynamic_update_slice_in_dim(
                outbuf, jnp.where(valid, y[None], old), m_c, axis=0)
            nxt = lax.ppermute(y, AXIS_PP, fwd_perm) if npp > 1 else y
            return (nxt, outbuf), None

        # derive the carries from xs_local (inherits its dp vma), then mark
        # them pp-varying — the tick body makes them so (axis_index/ppermute)
        cur0 = _pvary(xs_local[0] * 0, (AXIS_PP,))
        out0 = _pvary(xs_local * 0, (AXIS_PP,))
        (_, outbuf), _ = lax.scan(
            tick, (cur0, out0), jnp.arange(n_micro + npp - 1))
        # only the last stage wrote anything; psum replicates it back to all
        # pp ranks (every other stage's buffer is still zero)
        return lax.psum(outbuf, AXIS_PP)

    xspec = P(None, baxis, None, None)
    blocks_specs = jax.tree.map(lambda _: P(AXIS_PP), blocks)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(blocks_specs, xspec),
        out_specs=xspec,
    )
    return fn(blocks, xs)


def pipeline_forward_logits(
    params,
    spec: ModelSpec,
    tokens: jnp.ndarray,  # [B, T], B divisible by n_micro (× dp ideally)
    mesh: Mesh,
    n_micro: int = 2,
    remat: bool = False,
) -> jnp.ndarray:
    """Full-sequence logits [B, T, V], layers pipelined over ``pp``.

    Semantics match :func:`quorum_tpu.models.transformer.forward_logits`
    exactly (same math, different schedule) — pinned by
    tests/test_pipeline.py.
    """
    from quorum_tpu.models.transformer import _embed, _final_norm, _unembed

    npp = _check_pp_mesh(mesh, spec)
    b, t_len = tokens.shape
    if b % n_micro:
        raise ValueError(f"batch {b} must divide into {n_micro} microbatches")
    del npp
    positions = jnp.arange(t_len)
    x = _embed(params, spec, tokens, positions)  # [B, T, D]
    mb = b // n_micro
    xs = x.reshape(n_micro, mb, t_len, -1)
    out = _pipeline_blocks(params["blocks"], xs, spec, mesh, remat)
    x = out.reshape(b, t_len, -1)
    x = _final_norm(params, spec, x)
    return _unembed(params, spec, x)


# ---- pipeline-staged DECODE (serving) --------------------------------------
#
# The inference twin of the training pipeline above, for models whose
# weight+KV footprint exceeds one device group's HBM (ROADMAP item 4; MPMD
# placement per PAPERS.md, the stage-pipelined decode shape Jupiter applies
# at the edge). Stage s holds layers [s·L/pp, (s+1)·L/pp) AND those layers'
# KV-cache shard (kv_cache_sharding shards the layer axis over pp); the
# microbatch slots are DECODE ROWS: the engine's slot batch splits into pp
# contiguous row groups, and at tick t stage s advances row group
# (t−s) mod pp by one layer-stage — one ring ppermute per tick carries the
# activation forward (stage s→s+1) and the freshly sampled token back
# (last stage→0), so in steady state every stage is busy every tick and
# each group emits one token per pp ticks. Everything — n_steps token
# steps, sampling with the engine's full sampler closure (penalties,
# logit bias, constrained-DFA masks, logprobs), on-device finish
# accounting — runs inside ONE compiled program; under decode_loop=C the
# tick scan nests inside the fused megachunk scan, so the staged schedule
# keeps the decode_pipeline=K × decode_loop=C dispatch ring semantics of
# the unstaged engine bit for bit (tests/test_pp_decode.py pins pp=2
# token-for-token against a single-device engine).
#
# Per-layer math is decode_step's exactly: each stage runs
# transformer.decode_step_blocks on its layer shard, embed/unembed run at
# stage 0 / the last stage on replicated non-block params. Known
# inefficiency, documented: every stage traces the unembed+sample block,
# but a lax.cond on the stage index skips its execution off the last
# stage. Also documented: the last stage runs sample_fn at FULL batch
# width once per tick (the group's logits scattered into a zero [B,vocab]
# lane) — pp× the unstaged path's sampler FLOPs, with all but the tick's
# sg rows merged away. The full-width call is what keeps the engine's
# row-indexed sampler closures (RNG key rows, DFA state rows, bias rows)
# bit-identical to decode_chunk's without re-deriving a group-local
# indexing contract; slot batches are small next to the layer stack, so
# the win of a sliced sampler has not yet justified that second contract.


def _row_groups(mesh: Mesh, batch: int) -> int:
    npp = mesh.shape[AXIS_PP]
    if batch % npp:
        raise ValueError(
            f"staged decode needs the slot batch ({batch}) divisible by "
            f"pp={npp} (the row groups are the pipeline's microbatches)")
    return npp


def staged_decode_chunk(
    params,
    spec: ModelSpec,
    mesh: Mesh,
    n_steps: int,
    token,    # [B] current token ids
    lengths,  # [B]
    live,     # [B] bool
    budget,   # [B] int32
    eos,      # [B] int32
    cache_k,  # [L, B, K, max_seq, hd] — layer axis sharded over pp
    cache_v,
    sample_fn,
    sample_carry,
    history: int | None = None,
    flash: str | None = None,
):
    """One pipeline-staged decode chunk; same contract as
    :func:`quorum_tpu.models.transformer.decode_chunk` (tokens, per-row
    ``n_valid``, on-device finish accounting, ``sample_fn`` carry/aux
    threading), scheduled as a row-group pipeline over the mesh's ``pp``
    axis. ``sample_fn`` may close over replicated engine state (sampler
    knobs, bias rows, grammar tables) — closures enter shard_map as
    replicated values."""
    from quorum_tpu.models.transformer import (
        decode_step_blocks,
        decode_token_embed,
        _final_norm,
        _unembed,
    )

    npp = _row_groups(mesh, token.shape[0])
    b = token.shape[0]
    sg = b // npp
    n_ticks = npp * n_steps + npp - 1
    ring = [(i, (i + 1) % npp) for i in range(npp)]
    blocks = params["blocks"]
    other = {k: v for k, v in params.items() if k != "blocks"}

    # Aux output shapes (logprob triples, masked-token counts, …) come from
    # one abstract evaluation of the engine's sampler — trace-free, exactly
    # the decode_loop skip-branch pattern.
    aux_shapes = jax.eval_shape(
        lambda lg, lv, c: sample_fn(lg, lv, c)[2],
        jax.ShapeDtypeStruct((b, spec.vocab_size), jnp.float32),
        jax.ShapeDtypeStruct((b,), jnp.bool_),
        sample_carry,
    )

    def state0():
        # The ONE source of truth for the scan's state pytree: local()'s
        # st0 initialization and the shard_map out_specs (via eval_shape)
        # both build from here, so they can never drift apart.
        return dict(
            live=live, budget=budget, lens=lengths, carry=sample_carry,
            toks=jnp.zeros((n_steps, b), jnp.int32),
            valid=jnp.zeros((n_steps, b), bool),
            aux=tuple(jnp.zeros((n_steps,) + tuple(sh.shape), sh.dtype)
                      for sh in jax.tree.leaves(aux_shapes)),
        )

    def local(blocks_local, ck_l, cv_l):
        s = lax.axis_index(AXIS_PP)
        is_first_stage = s == 0
        is_last = s == npp - 1

        def embed_group(tok_g, lens_g):
            # ``other`` = the replicated non-block params (embed/unembed/
            # final-norm live outside the staged region, like the training
            # pipeline's).
            return decode_token_embed(other, spec, tok_g, lens_g)

        def slice_rows(arr, rows0, width=None):
            w = sg if width is None else width
            starts = (rows0,) + (0,) * (arr.ndim - 1)
            sizes = (w,) + arr.shape[1:]
            return lax.dynamic_slice(arr, starts, sizes)

        def scat_rows(arr, val, rows0, gate):
            starts = (rows0,) + (0,) * (arr.ndim - 1)
            old = lax.dynamic_slice(arr, starts, val.shape)
            return lax.dynamic_update_slice(
                arr, jnp.where(gate, val, old), starts)

        def tick(carry, t):
            bundle, st, ck_l, cv_l = carry
            rel = t - s
            valid = (rel >= 0) & (rel < npp * n_steps)
            g = rel % npp          # row group this stage advances this tick
            k = rel // npp         # that group's token index in the chunk
            rows0 = g * sg

            # Stage 0 input: the group's chunk-entry state for its first
            # token, else the token+state the LAST stage sampled last tick
            # (the ring half of the ppermute). Later stages consume their
            # predecessor's activation with the row state forwarded along.
            first = rel < npp
            init_tok = slice_rows(token, rows0)
            init_live = slice_rows(live, rows0)
            init_lens = slice_rows(lengths, rows0)
            in_tok = jnp.where(first, init_tok, bundle["tok"])
            in_live = jnp.where(first, init_live, bundle["live"])
            in_lens = jnp.where(first, init_lens, bundle["lens"])
            cur_tok = jnp.where(is_first_stage, in_tok, bundle["tok"])
            cur_live = jnp.where(is_first_stage, in_live, bundle["live"])
            cur_lens = jnp.where(is_first_stage, in_lens, bundle["lens"])
            # Dead rows run the static batch lane at position 0, exactly as
            # decode_chunk's `pos = where(lv, lens, 0)` does — keeps the
            # two schedules' forwards (and their aux records) bit-equal.
            pos_rows = jnp.where(cur_live, cur_lens, 0)
            x0 = embed_group(in_tok, pos_rows)
            x_in = jnp.where(is_first_stage, x0, bundle["x"])

            # This stage's layers on its cache slab for the group's rows;
            # fill/drain ticks run the same static-shape program with
            # writes masked off (the training pipeline's idle-tick rule).
            allow = cur_live & valid
            ck_rows = jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, rows0, sg, axis=1),
                ck_l)
            cv_rows = jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, rows0, sg, axis=1),
                cv_l)
            y, ck_rows, cv_rows = decode_step_blocks(
                blocks_local, spec, x_in, pos_rows, ck_rows, cv_rows,
                write_mask=allow, history=history, flash=flash)
            ck_l = jax.tree.map(
                lambda a, r: lax.dynamic_update_slice_in_dim(
                    a, r, rows0, axis=1), ck_l, ck_rows)
            cv_l = jax.tree.map(
                lambda a, r: lax.dynamic_update_slice_in_dim(
                    a, r, rows0, axis=1), cv_l, cv_rows)

            kc = jnp.clip(k, 0, n_steps - 1)

            def do_sample(op):
                st, y = op
                h = _final_norm(other, spec, y)
                logits_g = _unembed(other, spec, h[:, 0, :]).astype(
                    jnp.float32)
                logits_full = lax.dynamic_update_slice(
                    jnp.zeros((b, spec.vocab_size), jnp.float32),
                    logits_g, (rows0, jnp.int32(0)))
                lv_full = scat_rows(jnp.zeros((b,), bool), allow, rows0,
                                    True)
                nxt_full, new_carry, aux = sample_fn(
                    logits_full, lv_full, st["carry"])
                # Merge ONLY this tick's group rows into the sampler carry
                # (keys/counts/DFA are all row-indexed): every row's RNG
                # chain splits exactly once per token, exactly as the
                # unstaged chunk's batched split does.
                rows_m = ((jnp.arange(b) >= rows0)
                          & (jnp.arange(b) < rows0 + sg) & valid)

                def merge(new, old):
                    m = rows_m.reshape((b,) + (1,) * (new.ndim - 1))
                    return jnp.where(m, new, old)

                carry2 = jax.tree.map(merge, new_carry, st["carry"])
                # decode_chunk's finish accounting, verbatim on the group.
                nxt_g = slice_rows(nxt_full, rows0)
                nxt_g = jnp.where(cur_live, nxt_g, cur_tok)
                eos_g = slice_rows(eos, rows0)
                bud_g = slice_rows(st["budget"], rows0)
                lens_new = cur_lens + cur_live.astype(cur_lens.dtype)
                bud_new = bud_g - cur_live.astype(bud_g.dtype)
                fin = cur_live & ((nxt_g == eos_g) | (bud_new <= 0))
                live_new = cur_live & ~fin
                st2 = dict(st)
                st2["carry"] = carry2
                st2["live"] = scat_rows(st["live"], live_new, rows0, valid)
                st2["budget"] = scat_rows(st["budget"], bud_new, rows0,
                                          valid)
                st2["lens"] = scat_rows(st["lens"], lens_new, rows0, valid)
                old_t = lax.dynamic_slice(st["toks"], (kc, rows0), (1, sg))
                st2["toks"] = lax.dynamic_update_slice(
                    st["toks"], jnp.where(valid, nxt_g[None], old_t),
                    (kc, rows0))
                old_v = lax.dynamic_slice(st["valid"], (kc, rows0), (1, sg))
                st2["valid"] = lax.dynamic_update_slice(
                    st["valid"], jnp.where(valid, cur_live[None], old_v),
                    (kc, rows0))
                bufs = []
                for buf, leaf in zip(st["aux"], jax.tree.leaves(aux)):
                    if leaf.ndim and leaf.shape[0] == b:
                        starts = (kc,) + (0,) * leaf.ndim
                        oldb = lax.dynamic_slice(buf, starts,
                                                 (1,) + leaf.shape)
                        m = rows_m.reshape((b,) + (1,) * (leaf.ndim - 1))
                        bufs.append(lax.dynamic_update_slice(
                            buf, jnp.where(m, leaf, oldb[0])[None], starts))
                    else:  # per-step scalar (masked-entry counts): sum the
                        bufs.append(  # group ticks of token k together
                            buf.at[kc].add(jnp.where(valid, leaf, 0)))
                st2["aux"] = tuple(bufs)
                return st2, nxt_g, live_new, lens_new

            def skip_sample(op):
                st, _y = op
                return st, cur_tok, cur_live, cur_lens

            st, out_tok, out_live, out_lens = lax.cond(
                is_last, do_sample, skip_sample, (st, y))
            out_bundle = {"x": y, "tok": out_tok, "live": out_live,
                          "lens": out_lens}
            out_bundle = jax.tree.map(
                lambda v: lax.ppermute(v, AXIS_PP, ring), out_bundle)
            return (out_bundle, st, ck_l, cv_l), None

        st0 = state0()
        bundle0 = dict(
            x=jnp.zeros((sg, 1, spec.d_model), jnp.dtype(spec.dtype)),
            tok=jnp.zeros((sg,), jnp.int32),
            live=jnp.zeros((sg,), bool),
            lens=jnp.zeros((sg,), jnp.int32),
        )
        carry0 = (_pvary(bundle0, (AXIS_PP,)), _pvary(st0, (AXIS_PP,)),
                  ck_l, cv_l)
        (_, st, ck_l, cv_l), _ = lax.scan(
            tick, carry0, jnp.arange(n_ticks))

        # Only the LAST stage's full-width state/output copies are
        # authoritative (it owns sampling); psum-select replicates them
        # back to every stage — the training pipeline's outbuf pattern.
        def from_last(v):
            if v.dtype == jnp.bool_:
                z = lax.psum(jnp.where(is_last, v.astype(jnp.int32), 0),
                             AXIS_PP)
                return z.astype(jnp.bool_)
            return lax.psum(jnp.where(is_last, v, jnp.zeros_like(v)),
                            AXIS_PP)

        out = jax.tree.map(from_last, st)
        return ck_l, cv_l, out

    staged = jax.tree.map(lambda _: P(AXIS_PP), blocks)
    cache_specs_k = jax.tree.map(lambda _: P(AXIS_PP), cache_k)
    cache_specs_v = jax.tree.map(lambda _: P(AXIS_PP), cache_v)
    rep_out = jax.tree.map(lambda _: P(), jax.eval_shape(state0))
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(staged, cache_specs_k, cache_specs_v),
        out_specs=(cache_specs_k, cache_specs_v, rep_out),
        check_rep=False,
    )
    cache_k, cache_v, out = fn(blocks, cache_k, cache_v)
    toks = out["toks"].T                       # [B, n_steps]
    valid_t = out["valid"].T
    n_valid = jnp.sum(valid_t.astype(jnp.int32), axis=1)
    return (toks, valid_t, n_valid, out["live"], out["budget"],
            cache_k, cache_v, out["lens"], out["carry"],
            tuple(out["aux"]))


def staged_decode_loop(
    params,
    spec: ModelSpec,
    mesh: Mesh,
    n_steps: int,
    n_chunks: int,
    token, lengths, live, budget, eos,
    cache_k, cache_v,
    sample_fn, sample_carry,
    history: int | None = None,
    flash: str | None = None,
):
    """Megachunk wrapper for the staged chunk — decode_loop's contract
    (leading per-chunk axis on tokens/n_valid/aux, all-rows-finished early
    exit, carry passthrough on skipped chunks) with the ppermute tick scan
    nested inside the fused C-chunk scan: one dispatch, C×n_steps tokens,
    the stage ring full the whole way."""
    def run_chunk(op):
        tok, lens, lv, bud, ck, cv, s_carry = op
        (toks, _valid, n_valid, lv, bud, ck, cv, lens, s_carry, aux) = \
            staged_decode_chunk(params, spec, mesh, n_steps, tok, lens, lv,
                                bud, eos, ck, cv, sample_fn, s_carry,
                                history=history, flash=flash)
        return (toks[:, -1], lens, lv, bud, ck, cv, s_carry), \
            (toks, n_valid, aux)

    carry0 = (token, lengths, live, budget, cache_k, cache_v, sample_carry)
    out_shapes = jax.eval_shape(lambda op: run_chunk(op)[1], carry0)

    def skip_chunk(op):
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             out_shapes)
        return op, zeros

    def body(carry, _):
        return lax.cond(jnp.any(carry[2]), run_chunk, skip_chunk, carry)

    carry, (toks, n_valid, aux) = lax.scan(body, carry0, None,
                                           length=n_chunks)
    token, lengths, live, budget, cache_k, cache_v, sample_carry = carry
    return (toks, n_valid, token, live, budget, cache_k, cache_v, lengths,
            sample_carry, aux)


def pp_loss_fn(params, spec: ModelSpec, tokens, mesh, n_micro: int,
               remat: bool = True):
    """Mean next-token cross-entropy through the pipeline (same contract as
    quorum_tpu.training.trainer.loss_fn)."""
    logits = pipeline_forward_logits(
        params, spec, tokens[:, :-1], mesh, n_micro, remat=remat)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    m = (targets != 0).astype(jnp.float32)
    return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)


def pp_train_init(spec: ModelSpec, mesh: Mesh, *, seed: int = 0,
                  optimizer=None):
    """Sharded TrainState with blocks staged over pp (optimizer moments
    inherit the layout through jit output propagation)."""
    from quorum_tpu.models.init import init_params
    from quorum_tpu.training.trainer import TrainState, make_optimizer

    opt = optimizer or make_optimizer()
    params = shard_pytree_pp(mesh, init_params(spec, seed))
    opt_state = jax.jit(opt.init)(params)
    rep = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda x: x if isinstance(x.sharding, NamedSharding)
        else jax.device_put(x, rep),
        opt_state,
    )
    return TrainState(params=params, opt_state=opt_state,
                      step=jax.device_put(jnp.zeros((), jnp.int32), rep))


def make_pp_train_step(spec: ModelSpec, mesh: Mesh, *, n_micro: int = 2,
                       optimizer=None, remat: bool = True):
    """One pipelined SGD step: ``step(state, tokens [B, T]) → (state, loss)``.

    Gradients flow backward through the same pipeline (ppermute/scan/psum
    transpose to the reverse schedule); AdamW updates run where each stage's
    weights live.
    """
    import optax  # lazy: serving installs don't ship the training deps

    from quorum_tpu.training.trainer import TrainState, make_optimizer

    opt = optimizer or make_optimizer()
    token_sharding = NamedSharding(mesh, P(AXIS_DP, None))

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens: jnp.ndarray):
        loss, grads = jax.value_and_grad(pp_loss_fn)(
            state.params, spec, tokens, mesh, n_micro, remat)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    def run(state, tokens):
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), token_sharding)
        return step(state, tokens)

    return run
