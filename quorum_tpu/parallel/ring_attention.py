"""Ring attention: sequence-parallel causal attention over the ``sp`` mesh axis.

Long-context design (SURVEY.md §5.7): the sequence dimension is sharded over
``sp`` devices, so no device ever materializes full-length K/V — activation
memory per chip is O(S/sp). Each device computes blockwise attention of its
local query block against the K/V block it currently holds, then passes that
K/V block to its ring neighbor with ``lax.ppermute`` (ICI nearest-neighbor
traffic), repeating sp times. Online softmax (the same math as the flash
kernel, quorum_tpu.ops.flash_attention) merges the partial results exactly.

Composition with the rest of the mesh: the wrapper is a ``shard_map`` over the
FULL (dp, sp, tp) mesh — batch stays sharded on dp and heads on tp; only the
ring loop communicates, and only over sp. Blocks entirely above the causal
diagonal contribute nothing but still take a ring step (the permutation must
stay collective); their work is masked out.

The reference proxy has no sequence handling at all (prompts are opaque
strings relayed over HTTP, /root/reference/src/quorum/oai_proxy.py:185-192) —
this module is north-star functionality, not behavioral parity.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from quorum_tpu.ops.attention import NEG_INF
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP


def _ring_local(q, k, v, lengths, *, axis: str, sp_size: int, _mesh_axes=()):
    """Per-device ring loop. q/k/v: [B, H_local, S_local, hd]; lengths [B]."""
    idx = lax.axis_index(axis)
    s_local = q.shape[2]
    scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32) * scale
    row_global = idx * s_local + jnp.arange(s_local)  # [S_local]

    perm = [(j, (j + 1) % sp_size) for j in range(sp_size)]

    def update(m, l, acc, k_cur, v_cur, i):
        # The block we hold at step i originated on device (idx - i) mod sp.
        src = (idx - i) % sp_size
        col_global = src * s_local + jnp.arange(s_local)  # [S_local]
        logits = jnp.einsum(
            "bhsd,bhtd->bhst", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        causal = col_global[None, :] <= row_global[:, None]   # [S, T]
        valid = col_global[None, :] < lengths[:, None]         # [B, T]
        keep = causal[None, :, :] & valid[:, None, :]          # [B, S, T]
        logits = jnp.where(keep[:, None, :, :], logits, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.einsum(
            "bhst,bhtd->bhsd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = update(m, l, acc, k_cur, v_cur, i)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    b, h, s, hd = q.shape
    m0 = jnp.full((b, h, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s, hd), jnp.float32)
    # Mark the freshly-created carries as device-varying so the scan carry
    # type matches its (varying) outputs under shard_map's vma typing.
    try:
        m0, l0, acc0 = jax.lax.pcast((m0, l0, acc0), tuple(_mesh_axes), to="varying")
    except (AttributeError, TypeError):  # older jax spells it pvary
        m0, l0, acc0 = jax.lax.pvary((m0, l0, acc0), tuple(_mesh_axes))
    # sp_size-1 (compute + permute) steps, then one final compute with the
    # last-held block OUTSIDE the scan — the ring's last permutation would
    # only be thrown away, so it is never sent.
    (m, l, acc, k_last, v_last), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(sp_size - 1)
    )
    m, l, acc = update(m, l, acc, k_last, v_last, sp_size - 1)
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_prefill_attention(
    q: jnp.ndarray,        # [B, H, S, hd] (global view)
    k: jnp.ndarray,        # [B, H, S, hd] — KV heads pre-broadcast to H
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B]
    mesh: Mesh,
    *,
    sp: str = AXIS_SP,
) -> jnp.ndarray:
    """Causal, length-masked attention with the sequence sharded over ``sp``.

    Batch rides dp, heads ride tp, sequence rides sp; only sp communicates
    (one ppermute of the local K/V block per ring step).
    """
    sp_size = mesh.shape[sp]
    qs = P(AXIS_DP, AXIS_TP, sp, None)
    inner = partial(_ring_local, axis=sp, sp_size=sp_size,
                    _mesh_axes=tuple(mesh.axis_names))
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(qs, qs, qs, P(AXIS_DP)),
        out_specs=qs,
    )
    return fn(q, k, v, lengths)
