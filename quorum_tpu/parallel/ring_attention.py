"""Ring attention: sequence-parallel causal attention over the ``sp`` mesh axis.

Long-context design (SURVEY.md §5.7): the sequence dimension is sharded over
``sp`` devices, so no device ever materializes full-length K/V — activation
memory per chip is O(S/sp). Each device computes blockwise attention of its
local query block against the K/V block it currently holds, then passes that
K/V block to its ring neighbor with ``lax.ppermute`` (ICI nearest-neighbor
traffic), repeating sp times. Online softmax (the same math as the flash
kernel, quorum_tpu.ops.flash_attention) merges the partial results exactly.

Composition with the rest of the mesh: the wrapper is a ``shard_map`` over the
FULL (dp, sp, tp) mesh — batch stays sharded on dp and heads on tp; only the
ring loop communicates, and only over sp. Blocks entirely above the causal
diagonal contribute nothing but still take a ring step (the permutation must
stay collective); their work is masked out.

The reference proxy has no sequence handling at all (prompts are opaque
strings relayed over HTTP, /root/reference/src/quorum/oai_proxy.py:185-192) —
this module is north-star functionality, not behavioral parity.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from quorum_tpu.ops.attention import NEG_INF
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP


def _ring_local(q, k, v, lengths, *, axis: str, sp_size: int, _mesh_axes=()):
    """Per-device ring loop with GQA grouped *inside* the ring.

    q: [B, H_local, S_local, hd]; k/v: [B, K_local, S_local, hd] with
    H_local = K_local · G. Queries are reshaped to [B, K, G, S, hd] and
    contracted against the shared KV heads directly — the K/V blocks that
    ride the ring stay at KV-head width, so ICI traffic and HBM footprint
    are G× smaller than broadcasting KV to query heads before the ring
    (the round-2 wrapper's ``jnp.repeat``, VERDICT r2 weakness 3).
    """
    idx = lax.axis_index(axis)
    b, h, s_local, hd = q.shape
    n_kv = k.shape[1]
    g = h // n_kv
    scale = hd ** -0.5
    qf = q.astype(jnp.float32).reshape(b, n_kv, g, s_local, hd) * scale
    row_global = idx * s_local + jnp.arange(s_local)  # [S_local]

    perm = [(j, (j + 1) % sp_size) for j in range(sp_size)]

    def update(m, l, acc, k_cur, v_cur, i):
        # The block we hold at step i originated on device (idx - i) mod sp.
        src = (idx - i) % sp_size
        col_global = src * s_local + jnp.arange(s_local)  # [S_local]
        logits = jnp.einsum(
            "bkgsd,bktd->bkgst", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        causal = col_global[None, :] <= row_global[:, None]   # [S, T]
        valid = col_global[None, :] < lengths[:, None]         # [B, T]
        keep = causal[None, :, :] & valid[:, None, :]          # [B, S, T]
        logits = jnp.where(keep[:, None, None, :, :], logits, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.einsum(
            "bkgst,bktd->bkgsd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = update(m, l, acc, k_cur, v_cur, i)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    m0 = jnp.full((b, n_kv, g, s_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n_kv, g, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, n_kv, g, s_local, hd), jnp.float32)
    # Mark the freshly-created carries as device-varying so the scan carry
    # type matches its (varying) outputs under shard_map's vma typing.
    try:
        m0, l0, acc0 = jax.lax.pcast((m0, l0, acc0), tuple(_mesh_axes), to="varying")
    except (AttributeError, TypeError):
        try:  # older jax spells it pvary
            m0, l0, acc0 = jax.lax.pvary((m0, l0, acc0), tuple(_mesh_axes))
        except AttributeError:
            pass  # pre-vma jax (< 0.5): no varying-manual typing — no-op
    # sp_size-1 (compute + permute) steps, then one final compute with the
    # last-held block OUTSIDE the scan — the ring's last permutation would
    # only be thrown away, so it is never sent.
    (m, l, acc, k_last, v_last), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(sp_size - 1)
    )
    m, l, acc = update(m, l, acc, k_last, v_last, sp_size - 1)
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(b, h, s_local, hd).astype(q.dtype)


def _axis_if_divisible(dim: int, axis: str, mesh: Mesh) -> str | None:
    """Shard ``dim`` over ``axis`` only when it divides evenly; replicate
    otherwise (e.g. batch-1 engine admission on a dp≥2 mesh, or 2 KV heads
    on tp=4)."""
    return axis if dim % mesh.shape[axis] == 0 else None


def gqa_axis_selection(b: int, h: int, n_kv: int, mesh: Mesh):
    """(baxis, haxis, kaxis) for sequence-parallel attention wrappers —
    shared by the ring and Ulysses strategies so the sharding-selection
    rules can never diverge. Batch rides dp and heads ride tp when they
    divide; when H would shard over tp but K would not, q's heads are
    replicated alongside the replicated KV heads so the per-device GQA
    grouping stays consistent."""
    baxis = _axis_if_divisible(b, AXIS_DP, mesh)
    haxis = _axis_if_divisible(h, AXIS_TP, mesh)
    kaxis = _axis_if_divisible(n_kv, AXIS_TP, mesh)
    if haxis != kaxis:
        haxis = kaxis
    return baxis, haxis, kaxis


def ring_prefill_attention(
    q: jnp.ndarray,        # [B, H, S, hd] (global view)
    k: jnp.ndarray,        # [B, K, S, hd] — KV heads; grouped inside the ring
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B]
    mesh: Mesh,
    *,
    sp: str = AXIS_SP,
) -> jnp.ndarray:
    """Causal, length-masked GQA attention with the sequence sharded over
    ``sp``.

    Batch rides dp, heads ride tp, sequence rides sp; only sp communicates
    (one ppermute of the local KV-width block per ring step). Dims the mesh
    doesn't divide (batch-1 admissions, KV heads < tp) replicate instead of
    failing. When H and K would land on different tp shard counts (H % tp
    == 0 but K % tp != 0), q's heads are replicated too so the per-device
    GQA grouping stays consistent.
    """
    sp_size = mesh.shape[sp]
    b, h = q.shape[0], q.shape[1]
    n_kv = k.shape[1]
    if q.shape[2] % sp_size != 0:
        # Sequence can't shard over sp (e.g. a 16-token admission bucket on
        # sp=32): fall back to the dense replicated path rather than fail
        # the request — short sequences don't need the ring anyway.
        from quorum_tpu.ops.attention import prefill_attention

        return prefill_attention(q, k, v, lengths)
    baxis, haxis, kaxis = gqa_axis_selection(b, h, n_kv, mesh)
    qs = P(baxis, haxis, sp, None)
    ks = P(baxis, kaxis, sp, None)
    # The online-softmax carries vary only over the axes the inputs are
    # actually sharded on (shard_map's vma typing rejects carries marked
    # varying over axes the out_specs call replicated).
    varying = tuple(a for a in dict.fromkeys((baxis, haxis, sp)) if a)
    inner = partial(_ring_local, axis=sp, sp_size=sp_size, _mesh_axes=varying)
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(qs, ks, ks, P(baxis)),
        out_specs=qs,
    )
    return fn(q, k, v, lengths)
