"""Logical-axis → PartitionSpec rules for model state.

The scaling-book recipe: name the logical axes of every array once, map
logical axes to mesh axes in one table, and let GSPMD insert collectives.
Megatron-style tensor parallelism falls out of two rules:

  - project *into* parallel subspaces (heads, MLP hidden, experts, vocab)
    with the output dimension sharded over ``tp``  → no communication;
  - project *back* to the model dimension with the input dimension sharded
    over ``tp`` → one psum (all-reduce) per block, inserted by XLA.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_SP, AXIS_TP

# Logical axis name → mesh axis (None = replicated).
LOGICAL_RULES: dict[str, str | None] = {
    "batch": AXIS_DP,
    "seq": None,          # sequence is replicated except under ring attention
    "seq_shard": AXIS_SP,  # ring attention: sequence blocks over the sp axis
    "model": None,         # d_model stays replicated (activations all-reduced)
    "heads": AXIS_TP,
    "kv_heads": AXIS_TP,
    "head_dim": None,
    "ff": AXIS_TP,         # MLP hidden
    "experts": AXIS_TP,    # expert parallelism shares the tp axis
    "vocab": AXIS_TP,
    # Scanned-layer leading dim: stage-sharded over pp (a no-op placement on
    # every mesh whose pp axis is 1 — i.e. everything except the
    # pipeline-staged decode group and the pp training mesh).
    "layers": AXIS_PP,
    "pos": None,
}


def logical_to_spec(axes: tuple[str | None, ...]) -> P:
    """``("layers", "model", "ff")`` → ``P(None, None, "tp")``."""
    return P(*(LOGICAL_RULES.get(a) if a else None for a in axes))


def logical_to_sharding(mesh: Mesh, axes: tuple[str | None, ...]) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(axes))


# Logical axes for every parameter leaf the transformer uses
# (see quorum_tpu.models.transformer for the pytree layout).
PARAM_LOGICAL_AXES: dict[str, tuple[str | None, ...]] = {
    # embeddings
    "tok_emb": ("vocab", "model"),
    "pos_emb": ("pos", "model"),
    "lm_head": ("model", "vocab"),
    "final_norm_w": ("model",),
    "final_norm_b": ("model",),
    # per-layer (leading "layers" dim — scanned)
    "attn_norm_w": ("layers", "model"),
    "attn_norm_b": ("layers", "model"),
    "wq": ("layers", "model", "heads"),
    "wk": ("layers", "model", "kv_heads"),
    "wv": ("layers", "model", "kv_heads"),
    "wo": ("layers", "heads", "model"),
    "bq": ("layers", "heads"),
    "bk": ("layers", "kv_heads"),
    "bv": ("layers", "kv_heads"),
    "bo": ("layers", "model"),
    "mlp_norm_w": ("layers", "model"),
    "mlp_norm_b": ("layers", "model"),
    "w_gate": ("layers", "model", "ff"),
    "w_up": ("layers", "model", "ff"),
    "w_down": ("layers", "ff", "model"),
    "b_up": ("layers", "ff"),
    "b_down": ("layers", "model"),
    # MoE
    "router": ("layers", "model", "experts"),
    "moe_w_gate": ("layers", "experts", "model", None),
    "moe_w_up": ("layers", "experts", "model", None),
    "moe_w_down": ("layers", "experts", None, "model"),
}

# KV cache: [layers, batch, kv_heads, max_seq, head_dim]
KV_CACHE_AXES: tuple[str | None, ...] = ("layers", "batch", "kv_heads", "seq", "head_dim")


def kv_cache_sharding(mesh: Mesh, n_kv_heads: int, batch: int | None = None,
                      *, seq_shard: bool = False) -> "NamedSharding":
    """KV-cache sharding that degrades gracefully for GQA: when the kv-head
    count doesn't divide the tp axis (e.g. 2 KV heads on tp=4), the head axis
    is replicated — attention q·K still runs tp-sharded over query heads.

    The leading layer axis shards over ``pp`` (a no-op except on the
    pipeline-staged decode mesh, where each stage holds its own layers' KV —
    the engine rejects ``pp`` that doesn't divide ``n_layers``).

    ``seq_shard=True`` additionally shards the position axis over ``sp`` —
    the disagg PREFILL group's staging cache under ``sp>1``: a 100k-token
    admission's staged KV occupies O(max_seq/sp) HBM per device while the
    decode group keeps its latency-shaped replicated-sequence layout (the
    handoff reshards on the fly)."""
    axes = list(KV_CACHE_AXES)
    if n_kv_heads % mesh.shape[AXIS_TP] != 0:
        axes[2] = None
    if batch is not None and batch % mesh.shape[AXIS_DP] != 0:
        axes[1] = None
    if seq_shard and mesh.shape[AXIS_SP] > 1:
        axes[3] = "seq_shard"
    return logical_to_sharding(mesh, tuple(axes))


def paged_kv_sharding(mesh: Mesh, n_kv_heads: int) -> "NamedSharding":
    """Page-pool sharding for the paged KV layout (``kv_pages=1``):
    ``[layers, pages, kv_heads, page_size, head_dim]``. The physical page
    axis never shards — pages are the allocation unit and a row's chain
    scatters arbitrarily across the pool, so a sharded page axis would turn
    every table gather into a cross-device shuffle. kv_heads shard over tp
    with the same GQA degrade rule as :func:`kv_cache_sharding`; the layer
    axis shards over pp (rejected >1 by the engine under kv_pages, so in
    practice a no-op kept for shape symmetry)."""
    axes: list = ["layers", None, "kv_heads", None, None]
    if n_kv_heads % mesh.shape[AXIS_TP] != 0:
        axes[2] = None
    return logical_to_sharding(mesh, tuple(axes))
# Activations: [batch, seq, model]
ACT_AXES: tuple[str | None, ...] = ("batch", "seq", "model")
# Token ids: [batch, seq]
TOKEN_AXES: tuple[str | None, ...] = ("batch", "seq")


def param_partition_specs(
    params: Mapping[str, Any], lead_axes: int = 0,
    *, replicate_kv_heads: bool = False
) -> dict[str, Any]:
    """PartitionSpec pytree matching a parameter pytree (same nesting).

    ``lead_axes`` prepends that many replicated dims to every leaf's spec —
    used for member-stacked ensemble params ``[M, …]`` (the member axis is
    vmapped, never sharded).

    ``replicate_kv_heads`` replicates every leaf whose logical axes include
    ``kv_heads`` (wk/wv/bk/bv). The kv projection's output dim is the *flat*
    ``K·hd``, so ``_fit_spec``'s divisibility check can't see head
    boundaries: 2 KV heads × hd=16 on tp=4 passes (32 % 4 == 0) but shards
    each KV head across two devices. Sub-head-sharded kv projections
    miscompile under GSPMD on jax 0.4.x for batch-1 prefill (the engine's
    slot-mode admission path) — wrong logits, deterministic, mesh-dependent
    (dp=2×tp=4 yes, tp=4 no) — which was half of the PR 16 "MoE EP
    divergence" quarantine. Replicating mirrors ``kv_cache_sharding``'s GQA
    degrade rule: when kv heads don't divide tp, whole-head sharding is
    impossible and sharding half a head buys nothing."""

    def spec_for(name: str) -> P:
        axes = PARAM_LOGICAL_AXES.get(name)
        if axes is None:
            return P()  # unknown leaf → replicate
        if replicate_kv_heads and "kv_heads" in axes:
            axes = tuple(None if a == "kv_heads" else a for a in axes)
        return P(*((None,) * lead_axes + tuple(logical_to_spec(axes))))

    def walk(tree: Mapping[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for k, v in tree.items():
            if isinstance(v, Mapping):
                if "q8" in v:
                    # int8-quantized leaf (models/quant.py): q8 has the parent
                    # leaf's shape → parent spec; the scale keeps the same
                    # logical axes with reduced dims at size 1, which
                    # _fit_spec auto-replicates (1 % mesh_size != 0).
                    out[k] = {"q8": spec_for(k), "qs": spec_for(k)}
                else:
                    out[k] = walk(v)
            elif v is None:
                out[k] = None
            else:
                out[k] = spec_for(k)
        return out

    return walk(params)


def _fit_spec(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Drop sharding on any dim the mesh doesn't divide (e.g. vocab 50257 on
    tp=4) — replicate that dim instead of failing. XLA still shards the rest."""
    fitted = []
    for dim, axis in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axis is None:
            fitted.append(None)
        else:
            size = mesh.shape[axis] if isinstance(axis, str) else 1
            fitted.append(axis if dim % size == 0 else None)
    return P(*fitted)


def param_shardings(
    mesh: Mesh, params: Mapping[str, Any], lead_axes: int = 0,
    n_kv_heads: int | None = None,
) -> dict[str, Any]:
    """Shardings for a param pytree; pass ``n_kv_heads`` so GQA kv
    projections degrade to replicated (whole leaf) when the head count
    doesn't divide tp — see :func:`param_partition_specs`."""
    replicate_kv = (n_kv_heads is not None
                    and n_kv_heads % mesh.shape[AXIS_TP] != 0)
    specs = param_partition_specs(params, lead_axes,
                                  replicate_kv_heads=replicate_kv)
    return jax.tree.map(
        lambda x, s: None if x is None else NamedSharding(mesh, _fit_spec(s, x.shape, mesh)),
        dict(params),
        specs,
        is_leaf=lambda x: x is None or not isinstance(x, Mapping),
    )


def shard_pytree(mesh: Mesh, params: Mapping[str, Any],
                 n_kv_heads: int | None = None) -> dict[str, Any]:
    """Place a host/param pytree onto the mesh with the standard TP layout."""
    shardings = param_shardings(mesh, params, n_kv_heads=n_kv_heads)
    return jax.tree.map(
        lambda x, s: x if x is None else jax.device_put(x, s),
        dict(params),
        shardings,
        is_leaf=lambda x: x is None or not isinstance(x, Mapping),
    )
