"""Ulysses sequence parallelism: all-to-all attention over the ``sp`` axis.

The second standard SP strategy beside ring attention
(parallel/ring_attention.py). Where the ring keeps the sequence sharded and
circulates K/V blocks device-to-device (sp ppermutes per layer), Ulysses
re-shards ONCE per attention: a single packed all-to-all (q/k/v
interleaved per sp-group along the head axis) converts sequence-sharded
activations into head-sharded ones — each device holds the FULL sequence
for its H/sp head slice — attention runs entirely locally, and one
all-to-all converts back. TWO collective launches per layer, total bytes
O(B·S·(D + 2·K·hd)/sp), rather than sp dependent ring hops.

Trade-offs vs the ring (why both exist):

  - Ulysses holds full-length K/V for its head slice — per-device attention
    memory is O(S·K/sp · hd), not O(S/sp). Fine for prefill at serving
    context lengths; the ring remains the answer when even one head's
    full-length K/V cannot fit.
  - Ulysses needs the HEAD counts divisible by sp (H/tp-shard and K must
    split over sp); GQA models with few KV heads cap sp at K. The ring has
    no head constraint.
  - Because each device sees the whole sequence, windowed (mistral) specs
    work unchanged — the ring rejects them (it would widen the receptive
    field).

The reference proxy has no sequence handling at all
(/root/reference/src/quorum/oai_proxy.py:185-192); north-star
functionality, not behavioral parity.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from quorum_tpu.ops.attention import prefill_attention
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP
from quorum_tpu.parallel.ring_attention import gqa_axis_selection


def _ulysses_local(q, k, v, lengths, *, axis: str, sp_size: int, window: int):
    """Per-device body: seq-sharded in → ONE packed all-to-all → full-seq
    attention on a head slice → one all-to-all back to seq-sharded out.

    q/k/v share one inbound transfer: ``all_to_all(split_axis=1)`` hands
    destination device d the d-th sp-slice of the packed head axis, so the
    packing interleaves PER-GROUP — group d carries (q-heads d·hq/sp…,
    k-heads d·hk/sp…, v-heads …) contiguously and every split boundary
    stays pure. The head-divisibility preconditions are enforced by
    ``ulysses_supported`` before shard_map dispatches here."""
    b, hq, s_loc, hd = q.shape
    hk = k.shape[1]
    gq, gk = hq // sp_size, hk // sp_size

    def grouped(x, g):
        # [B, sp·g, s, hd] → [B, sp, g, s, hd]
        return x.reshape(b, sp_size, g, s_loc, hd)

    packed = jnp.concatenate(
        [grouped(q, gq), grouped(k, gk), grouped(v, gk)], axis=2
    ).reshape(b, hq + 2 * hk, s_loc, hd)
    ph = lax.all_to_all(packed, axis, split_axis=1, concat_axis=2, tiled=True)
    # ph [B, gq+2·gk, S, hd]: this device's q/k/v head slices, full sequence.
    qh = ph[:, :gq]
    kh = ph[:, gq:gq + gk]
    vh = ph[:, gq + gk:]
    out = prefill_attention(qh, kh, vh, lengths, window=window)
    # [B, hq/sp, S, hd] → [B, hq, s_loc, hd]: split seq, gather heads.
    return lax.all_to_all(out, axis, split_axis=2, concat_axis=1, tiled=True)


def ulysses_supported(h: int, n_kv: int, mesh: Mesh, sp: str = AXIS_SP) -> bool:
    """Statically checkable Ulysses requirement: the per-device head counts
    must split over sp. The engine uses this to FAIL FAST at construction —
    a silent dense fallback at serving time would materialize full
    replicated attention at exactly the context lengths sp exists for.
    (Sequence-length divisibility stays a per-request dynamic fallback.)"""
    _, haxis, kaxis = gqa_axis_selection(1, h, n_kv, mesh)
    tp_div = mesh.shape[AXIS_TP] if haxis else 1
    sp_size = mesh.shape[sp]
    return (h // tp_div) % sp_size == 0 and (n_kv // tp_div) % sp_size == 0


def ulysses_prefill_attention(
    q: jnp.ndarray,        # [B, H, S, hd] (global view)
    k: jnp.ndarray,        # [B, K, S, hd]
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B]
    mesh: Mesh,
    *,
    sp: str = AXIS_SP,
    window: int = 0,
) -> jnp.ndarray:
    """Causal, length-masked GQA attention, sequence sharded over ``sp``
    via head↔sequence all-to-alls. Falls back to the dense replicated path
    when the shapes don't divide (short admission buckets, few heads)."""
    sp_size = mesh.shape[sp]
    b, h, s, _ = q.shape
    n_kv = k.shape[1]
    baxis, haxis, kaxis = gqa_axis_selection(b, h, n_kv, mesh)
    if (sp_size == 1 or s % sp_size != 0
            or not ulysses_supported(h, n_kv, mesh, sp)):
        return prefill_attention(q, k, v, lengths, window=window)
    qs = P(baxis, haxis, sp, None)
    ks = P(baxis, kaxis, sp, None)
    fn = shard_map(
        partial(_ulysses_local, axis=sp, sp_size=sp_size, window=window),
        mesh=mesh,
        in_specs=(qs, ks, ks, P(baxis)),
        out_specs=qs,
    )
    return fn(q, k, v, lengths)
