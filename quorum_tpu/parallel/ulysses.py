"""Ulysses sequence parallelism: all-to-all attention over the ``sp`` axis.

The second standard SP strategy beside ring attention
(parallel/ring_attention.py). Where the ring keeps the sequence sharded and
circulates K/V blocks device-to-device (sp ppermutes per layer), Ulysses
re-shards ONCE per attention: all-to-alls convert sequence-sharded
activations into head-sharded ones (each device holds the FULL sequence for
H/sp of the heads), attention runs entirely locally, and one all-to-all
converts back — four collective launches per layer (q, k, v in; out back;
packing q/k/v into one transfer is possible but needs a per-sp-group head
reordering), total bytes O(B·S·(D + 2·K·hd)/sp) in two resharding phases
rather than sp dependent ring hops.

Trade-offs vs the ring (why both exist):

  - Ulysses holds full-length K/V for its head slice — per-device attention
    memory is O(S·K/sp · hd), not O(S/sp). Fine for prefill at serving
    context lengths; the ring remains the answer when even one head's
    full-length K/V cannot fit.
  - Ulysses needs the HEAD counts divisible by sp (H/tp-shard and K must
    split over sp); GQA models with few KV heads cap sp at K. The ring has
    no head constraint.
  - Because each device sees the whole sequence, windowed (mistral) specs
    work unchanged — the ring rejects them (it would widen the receptive
    field).

The reference proxy has no sequence handling at all
(/root/reference/src/quorum/oai_proxy.py:185-192); north-star
functionality, not behavioral parity.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax ≥ 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from quorum_tpu.ops.attention import prefill_attention
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP
from quorum_tpu.parallel.ring_attention import gqa_axis_selection


def _ulysses_local(q, k, v, lengths, *, axis: str, window: int):
    """Per-device body: seq-sharded in → all-to-all → full-seq attention on
    a head slice → all-to-all back to seq-sharded out."""
    # [B, h_loc, s_loc, hd] → [B, h_loc/sp, S, hd]: split heads, gather seq.
    qh = lax.all_to_all(q, axis, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis, split_axis=1, concat_axis=2, tiled=True)
    out = prefill_attention(qh, kh, vh, lengths, window=window)
    # [B, h_loc/sp, S, hd] → [B, h_loc, s_loc, hd]: split seq, gather heads.
    return lax.all_to_all(out, axis, split_axis=2, concat_axis=1, tiled=True)


def ulysses_supported(h: int, n_kv: int, mesh: Mesh, sp: str = AXIS_SP) -> bool:
    """Statically checkable Ulysses requirement: the per-device head counts
    must split over sp. The engine uses this to FAIL FAST at construction —
    a silent dense fallback at serving time would materialize full
    replicated attention at exactly the context lengths sp exists for.
    (Sequence-length divisibility stays a per-request dynamic fallback.)"""
    _, haxis, kaxis = gqa_axis_selection(1, h, n_kv, mesh)
    tp_div = mesh.shape[AXIS_TP] if haxis else 1
    sp_size = mesh.shape[sp]
    return (h // tp_div) % sp_size == 0 and (n_kv // tp_div) % sp_size == 0


def ulysses_prefill_attention(
    q: jnp.ndarray,        # [B, H, S, hd] (global view)
    k: jnp.ndarray,        # [B, K, S, hd]
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B]
    mesh: Mesh,
    *,
    sp: str = AXIS_SP,
    window: int = 0,
) -> jnp.ndarray:
    """Causal, length-masked GQA attention, sequence sharded over ``sp``
    via head↔sequence all-to-alls. Falls back to the dense replicated path
    when the shapes don't divide (short admission buckets, few heads)."""
    sp_size = mesh.shape[sp]
    b, h, s, _ = q.shape
    n_kv = k.shape[1]
    baxis, haxis, kaxis = gqa_axis_selection(b, h, n_kv, mesh)
    if (sp_size == 1 or s % sp_size != 0
            or not ulysses_supported(h, n_kv, mesh, sp)):
        return prefill_attention(q, k, v, lengths, window=window)
    qs = P(baxis, haxis, sp, None)
    ks = P(baxis, kaxis, sp, None)
    fn = shard_map(
        partial(_ulysses_local, axis=sp, window=window),
        mesh=mesh,
        in_specs=(qs, ks, ks, P(baxis)),
        out_specs=qs,
    )
    return fn(q, k, v, lengths)
