"""Native quorum serving (docs/quorum.md).

The paper's topology — fan one prompt out to N members, combine the
answers — rebuilt as a first-class serving workload instead of a proxy
pattern, in three layers that compose but ship independently:

  1. **Shared-prefix member dedup** (engine tier): on a ``members=M``
     stacked engine with ``member_seeds=shared``, a member-complete
     admission group carrying one prompt prefills it ONCE and broadcasts
     the K/V into all M cache rows — ``quorum_dedup=1`` on the engine
     URL; savings on ``quorum_tpu_quorum_dedup_tokens_total``. Lives in
     :mod:`quorum_tpu.engine.engine` (``_dedup_admit_fn``).

  2. **In-engine aggregation hop** (strategy tier): the aggregator's
     synthesis runs as an ordinary engine request with its own QoS class
     (``aggregator_priority``), optionally streamed live as the client
     response (``stream_aggregate``) and optionally drafted through the
     prompt-lookup speculation machinery (``speculative_aggregation``).
     Lives in :mod:`quorum_tpu.strategies.aggregate`.

  3. **Cross-cell quorum** (router tier, this package): a ``quorum=M``
     request fans out to M distinct ring-chosen replicas and combines at
     the tier that already owns failover. A member that dies
     mid-generation is first retried token-exact on a spare cell (the
     PR 19 resume wire contract), and only then DROPPED — the request is
     served from the survivors (``quorum_tpu_quorum_degraded_total``),
     never failed while any member holds content.
"""

from quorum_tpu.quorum.fanout import (
    MAX_QUORUM,
    QuorumLeg,
    choose_members,
    pop_quorum,
    quorum_complete,
    quorum_stream,
    validate_quorum,
)

__all__ = [
    "MAX_QUORUM",
    "QuorumLeg",
    "choose_members",
    "pop_quorum",
    "quorum_complete",
    "quorum_stream",
    "validate_quorum",
]
