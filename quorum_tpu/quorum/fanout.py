"""Cross-cell quorum fan-out: one request → M ring replicas → one combine.

The router-tier leg of native quorum serving (docs/quorum.md): a request
carrying ``quorum: M`` fans out to M DISTINCT replicas in ring candidate
order (heterogeneous members — each leg is an independent cell), and the
member answers combine at the router, the tier that already owns failover.

Degradation contract (the whole point): a member leg that fails never
fails the REQUEST —

  - pre-first-byte failure retries the leg on a spare candidate (a ring
    member not already serving another leg), then drops the member
    (``member_failed``);
  - a mid-stream death is first retried TOKEN-EXACT on a spare via the
    zero-loss resume wire contract (``resume_tokens``/``resume_chars``/
    ``qt_tokens`` — docs/robustness.md), so a killed member usually
    finishes its answer on a sibling cell with no duplicate or dropped
    tokens; only when no spare commits is the member dropped
    (``stream_broken``, or ``resume_diverged`` when the replay guard
    itself refused);
  - a member that completes empty is dropped (``no_content``).

Members resume only onto SPARE candidates, never onto a replica already
serving another leg: two legs on one cell would silently halve the
quorum's fault independence, which is worse than an honestly-degraded
quorum. Every dropped member lands on
``quorum_tpu_quorum_degraded_total{reason=}`` and the flight recorder;
the request outcome (``full`` / ``degraded`` / ``failed``) lands on
``quorum_tpu_quorum_requests_total``. The request fails ONLY when no
member produced any content at all.

SSE surface reuses the parallel-proxy contract (oai.py): role chunk id
``chatcmpl-parallel``, member deltas ``chatcmpl-parallel-{i}``, final
combined chunk ``chatcmpl-parallel-final`` (finish_reason "stop"),
all-failed error chunk id ``error``, terminating ``[DONE]``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from quorum_tpu import faults, oai, sse
from quorum_tpu.backends.base import BackendError
from quorum_tpu.observability import QUORUM_DEGRADED, QUORUM_REQUESTS
from quorum_tpu.telemetry.recorder import RECORDER

logger = logging.getLogger(__name__)

# Hard ceiling on the quorum= knob (oai.validate_request_body enforces it
# request-side): past ~8 members the combine is paying fan-out latency for
# answers nobody reads, and a typo like quorum=300 must not fan out.
MAX_QUORUM = 8

QUORUM_MODEL_NAME = "quorum-proxy"

_DONE = object()


def validate_quorum(body: dict[str, Any]) -> str | None:
    """Shape-validate the ``quorum`` body knob (docs/quorum.md). Returns
    an error message for a 400, or None. Mirrors the other knob checks in
    :func:`quorum_tpu.oai.validate_request_body` (which calls this)."""
    q = body.get("quorum")
    if q is None:
        return None
    if isinstance(q, bool) or not isinstance(q, int) \
            or not 1 <= q <= MAX_QUORUM:
        return (f"Invalid value for 'quorum': {q!r} (an integer in "
                f"[1, {MAX_QUORUM}])")
    if q > 1:
        if body.get("n") not in (None, 1):
            return "'quorum' requires n=1"
        if body.get("logprobs"):
            return ("'quorum' cannot be combined with 'logprobs' (the "
                    "combined answer has no single token record stream)")
        if body.get("resume_tokens") is not None:
            return ("'quorum' cannot be combined with 'resume_tokens' "
                    "(member resume is router-internal)")
        if body.get("stream_token_ids"):
            return ("'quorum' cannot be combined with 'stream_token_ids' "
                    "(the quorum combine re-chunks member deltas, so "
                    "per-chunk token ids would be meaningless)")
    return None


def pop_quorum(body: dict[str, Any]) -> int:
    """Strip the ``quorum`` knob (it must never reach a replica — a
    forwarded knob would recurse the fan-out) and return the member count
    (1 = off). Call after :func:`validate_quorum`."""
    q = body.pop("quorum", None)
    return int(q) if isinstance(q, int) and not isinstance(q, bool) else 1


def choose_members(candidates: list[str], m: int) -> tuple[list[str], list[str]]:
    """Split the ring's candidate order into (assigned members, spares).

    The first M candidates ARE the quorum — ring order already encodes
    affinity-then-load placement, so member 0 is the replica a plain
    request would have landed on. The rest are the spare pool legs retry
    and resume onto."""
    return candidates[:m], candidates[m:]


@dataclass
class QuorumLeg:
    """One member's outcome: content + usage when served, the degrade
    reason when dropped. ``replica`` is the cell that finished the leg
    (after any retry/resume it may differ from the assignment)."""

    index: int
    replica: str = ""
    content: str = ""
    usage: dict[str, Any] | None = None
    body: dict[str, Any] | None = None   # full completion (non-streaming)
    ok: bool = False
    resumed: bool = False
    degraded_reason: str | None = None
    error: str = ""
    status_code: int = 0                 # last upstream status (diagnostics)
    tried: list[str] = field(default_factory=list)


def _drop(leg: QuorumLeg, reason: str, rid: str, error: str = "") -> None:
    """Drop one member from the quorum: the leg's loss is counted and
    recorded, the request lives on with the survivors."""
    leg.degraded_reason = reason
    if error:
        leg.error = error[:200]
    QUORUM_DEGRADED.inc(reason=reason)
    RECORDER.record("quorum-member-degraded", rid=rid, loop="router",
                    member=leg.index, replica=leg.replica or "none",
                    reason=reason, **({"error": leg.error}
                                      if leg.error else {}))


def _next_candidate(leg: QuorumLeg, assigned: str,
                    spares: list[str], replicas: dict[str, Any]) -> Any:
    """The leg's next untried cell: its ring assignment first, then the
    shared spare pool (popped — a spare serves at most one leg). Spares
    whose breaker is open are skipped, not burned."""
    if assigned not in leg.tried:
        leg.tried.append(assigned)
        r = replicas[assigned]
        if r.breaker.allow():
            return r
    while spares:
        name = spares.pop(0)
        if name in leg.tried:
            continue
        leg.tried.append(name)
        r = replicas[name]
        if r.breaker.allow():
            return r
    return None


def summarize(m: int, legs: list[QuorumLeg]) -> tuple[str, list[QuorumLeg]]:
    """(outcome, served legs) for the request-level counter/headers:
    ``full`` when every member contributed, ``degraded`` for a strict
    non-empty subset, ``failed`` when nothing came back."""
    served = [leg for leg in legs if leg.ok and leg.content]
    if len(served) == m:
        return "full", served
    if served:
        return "degraded", served
    return "failed", served


def quorum_headers(m: int, legs: list[QuorumLeg],
                   outcome: str) -> dict[str, str]:
    """Response headers carrying the quorum's shape (openapi.yaml): how
    many members were asked, how many answered, which cells served, and
    the first degrade reason when any member was dropped."""
    served = [leg for leg in legs if leg.ok and leg.content]
    out = {
        "X-Quorum-Members": str(m),
        "X-Quorum-Served": str(len(served)),
        "X-Quorum-Replicas": ",".join(leg.replica for leg in served),
    }
    reasons = [leg.degraded_reason for leg in legs if leg.degraded_reason]
    if outcome != "full" and reasons:
        out["X-Quorum-Degraded"] = reasons[0]
    return out


async def _leg_complete(leg: QuorumLeg, assigned: str, spares: list[str],
                        replicas: dict[str, Any], body: dict[str, Any],
                        headers: dict[str, str], deadline: float,
                        rid: str) -> None:
    """Run one non-streaming member leg to completion or drop. Failure
    policy mirrors the router's single-request path: 5xx/transport moves
    to the next spare (the replica already burned its own retry budget),
    4xx is replica-independent and ends the leg immediately."""
    while True:
        r = _next_candidate(leg, assigned, spares, replicas)
        if r is None:
            _drop(leg, "member_failed", rid,
                  leg.error or "no spare candidate")
            return
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            _drop(leg, "member_failed", rid, "deadline exhausted")
            return
        leg.replica = r.name
        r.inflight += 1
        r.requests += 1
        try:
            faults.fire("quorum.leg")
            result = await r.backend.complete(body, headers, remaining)
        except BackendError as e:
            leg.status_code = e.status_code
            leg.error = str(e)[:200]
            if e.status_code < 500:
                # Client errors are replica-independent: retrying spares
                # cannot help. Keep the body so an all-4xx quorum relays
                # the real error, not a 502 wrapper.
                leg.body = e.body
                _drop(leg, "member_failed", rid, str(e))
                return
            r.breaker.record_failure()
            continue
        except Exception as e:  # fault-injection / transport surprises
            leg.error = str(e)[:200]
            r.breaker.record_failure()
            continue
        finally:
            r.inflight -= 1
        leg.status_code = result.status_code
        if result.status_code >= 500:
            leg.error = str(result.body)[:200]
            r.breaker.record_failure()
            continue
        r.breaker.record_success()
        if result.status_code >= 400:
            leg.body = result.body
            _drop(leg, "member_failed", rid, str(result.body))
            return
        content = oai.extract_content(result.body)
        if not content:
            _drop(leg, "no_content", rid)
            return
        leg.ok = True
        leg.content = content
        leg.usage = result.usage if isinstance(result.usage, dict) else None
        leg.body = result.body
        return


async def quorum_complete(
    replicas: dict[str, Any],
    candidates: list[str],
    m: int,
    body: dict[str, Any],
    headers: dict[str, str],
    deadline: float,
    rid: str,
    separator: str,
) -> tuple[dict[str, Any], int, dict[str, str]]:
    """Non-streaming quorum: fan the request to M member legs, combine
    the survivors' answers into ONE chat.completion. Returns
    ``(response body, status, extra headers)``."""
    assigned, spare_list = choose_members(candidates, m)
    spares = list(spare_list)
    legs = [QuorumLeg(index=i) for i in range(m)]
    RECORDER.record("quorum-fanout", rid=rid, loop="router", members=m,
                    replicas=",".join(assigned), stream=False)
    coros = []
    for i, leg in enumerate(legs):
        if i < len(assigned):
            coros.append(_leg_complete(leg, assigned[i], spares, replicas,
                                       body, headers, deadline, rid))
        else:
            _drop(leg, "member_failed", rid, "no replica for member")
    await asyncio.gather(*coros)
    outcome, served = summarize(m, legs)
    QUORUM_REQUESTS.inc(outcome=outcome)
    RECORDER.record("quorum-served", rid=rid, loop="router",
                    outcome=outcome, served=len(served), members=m)
    hdrs = quorum_headers(m, legs, outcome)
    if outcome == "failed":
        # Relay a replica-independent client error as itself (one 4xx,
        # not a 502 hiding it); otherwise the PR 12 proxy_error contract.
        client_err = next((leg for leg in legs
                           if 400 <= leg.status_code < 500), None)
        if client_err is not None and client_err.body is not None:
            return client_err.body, client_err.status_code, hdrs
        return (oai.error_body(
            "quorum failed: no member produced content "
            f"(members={m}, last error: {legs[-1].error or 'none'})"),
            502, hdrs)
    first = served[0].body or {}
    combined = separator.join(leg.content for leg in served)
    return ({
        "id": first.get("id", oai.new_request_id()),
        "object": "chat.completion",
        "created": first.get("created", oai.now()),
        "model": first.get("model", QUORUM_MODEL_NAME),
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": combined},
            "finish_reason": "stop",
        }],
        "usage": oai.sum_usage([leg.usage for leg in served]),
        "quorum": {
            "members": m,
            "served": len(served),
            "replicas": [leg.replica for leg in served],
            "degraded": [
                {"member": leg.index, "reason": leg.degraded_reason}
                for leg in legs if leg.degraded_reason
            ],
        },
    }, 200, hdrs)


def _is_role_only(ev: Any) -> bool:
    if not isinstance(ev, dict) or ev.get("id") == "error":
        return False
    if "usage" in ev:
        return False
    choices = ev.get("choices") or []
    if len(choices) != 1 or choices[0].get("finish_reason"):
        return False
    delta = choices[0].get("delta") or {}
    return bool(delta) and set(delta) <= {"role"}


def _is_error_chunk(ev: Any) -> bool:
    if not isinstance(ev, dict):
        return False
    if ev.get("id") == "error":
        return True
    choices = ev.get("choices") or []
    return bool(choices) and choices[0].get("finish_reason") == "error"


async def _aclose_quiet(stream: Any) -> None:
    aclose = getattr(stream, "aclose", None)
    if aclose is None:
        return
    try:
        await aclose()
    except Exception:
        pass


async def _pump_leg(leg: QuorumLeg, assigned: str, spares: list[str],
                    replicas: dict[str, Any], base_body: dict[str, Any],
                    headers: dict[str, str], deadline: float, rid: str,
                    queue: asyncio.Queue, journal_limit: int) -> None:
    """Drive one streaming member leg, pushing ``(index, text)`` deltas
    into the merge queue and ``(index, _DONE)`` at the end (served or
    dropped — the merger reads the leg's fields).

    The leg journals its delivered token ids (``qt_tokens``, requested
    via ``stream_token_ids``) so a mid-stream death re-submits on a spare
    with ``resume_tokens``/``resume_chars`` — the PR 19 token-exact
    resume, scoped to one member. A replay-guard refusal (the structured
    ``qt_error: "resume_diverged"`` marker) drops the member immediately:
    retrying spares cannot help when the guard itself refused."""
    ids: list[int] = []
    unresumable = False
    started = False
    try:
        while True:
            r = _next_candidate(leg, assigned, spares, replicas)
            if r is None:
                _drop(leg, "stream_broken" if started else "member_failed",
                      rid, leg.error or "no spare candidate")
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                _drop(leg, "stream_broken" if started else "member_failed",
                      rid, "deadline exhausted")
                return
            b = dict(base_body)
            b["stream"] = True
            b["stream_token_ids"] = True
            b.pop("resume_tokens", None)
            b.pop("resume_chars", None)
            if started:
                if unresumable or not ids:
                    # Delivered content the journal cannot cover: a resume
                    # would drop or duplicate member deltas the client
                    # already has — drop the member instead.
                    _drop(leg, "stream_broken", rid,
                          leg.error or "journal cannot cover the stream")
                    return
                b["resume_tokens"] = list(ids)
                b["resume_chars"] = len(leg.content)
            leg.replica = r.name
            r.inflight += 1
            r.requests += 1
            stream = None
            broke: str = ""
            finished = False
            try:
                faults.fire("quorum.leg")
                stream = r.backend.stream(b, headers, remaining)
                async for ev in stream:
                    if not isinstance(ev, dict):
                        continue
                    qt = ev.pop("qt_tokens", None)
                    if ev.get("qt_error") == "resume_diverged":
                        r.breaker.record_success()
                        _drop(leg, "resume_diverged", rid,
                              oai.extract_delta_content(ev))
                        return
                    if _is_error_chunk(ev):
                        # The replica converted its own failure into the
                        # error-chunk contract — for the quorum that is a
                        # leg death, resumable like a transport one.
                        broke = oai.extract_delta_content(ev) or "error chunk"
                        break
                    if _is_role_only(ev):
                        continue
                    usage = ev.get("usage")
                    if isinstance(usage, dict):
                        leg.usage = usage
                    text = oai.extract_delta_content(ev)
                    if text:
                        started = True
                        leg.content += text
                        if qt:
                            ids.extend(qt)
                            if len(ids) > journal_limit:
                                unresumable = True
                        else:
                            unresumable = True
                        await queue.put((leg.index, text))
                    fin = next((c.get("finish_reason")
                                for c in ev.get("choices") or []
                                if isinstance(c, dict)
                                and c.get("finish_reason")), None)
                    if fin == "parked":
                        # Drain-park: the cell is shedding, not failing —
                        # resume on a spare without burning the breaker.
                        broke = "stream parked"
                        break
                    if fin:
                        finished = True
            except Exception as e:
                broke = str(e)[:200] or type(e).__name__
            finally:
                r.inflight -= 1
                if stream is not None:
                    await _aclose_quiet(stream)
            if finished:
                r.breaker.record_success()
                if not leg.content:
                    _drop(leg, "no_content", rid)
                    return
                leg.ok = True
                leg.resumed = len(leg.tried) > 1
                return
            leg.error = (broke or "stream ended without finish")[:200]
            if broke != "stream parked":
                r.breaker.record_failure()
            RECORDER.record("quorum-leg-broken", rid=rid, loop="router",
                            member=leg.index, replica=r.name,
                            error=leg.error, resumable=bool(
                                not started or (ids and not unresumable)))
            # Loop: next candidate, token-exact resume when started.
    finally:
        await queue.put((leg.index, _DONE))


async def quorum_stream(
    replicas: dict[str, Any],
    candidates: list[str],
    m: int,
    body: dict[str, Any],
    headers: dict[str, str],
    deadline: float,
    rid: str,
    separator: str,
    journal_limit: int = 4096,
    suppress_individual: bool = False,
) -> AsyncIterator[bytes]:
    """Streaming quorum: M member legs merge live into one SSE stream
    under the parallel-proxy chunk contract, then the final combined
    chunk joins the survivors. Member deaths degrade mid-flight (the
    dropped member's delivered deltas stay — they cannot be unsent — and
    its partial answer joins the combine)."""
    assigned, spare_list = choose_members(candidates, m)
    spares = list(spare_list)
    legs = [QuorumLeg(index=i) for i in range(m)]
    RECORDER.record("quorum-fanout", rid=rid, loop="router", members=m,
                    replicas=",".join(assigned), stream=True)
    yield sse.encode_event(oai.role_chunk(QUORUM_MODEL_NAME))

    queue: asyncio.Queue = asyncio.Queue()
    tasks = []
    for i, leg in enumerate(legs):
        if i < len(assigned):
            tasks.append(asyncio.create_task(_pump_leg(
                leg, assigned[i], spares, replicas, body, headers,
                deadline, rid, queue, journal_limit)))
        else:
            _drop(leg, "member_failed", rid, "no replica for member")
    try:
        finished = 0
        while finished < len(tasks):
            index, item = await queue.get()
            if item is _DONE:
                finished += 1
                continue
            if not suppress_individual:
                yield sse.encode_event(oai.content_chunk(
                    item, model=QUORUM_MODEL_NAME, backend_index=index))
    finally:
        for t in tasks:
            t.cancel()

    outcome, served = summarize(m, legs)
    QUORUM_REQUESTS.inc(outcome=outcome)
    RECORDER.record("quorum-served", rid=rid, loop="router",
                    outcome=outcome, served=len(served), members=m)
    # Dropped members with partial content still join the combine: their
    # deltas already reached the client, and a half answer from a killed
    # cell beats pretending it said nothing.
    partial = [leg for leg in legs
               if leg.content and not leg.ok]
    contributions = sorted(served + partial, key=lambda leg: leg.index)
    if contributions:
        combined = separator.join(leg.content for leg in contributions)
        yield sse.encode_event(oai.final_chunk(combined,
                                               model=QUORUM_MODEL_NAME))
    else:
        yield sse.encode_event(oai.error_chunk(
            "Error: quorum failed: no member produced content",
            model=QUORUM_MODEL_NAME))
    yield sse.encode_done()
