"""Multi-replica serving tier: a prefix-affinity router across engine cells.

The third tier of the MPMD topology (docs/scaling.md): device groups make
one engine cell (``disagg=P+D``), engine cells make one replica process,
and this package is the tier over N replicas — a standalone asyncio router
(``python -m quorum_tpu.router``) speaking the same OpenAI surface as
``server/app.py`` and placing each request by **conversation-prefix
affinity**: the tokenized prompt's chunk-trie root hashes through
bounded-load consistent hashing, so a conversation's turns land on the
replica whose PR 3 prefix store already holds its KV prefix. Affinity —
not raw fan-out — is what converts extra replicas into throughput
(Jupiter's collaborative-inference lesson, PAPERS.md).

Layout:
  ring.py       bounded-load consistent hashing over replica names
  affinity.py   conversation/chain → ring key (prefix-stable hashing)
  replica.py    per-replica HttpBackend + Breaker, /ready rotation,
                prefix-chunk migration between replicas
  app.py        the router ASGI app (chat surface, failover, metrics)
  fake_replica  a jax-free scripted replica process (bench baseline,
                chaos replica-kill drill, tests)
"""

from quorum_tpu.router.app import (  # noqa: F401
    RouterConfig,
    build_replica_set,
    create_router_app,
)
from quorum_tpu.router.replica import Replica, ReplicaSet  # noqa: F401
from quorum_tpu.router.ring import BoundedLoadRing, hash_key  # noqa: F401
