"""``python -m quorum_tpu.router`` — run the prefix-affinity router.

Config-driven replica list, either inline::

    python -m quorum_tpu.router --port 8080 \\
        --replicas http://host-a:8000,http://host-b:8000

or from a YAML file (``--config router.yaml``)::

    replicas:
      - {name: cell-a, url: "http://host-a:8000"}
      - {name: cell-b, url: "http://host-b:8000"}
    policy: affinity          # or random (the bench baseline)
    affinity_chunk: 64
    retries: 1
    ready_interval: 2.0
    migrate_on_rotation: true

The router is pure host/HTTP code — no jax, no device state; it runs on
any box that can reach the replicas. See docs/scaling.md ("Replica tier").
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from quorum_tpu.router.app import RouterConfig, create_router_app
from quorum_tpu.server.serve import serve


def load_router_config(path: str | None,
                       replicas_arg: str | None,
                       **overrides) -> RouterConfig:
    raw: dict = {}
    if path:
        import yaml

        with open(path) as f:
            loaded = yaml.safe_load(f)
        if not isinstance(loaded, dict):
            raise ValueError(f"router config {path} is not a mapping")
        raw = loaded.get("router", loaded)
    if replicas_arg:
        raw["replicas"] = [u.strip() for u in replicas_arg.split(",")
                           if u.strip()]
    for k, v in overrides.items():
        if v is not None:
            raw[k] = v
    return RouterConfig.from_dict(raw)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="quorum_tpu prefix-affinity replica router")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--config", default=None,
                        help="router YAML (replicas/policy/… keys)")
    parser.add_argument("--replicas", default=None,
                        help="comma-separated replica base URLs "
                             "(overrides the config file's list)")
    parser.add_argument("--policy", default=None,
                        choices=("affinity", "random"))
    parser.add_argument("--affinity-chunk", type=int, default=None)
    parser.add_argument("--retries", type=int, default=None)
    parser.add_argument("--ready-interval", type=float, default=None)
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(levelname)s:%(asctime)s:%(name)s: %(message)s")
    cfg = load_router_config(
        args.config, args.replicas,
        policy=args.policy, affinity_chunk=args.affinity_chunk,
        retries=args.retries, ready_interval=args.ready_interval)
    app = create_router_app(cfg)
    logging.getLogger(__name__).info(
        "router over %d replicas (policy=%s): %s",
        len(cfg.replicas), cfg.policy,
        ", ".join(f"{n}={u}" for n, u in cfg.replicas))
    try:
        asyncio.run(serve(app, args.host, args.port))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
