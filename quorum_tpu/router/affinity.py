"""Conversation → ring key: the prefix-affinity hash.

A conversation's turns must all land on the SAME replica for the PR 3
prefix store to hit past one box — and chat clients rebuild the prompt by
appending, so every turn's prompt *starts with* the first turn's prompt.
The stable identity of a conversation is therefore the ROOT of its
chunk-trie path: tokenize the rendered prompt exactly the way
``cache/prefix_store.py`` chunks it (fixed-size token chunks over the
rendered chat template) and hash the first chunk. Turn 2..N extend the
path; the root edge never changes, so the key never changes.

Two consequences, both deliberate:

  - Conversations sharing a long system prompt share a root chunk and
    co-locate — which is exactly where shared-prefix cache hits live. The
    bounded-load ring (``ring.py``) keeps such a hot key range from
    melting one replica.
  - The router's tokenizer need not match the replicas' (a replica may
    serve a real HF vocab): the key only has to be a *stable, prefix-
    preserving* function of the conversation, and the deterministic byte
    tokenizer is that for any replica tokenizer.
"""

from __future__ import annotations

from typing import Any

from quorum_tpu.engine.tokenizer import ByteTokenizer
from quorum_tpu.oai import flatten_content
from quorum_tpu.router.ring import hash_key

# Affinity chunk: tokens of rendered prompt hashed as the conversation key.
# Mirrors the prefix store's chunk granularity in spirit; the router knob
# (--affinity-chunk) tunes it. 64 byte-tokens ≈ the opening system line.
DEFAULT_AFFINITY_CHUNK = 64

# One byte-level tokenizer for the router process (vocab 259 = specials +
# all 256 bytes — chunk boundaries then cut the SAME byte positions for
# every prompt regardless of any replica's model vocab).
_TOKENIZER = ByteTokenizer(259)


def conversation_tokens(body: dict[str, Any]) -> list[int]:
    """Byte tokens of the conversation's IMMUTABLE head: the rendered
    messages up to and including the first user message. Later turns
    append messages, so this head never changes — and because chat
    rendering is line-by-line, its rendered text is a byte-PREFIX of every
    later turn's full rendered prompt, i.e. the root of the conversation's
    chunk-trie path. (Keying the full prompt truncated to one chunk is NOT
    stable: a first turn shorter than the chunk grows past the truncation
    point on turn two and changes its own key.)"""
    messages = body.get("messages")
    if isinstance(messages, list) and messages:
        head = []
        for m in messages:
            if not isinstance(m, dict):
                continue
            head.append(m)
            if m.get("role") == "user":
                break
        # render_chat appends the "assistant:" generation cue — strip it:
        # the head must be a byte-prefix of the FULL rendered prompt,
        # where the next line after the first user message is a history
        # message, not the cue.
        text = "\n".join(
            _TOKENIZER.render_chat(head).splitlines()[:-1]) + "\n"
    else:
        # Legacy /completions-shaped bodies: the raw prompt is the
        # conversation.
        text = flatten_content(body.get("prompt"))
    return _TOKENIZER.encode(text)


def _key_of_ids(ids: list[int], chunk_tokens: int) -> int:
    """Hash of the first ``chunk_tokens`` ids — ONE packing (4 bytes per
    id, covering any real vocab) shared by the conversation and chain
    keys, so a chain exported by a byte-tokenizing replica re-keys to the
    same ring position as the conversation that grew it."""
    head = ids[:max(1, int(chunk_tokens))]
    return hash_key(b"".join(int(t).to_bytes(4, "big") for t in head))


def conversation_key(body: dict[str, Any],
                     chunk_tokens: int = DEFAULT_AFFINITY_CHUNK) -> int:
    """Ring position of the conversation: hash of the first
    ``chunk_tokens`` tokens (the chunk-trie root edge); prompts shorter
    than one chunk hash whole, so tiny prompts still spread."""
    return _key_of_ids(conversation_tokens(body), chunk_tokens)


def chain_key(tokens: list[int],
              chunk_tokens: int = DEFAULT_AFFINITY_CHUNK) -> int:
    """Ring position of an exported prefix chunk chain (migration
    regrouping). The conversation key hashes only the conversation's
    HEAD (up to the first user message) — which may be SHORTER than one
    affinity chunk — so hashing the chain's first chunk blindly would
    mis-key every short-head conversation and seed its chains on a
    replica its next turn never routes to. With byte-tokenizing replicas
    (the default) the chain's ids decode back to the rendered text
    exactly, so the head boundary is recoverable: decode the chain's
    opening, cut at the first ``\\nassistant:`` line break (the rendered
    prompt's first post-head line — history reply or generation cue),
    and re-key the head's own ids. When the boundary is not found (a
    custom replica vocab whose ids fold differently, or a first message
    containing the delimiter) fall back to the first-chunk hash — still
    deterministic, merely unaligned."""
    head = ids = list(tokens)
    text = _TOKENIZER.decode(ids[: 4 * max(1, int(chunk_tokens))])
    cut = text.find("\nassistant:")
    if cut >= 0:
        head = _TOKENIZER.encode(text[: cut + 1])
        if ids[: len(head)] != head:
            head = ids  # decode/encode disagree: not byte-token ids
    return _key_of_ids(head, chunk_tokens)
