"""The router ASGI app: the OpenAI surface, placed by prefix affinity.

Accepts the same ``POST /chat/completions`` (+ ``/v1`` alias) surface as
``server/app.py`` and places each request on a replica by conversation-
prefix affinity (``affinity.py`` key → ``ring.py`` bounded-load consistent
hashing), so a conversation's turns land where its KV prefix already lives.
Everything upstream-facing reuses the PR 4 HTTP machinery: per-replica
:class:`HttpBackend` (pooled clients, capped-exponential retries,
Retry-After pacing), per-replica :class:`Breaker` for failover, ``/ready``
polling for ring rotation with prefix migration (``replica.py``).

Failover contract (the one the HTTP backend's streaming retry boundary
makes safe): a replica that fails BEFORE its 2xx event stream opens —
connect error, 5xx, 503 shed — moves the request to the next ring
candidate; once a stream is open, tokens are on the client's wire and a
mid-stream failure surfaces as an SSE error chunk, never a re-send
(double-delivered tokens are a correctness bug, not a retry). Non-streaming
requests failover on any 5xx outcome. 4xx outcomes relay immediately — a
client error is the same on every replica.

SSE pass-through preserves TTFT: upstream events re-encode and flush
frame-by-frame as they arrive (no buffering, no coalescing beyond the
upstream's own), with the router adding only its hash lookup (~µs) to the
first-byte path.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

import httpx

from quorum_tpu import oai, sse
from quorum_tpu.backends.base import BackendError
from quorum_tpu.observability import (
    METRICS,
    ROUTER_AFFINITY_HITS,
    ROUTER_AFFINITY_MISSES,
    ROUTER_FAILOVERS,
    ROUTER_REQUESTS,
)
from quorum_tpu.router import affinity
from quorum_tpu.router.replica import Replica, ReplicaSet
from quorum_tpu.server.asgi import (
    App,
    JSONResponse,
    Request,
    Response,
    StreamingResponse,
)
from quorum_tpu.telemetry.recorder import RECORDER

logger = logging.getLogger(__name__)

# Response headers recomputed by this hop, never relayed from upstream.
_PASSTHROUGH_SKIP = {"content-length", "content-type", "transfer-encoding",
                     "content-encoding", "connection"}


class _StreamGuard:
    """Wraps the passthrough generator so the replica's in-flight count
    decrements EXACTLY once no matter how the stream ends — exhaustion,
    an exception, or ``aclose()`` on a generator whose body never ran
    (PEP 525: closing an unstarted async generator skips its ``finally``,
    which is how a client disconnecting before the response starts would
    otherwise leak ``inflight`` forever and bounded-load placement would
    drift all traffic off a healthy replica)."""

    def __init__(self, gen, dec):
        self._gen = gen
        self._dec = dec

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self._gen.__anext__()
        except StopAsyncIteration:
            self._dec()
            raise
        except BaseException:
            self._dec()
            raise

    async def aclose(self):
        self._dec()
        aclose = getattr(self._gen, "aclose", None)
        if aclose is not None:
            await aclose()


@dataclass
class RouterConfig:
    """Config for one router process (``python -m quorum_tpu.router``)."""

    replicas: list[tuple[str, str]] = field(default_factory=list)
    policy: str = "affinity"            # or "random" (the bench baseline)
    affinity_chunk: int = affinity.DEFAULT_AFFINITY_CHUNK
    retries: int = 1                    # per-replica HttpBackend retries
    timeout: float = 120.0              # default request budget (seconds)
    ready_interval: float = 2.0         # /ready poll period; <=0 disables
    migrate_on_rotation: bool = True
    vnodes: int = 64
    load_factor: float = 1.25
    breaker_threshold: int = 3
    breaker_window: float = 30.0
    breaker_cooldown: float = 5.0

    def __post_init__(self) -> None:
        if self.policy not in ("affinity", "random"):
            raise ValueError(
                f"unknown routing policy {self.policy!r} "
                "(affinity or random)")
        if not self.replicas:
            raise ValueError("router config names no replicas")

    @classmethod
    def from_dict(cls, raw: dict) -> "RouterConfig":
        replicas = []
        for i, entry in enumerate(raw.get("replicas") or []):
            if isinstance(entry, str):
                replicas.append((f"replica-{i}", entry))
            elif isinstance(entry, dict) and entry.get("url"):
                replicas.append(
                    (str(entry.get("name") or f"replica-{i}"),
                     str(entry["url"])))
            else:
                raise ValueError(f"bad replica entry: {entry!r}")
        kwargs = {k: raw[k] for k in (
            "policy", "affinity_chunk", "retries", "timeout",
            "ready_interval", "migrate_on_rotation", "vnodes",
            "load_factor", "breaker_threshold", "breaker_window",
            "breaker_cooldown") if k in raw}
        return cls(replicas=replicas, **kwargs)


def build_replica_set(cfg: RouterConfig,
                      client: httpx.AsyncClient | None = None,
                      control_client: httpx.AsyncClient | None = None,
                      ) -> ReplicaSet:
    from quorum_tpu.breaker import Breaker

    replicas = [
        Replica(name, url, retries=cfg.retries, client=client,
                breaker=Breaker(threshold=cfg.breaker_threshold,
                                window=cfg.breaker_window,
                                cooldown=cfg.breaker_cooldown))
        for name, url in cfg.replicas
    ]
    return ReplicaSet(
        replicas,
        vnodes=cfg.vnodes, load_factor=cfg.load_factor,
        affinity_chunk=cfg.affinity_chunk,
        ready_interval=cfg.ready_interval,
        migrate_on_rotation=cfg.migrate_on_rotation,
        control_client=control_client)


def create_router_app(cfg: RouterConfig,
                      replica_set: ReplicaSet | None = None,
                      client: httpx.AsyncClient | None = None,
                      control_client: httpx.AsyncClient | None = None,
                      ) -> App:
    """Build the router ASGI app. Tests inject a shared ``client``
    (e.g. an ASGITransport-backed one) or a prebuilt ``replica_set``."""
    mgr = replica_set if replica_set is not None else build_replica_set(
        cfg, client=client, control_client=control_client)

    app = App()
    app.state["router_config"] = cfg
    app.state["replica_set"] = mgr
    started = time.monotonic()

    def _forward_headers(request: Request) -> dict[str, str]:
        """Relay the client's headers minus host (the reference proxy's
        contract) — auth passes through for the REPLICA to enforce; the
        router holds no credential policy of its own."""
        return {k: v for k, v in request.headers.items()
                if k.lower() != "host"}

    def _shed_response() -> JSONResponse:
        retry = max([r.breaker.retry_after()
                     for r in mgr.replicas.values()] or [1.0])
        return JSONResponse(
            {"error": {"message": "no replica available "
                       "(all rotated out, breaker-open, or unreachable)",
                       "type": "overloaded_error"}},
            status_code=503,
            headers={"Retry-After": str(max(1, int(retry)))})

    def _pick(body: dict) -> tuple[str | None, list[str]]:
        """(affinity primary, candidate order) under the active policy."""
        if cfg.policy == "random":
            members = sorted(mgr.ring.members)
            random.shuffle(members)
            return None, members
        key = affinity.conversation_key(body, cfg.affinity_chunk)
        return mgr.placement(key)

    def _score_affinity(primary: str | None, served_by: str) -> None:
        if primary is not None and served_by == primary:
            ROUTER_AFFINITY_HITS.inc()
        else:
            ROUTER_AFFINITY_MISSES.inc()

    @app.route("POST", "/chat/completions", "/v1/chat/completions")
    async def chat_completions(request: Request) -> Response:
        await mgr.ensure_poller()
        rid = f"req-{uuid.uuid4().hex[:16]}"
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"Invalid JSON body: {e}",
                           "type": "invalid_request_error"}},
                status_code=400)
        headers = _forward_headers(request)
        is_streaming = bool(body.get("stream", False))
        # The timeout knob is READ, not consumed — the replica's server
        # pops and enforces it; the router only bounds its own HTTP waits.
        try:
            timeout = float(body.get("timeout") or cfg.timeout)
        except (TypeError, ValueError):
            timeout = cfg.timeout
        deadline = time.monotonic() + timeout

        primary, candidates = _pick(body)
        if not candidates:
            return _shed_response()

        last_err: BackendError | None = None
        last_result = None
        for name in candidates:
            r = mgr.replicas[name]
            if not r.breaker.allow():
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            r.inflight += 1
            r.requests += 1
            decremented = [False]
            guard_owns = False  # True once a _StreamGuard took ownership

            def dec(r=r, flag=decremented):
                if not flag[0]:
                    flag[0] = True
                    r.inflight -= 1

            try:
                if is_streaming:
                    stream = r.backend.stream(body, headers, remaining)
                    try:
                        first = await stream.__anext__()
                    except StopAsyncIteration:
                        first = None
                    # 2xx stream open (or cleanly empty): committed.
                    r.breaker.record_success()
                    ROUTER_REQUESTS.inc(replica=name, outcome="ok")
                    _score_affinity(primary, name)
                    RECORDER.record("router-route", rid=rid, loop="router",
                                    replica=name, stream=True,
                                    affinity=bool(primary == name))
                    resp = StreamingResponse(_StreamGuard(
                        _passthrough(r, rid, first, stream), dec))
                    guard_owns = True
                    resp.headers["X-Routed-To"] = name
                    resp.headers["X-Request-Id"] = rid
                    return resp
                result = await r.backend.complete(body, headers, remaining)
                if result.status_code >= 500:
                    # The replica already burned its own retry budget;
                    # the router's move is the NEXT replica.
                    r.breaker.record_failure()
                    ROUTER_FAILOVERS.inc(replica=name)
                    ROUTER_REQUESTS.inc(replica=name, outcome="failover")
                    RECORDER.record("router-failover", rid=rid,
                                    loop="router", replica=name,
                                    status=result.status_code)
                    last_result = result
                    continue
                r.breaker.record_success()
                ROUTER_REQUESTS.inc(replica=name, outcome="ok")
                _score_affinity(primary, name)
                RECORDER.record("router-route", rid=rid, loop="router",
                                replica=name, stream=False,
                                affinity=bool(primary == name))
                resp_headers = {
                    k: v for k, v in result.headers.items()
                    if k.lower() not in _PASSTHROUGH_SKIP}
                resp_headers["X-Routed-To"] = name
                resp_headers["X-Request-Id"] = rid
                return JSONResponse(result.body,
                                    status_code=result.status_code,
                                    headers=resp_headers)
            except BackendError as e:
                if e.status_code < 500:
                    # Client errors are replica-independent: relay
                    # (outcome "ok" — a faithful 4xx relay is the same
                    # series on the stream and non-stream paths).
                    ROUTER_REQUESTS.inc(replica=name, outcome="ok")
                    resp_headers = dict(e.headers)
                    resp_headers["X-Routed-To"] = name
                    return JSONResponse(e.body, status_code=e.status_code,
                                        headers=resp_headers)
                r.breaker.record_failure()
                ROUTER_FAILOVERS.inc(replica=name)
                ROUTER_REQUESTS.inc(replica=name, outcome="failover")
                RECORDER.record("router-failover", rid=rid, loop="router",
                                replica=name, status=e.status_code)
                last_err = e
                continue
            finally:
                # Streaming success hands the single decrement to the
                # _StreamGuard; every other exit (non-streaming, any
                # failure, even a non-BackendError) releases here. The
                # once-guard keeps the two hand-offs from double-counting.
                if not guard_owns:
                    dec()
        # Exhausted every candidate: relay the terminal failure with its
        # own status/Retry-After, else shed.
        if last_err is not None:
            ROUTER_REQUESTS.inc(replica="none", outcome="error")
            return JSONResponse(last_err.body,
                                status_code=last_err.status_code,
                                headers=last_err.headers)
        if last_result is not None:
            ROUTER_REQUESTS.inc(replica="none", outcome="error")
            resp_headers = {k: v for k, v in last_result.headers.items()
                            if k.lower() not in _PASSTHROUGH_SKIP}
            return JSONResponse(last_result.body,
                                status_code=last_result.status_code,
                                headers=resp_headers)
        return _shed_response()

    async def _passthrough(
        r: Replica, rid: str,
        first: dict[str, Any] | None,
        rest: AsyncIterator[dict[str, Any]],
    ) -> AsyncIterator[bytes]:
        """SSE pass-through: re-encode upstream events frame-by-frame (the
        h11 server flushes each yield — TTFT rides the first upstream
        event untouched). Mid-stream failure → error chunk + [DONE],
        NEVER a failover (tokens are already on the wire). The in-flight
        decrement belongs to the wrapping :class:`_StreamGuard`, which
        fires even when this body never runs."""
        model = "unknown"
        try:
            if first is not None:
                model = first.get("model") or model
                yield sse.encode_event(first)
            async for event in rest:
                yield sse.encode_event(event)
        except BackendError as e:
            r.breaker.record_failure()
            RECORDER.record("router-stream-broken", rid=rid, loop="router",
                            replica=r.name, error=str(e)[:200])
            yield sse.encode_event(
                oai.error_chunk(f"Backend failed: {e}", model=model))
        yield sse.encode_done()

    @app.route("GET", "/health", "/v1/health")
    async def health(request: Request) -> Response:
        await mgr.ensure_poller()
        rows = [r.state() | {"in_ring": r.name in mgr.ring}
                for r in mgr.replicas.values()]
        in_ring = sum(1 for row in rows if row["in_ring"])
        if in_ring == len(rows):
            status = "healthy"
        elif in_ring:
            status = "degraded"
        else:
            status = "unhealthy"
        body = {"status": status, "role": "router", "replicas": rows}
        if status == "unhealthy":
            return JSONResponse(body, status_code=503,
                                headers={"Retry-After": "5"})
        return JSONResponse(body)

    @app.route("GET", "/ready", "/v1/ready")
    async def ready(request: Request) -> Response:
        await mgr.ensure_poller()
        if len(mgr.ring):
            return JSONResponse({"status": "ready"})
        return JSONResponse(
            {"status": "unready", "reason": "no replica in the ring"},
            status_code=503, headers={"Retry-After": "5"})

    @app.route("GET", "/metrics", "/v1/metrics")
    async def metrics(request: Request) -> Response:
        lines = [
            "# TYPE quorum_tpu_uptime_seconds gauge",
            f"quorum_tpu_uptime_seconds {time.monotonic() - started:.3f}",
            "# TYPE quorum_tpu_router_replica_up gauge",
        ]
        for name, r in sorted(mgr.replicas.items()):
            up = 1 if name in mgr.ring else 0
            lines.append(
                f'quorum_tpu_router_replica_up{{replica="{name}"}} {up}')
        lines.append("# TYPE quorum_tpu_router_replicas_in_ring gauge")
        lines.append(
            f"quorum_tpu_router_replicas_in_ring {len(mgr.ring)}")
        lines.append("# TYPE quorum_tpu_router_inflight gauge")
        lines.append(
            f"quorum_tpu_router_inflight "
            f"{sum(r.inflight for r in mgr.replicas.values())}")
        lines.extend(METRICS.expose())
        return Response(("\n".join(lines) + "\n").encode(),
                        media_type="text/plain; version=0.0.4")

    @app.route("GET", "/router/replicas", "/v1/router/replicas")
    async def replicas(request: Request) -> Response:
        """Debug surface: live placement state per replica."""
        await mgr.ensure_poller()
        return JSONResponse({
            "policy": cfg.policy,
            "affinity_chunk": cfg.affinity_chunk,
            "in_ring": sorted(mgr.ring.members),
            "migrations": mgr.n_migrations,
            "replicas": [r.state() | {"in_ring": r.name in mgr.ring}
                         for r in mgr.replicas.values()],
        })

    @app.route("POST", "/router/migrate", "/v1/router/migrate")
    async def migrate(request: Request) -> Response:
        """Operator-triggered prefix migration: drain ``?from=NAME``'s hot
        chains to their current ring homes (or pin to ``?to=NAME``) ahead
        of a planned rotation — the same path the /ready poller drives
        automatically when a replica sheds."""
        src = request.query_params.get("from", "")
        dst = request.query_params.get("to") or None
        if src not in mgr.replicas or (dst is not None
                                       and dst not in mgr.replicas):
            return JSONResponse(
                {"error": {"message": f"unknown replica (from={src!r}, "
                           f"to={dst!r}); configured: "
                           f"{sorted(mgr.replicas)}",
                           "type": "invalid_request_error"}},
                status_code=404)
        try:
            out = await mgr.migrate_from(src, to=dst)
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"migration failed: {e}",
                           "type": "proxy_error"}},
                status_code=502)
        return JSONResponse(out)

    return app
