"""The router ASGI app: the OpenAI surface, placed by prefix affinity.

Accepts the same ``POST /chat/completions`` (+ ``/v1`` alias) surface as
``server/app.py`` and places each request on a replica by conversation-
prefix affinity (``affinity.py`` key → ``ring.py`` bounded-load consistent
hashing), so a conversation's turns land where its KV prefix already lives.
Everything upstream-facing reuses the PR 4 HTTP machinery: per-replica
:class:`HttpBackend` (pooled clients, capped-exponential retries,
Retry-After pacing), per-replica :class:`Breaker` for failover, ``/ready``
polling for ring rotation with prefix migration (``replica.py``).

Failover contract (the one the HTTP backend's streaming retry boundary
makes safe): a replica that fails BEFORE its 2xx event stream opens —
connect error, 5xx, 503 shed — moves the request to the next ring
candidate; non-streaming requests failover on any 5xx outcome; 4xx
outcomes relay immediately — a client error is the same on every replica.

Once a stream is open, a mid-stream death is NOT a re-send — it is a
token-exact RESUME (docs/robustness.md "Zero-loss streams"): the router
journals each live stream's emitted token ids (the replicas attach them
as ``qt_tokens`` when the router sets ``stream_token_ids``; stripped
before the client), and on a broken stream (or a drain-parked one —
finish ``parked``) re-submits on the next ring candidate with
``resume_tokens``/``resume_chars``. The replica replays the delivered
prefix through the engine's byte-comparing replay guard and emits only
the continuation, which the router splices into the still-open SSE
stream — no duplicate or dropped frames, original chunk identity, usage
merged as the union. Divergence, journal overflow, or candidate
exhaustion degrade to the PR 12 error-chunk contract; every outcome
lands on ``quorum_tpu_router_stream_resumes_total{outcome=}`` and the
recorder under the request's trace-id.

SSE pass-through preserves TTFT: upstream events re-encode and flush
frame-by-frame as they arrive (no buffering, no coalescing beyond the
upstream's own), with the router adding only its hash lookup (~µs) to the
first-byte path.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

import httpx

from quorum_tpu import faults, oai, sse
from quorum_tpu.backends.base import BackendError
from quorum_tpu.observability import (
    METRICS,
    ROUTER_AFFINITY_HITS,
    ROUTER_AFFINITY_MISSES,
    ROUTER_FAILOVERS,
    ROUTER_REQUESTS,
    ROUTER_STREAM_RESUMES,
    TRACE_PROPAGATED,
)
from quorum_tpu.quorum import fanout as quorum_fanout
from quorum_tpu.router import affinity
from quorum_tpu.router.replica import Replica, ReplicaSet
from quorum_tpu.server.asgi import (
    App,
    JSONResponse,
    Request,
    Response,
    StreamingResponse,
)
from quorum_tpu.telemetry import slo, tracecontext
from quorum_tpu.telemetry.recorder import RECORDER, merged_trace_events

logger = logging.getLogger(__name__)

# Response headers recomputed by this hop, never relayed from upstream.
_PASSTHROUGH_SKIP = {"content-length", "content-type", "transfer-encoding",
                     "content-encoding", "connection",
                     "x-request-id", "traceparent"}


class _StreamGuard:
    """Wraps the passthrough generator so the replica's in-flight count
    decrements EXACTLY once no matter how the stream ends — exhaustion,
    an exception, or ``aclose()`` on a generator whose body never ran
    (PEP 525: closing an unstarted async generator skips its ``finally``,
    which is how a client disconnecting before the response starts would
    otherwise leak ``inflight`` forever and bounded-load placement would
    drift all traffic off a healthy replica)."""

    def __init__(self, gen, dec):
        self._gen = gen
        self._dec = dec

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self._gen.__anext__()
        except StopAsyncIteration:
            self._dec()
            raise
        except BaseException:
            self._dec()
            raise

    async def aclose(self):
        self._dec()
        aclose = getattr(self._gen, "aclose", None)
        if aclose is not None:
            await aclose()


class _StreamJournal:
    """One live stream's bounded resume journal: the emitted token ids
    (from the replica's ``qt_tokens`` chunk metadata) plus the delivered
    char count — exactly what a sibling needs to regenerate and swallow
    the delivered prefix (``resume_tokens``/``resume_chars``). Also owns
    the splice bookkeeping: original chunk identity (resumed chunks are
    rewritten to it so the client sees ONE stream) and the usage union
    (``completion_tokens`` = journaled ids, replayed tokens never
    double-counted)."""

    __slots__ = ("limit", "ids", "chars", "finished", "unresumable",
                 "cid", "created", "resumed")

    def __init__(self, limit: int):
        self.limit = int(limit)
        self.ids: list[int] = []
        self.chars = 0
        self.finished = False      # a finish/error chunk reached the client
        self.unresumable = False   # journal overflow or missing qt_tokens
        self.cid: str | None = None
        self.created: Any = None
        self.resumed = False       # at least one splice committed

    def absorb(self, ev: Any) -> str:
        """Record ``ev`` — mutating it: ``qt_tokens`` is stripped (router-
        internal metadata), and after a splice chunk identity/usage are
        rewritten — and classify it: ``"forward"`` (relay to the client)
        or ``"parked"`` (a drain-parked finish: swallow and resume)."""
        if not isinstance(ev, dict):
            return "forward"
        qt = ev.pop("qt_tokens", None)
        ev.pop("qt_error", None)  # router-internal failure class
        if ev.get("id") == "error":
            # An upstream-relayed error chunk ends the stream for the
            # client; a later transport death must not trigger a resume.
            self.finished = True
            return "forward"
        choices = ev.get("choices")
        if choices is None and "usage" not in ev:
            return "forward"
        if self.cid is None and ev.get("id"):
            self.cid = ev.get("id")
            self.created = ev.get("created")
        elif self.resumed and ev.get("id") and self.cid is not None:
            ev["id"] = self.cid
            if self.created is not None:
                ev["created"] = self.created
        usage = ev.get("usage")
        if isinstance(usage, dict) and self.resumed:
            usage["completion_tokens"] = len(self.ids)
            usage["total_tokens"] = (
                int(usage.get("prompt_tokens") or 0) + len(self.ids))
        for c in choices or []:
            if not isinstance(c, dict):
                continue
            fin = c.get("finish_reason")
            if fin == "parked":
                return "parked"
            if fin:
                self.finished = True
            content = (c.get("delta") or {}).get("content")
            if content:
                self.chars += len(content)
                if qt:
                    self.ids.extend(qt)
                    if len(self.ids) > self.limit:
                        self.unresumable = True
                else:
                    # Content the journal can't attribute to token ids:
                    # a resume would drop or duplicate it — degrade.
                    self.unresumable = True
        return "forward"


def _is_role_only(ev: Any) -> bool:
    """A role-announcement chunk with no content/finish — a resumed
    stream re-emits one, which the splice must swallow (the client
    already has it)."""
    if not isinstance(ev, dict) or ev.get("id") == "error":
        return False
    if "usage" in ev:
        return False
    choices = ev.get("choices") or []
    if len(choices) != 1 or choices[0].get("finish_reason"):
        return False
    delta = choices[0].get("delta") or {}
    return bool(delta) and set(delta) <= {"role"}


def _is_error_chunk(ev: Any) -> bool:
    if not isinstance(ev, dict):
        return False
    if ev.get("id") == "error":
        return True
    choices = ev.get("choices") or []
    return bool(choices) and choices[0].get("finish_reason") == "error"


def _is_divergence_chunk(ev: Any) -> bool:
    """A replay-guard refusal: the upstream error chunk carries the
    structured ``qt_error: "resume_diverged"`` marker (set by the real
    server and the fake replica alike) — classification never keys on
    message text, which rewording would silently break."""
    return isinstance(ev, dict) and ev.get("qt_error") == "resume_diverged"


def _is_parked_finish(ev: Any) -> bool:
    """A drain-park finish chunk: internal ``finish_reason: "parked"``
    that must never reach a client — journalled streams resume on it,
    journal-less ones degrade to the error-chunk contract."""
    if not isinstance(ev, dict) or ev.get("id") == "error":
        return False
    return any(isinstance(c, dict) and c.get("finish_reason") == "parked"
               for c in ev.get("choices") or [])


async def _aclose_quiet(stream: Any) -> None:
    """Close an upstream stream generator without letting cleanup errors
    mask the real outcome (an abandoned generator would hold its HTTP
    response open until GC)."""
    aclose = getattr(stream, "aclose", None)
    if aclose is None:
        return
    try:
        await aclose()
    except Exception:
        pass


def _error_text(ev: Any) -> str:
    try:
        return str(ev["choices"][0]["delta"].get("content") or "")
    except Exception:
        return ""


@dataclass
class RouterConfig:
    """Config for one router process (``python -m quorum_tpu.router``)."""

    replicas: list[tuple[str, str]] = field(default_factory=list)
    policy: str = "affinity"            # or "random" (the bench baseline)
    affinity_chunk: int = affinity.DEFAULT_AFFINITY_CHUNK
    retries: int = 1                    # per-replica HttpBackend retries
    timeout: float = 120.0              # default request budget (seconds)
    ready_interval: float = 2.0         # /ready poll period; <=0 disables
    migrate_on_rotation: bool = True
    vnodes: int = 64
    load_factor: float = 1.25
    breaker_threshold: int = 3
    breaker_window: float = 30.0
    breaker_cooldown: float = 5.0
    # Burn-aware placement (docs/observability.md "Fleet plane"): demote
    # a replica whose ``burn_class`` SLO burn rate (from its last
    # /debug/telemetry snapshot, absorbed by the /ready poller) exceeds
    # the threshold. <= 0 disables; stale telemetry always fails open.
    burn_threshold: float = 0.5
    burn_class: str = "interactive"
    telemetry_max_age: float = 10.0
    # Mid-stream resume (module docstring): journal live streams and
    # re-place broken ones token-exactly. Off → the plain PR 12 contract
    # (mid-stream death = error chunk). resume_max_tokens bounds the
    # per-stream journal; a stream that outgrows it degrades to the
    # error-chunk contract instead of growing without bound.
    stream_resume: bool = True
    resume_max_tokens: int = 4096
    # Cross-cell quorum (docs/quorum.md): the separator joining member
    # answers in the router-tier combine of a ``quorum=M`` request.
    quorum_separator: str = "\n\n---\n\n"

    def __post_init__(self) -> None:
        if self.policy not in ("affinity", "random"):
            raise ValueError(
                f"unknown routing policy {self.policy!r} "
                "(affinity or random)")
        if not self.replicas:
            raise ValueError("router config names no replicas")

    @classmethod
    def from_dict(cls, raw: dict) -> "RouterConfig":
        replicas = []
        for i, entry in enumerate(raw.get("replicas") or []):
            if isinstance(entry, str):
                replicas.append((f"replica-{i}", entry))
            elif isinstance(entry, dict) and entry.get("url"):
                replicas.append(
                    (str(entry.get("name") or f"replica-{i}"),
                     str(entry["url"])))
            else:
                raise ValueError(f"bad replica entry: {entry!r}")
        kwargs = {k: raw[k] for k in (
            "policy", "affinity_chunk", "retries", "timeout",
            "ready_interval", "migrate_on_rotation", "vnodes",
            "load_factor", "breaker_threshold", "breaker_window",
            "breaker_cooldown", "burn_threshold", "burn_class",
            "telemetry_max_age", "stream_resume",
            "resume_max_tokens", "quorum_separator") if k in raw}
        return cls(replicas=replicas, **kwargs)


def build_replica_set(cfg: RouterConfig,
                      client: httpx.AsyncClient | None = None,
                      control_client: httpx.AsyncClient | None = None,
                      ) -> ReplicaSet:
    from quorum_tpu.breaker import Breaker

    replicas = [
        Replica(name, url, retries=cfg.retries, client=client,
                breaker=Breaker(threshold=cfg.breaker_threshold,
                                window=cfg.breaker_window,
                                cooldown=cfg.breaker_cooldown))
        for name, url in cfg.replicas
    ]
    return ReplicaSet(
        replicas,
        vnodes=cfg.vnodes, load_factor=cfg.load_factor,
        affinity_chunk=cfg.affinity_chunk,
        ready_interval=cfg.ready_interval,
        migrate_on_rotation=cfg.migrate_on_rotation,
        burn_threshold=cfg.burn_threshold,
        burn_class=cfg.burn_class,
        telemetry_max_age=cfg.telemetry_max_age,
        control_client=control_client)


def create_router_app(cfg: RouterConfig,
                      replica_set: ReplicaSet | None = None,
                      client: httpx.AsyncClient | None = None,
                      control_client: httpx.AsyncClient | None = None,
                      ) -> App:
    """Build the router ASGI app. Tests inject a shared ``client``
    (e.g. an ASGITransport-backed one) or a prebuilt ``replica_set``."""
    mgr = replica_set if replica_set is not None else build_replica_set(
        cfg, client=client, control_client=control_client)

    app = App()
    app.state["router_config"] = cfg
    app.state["replica_set"] = mgr
    started = time.monotonic()

    def _forward_headers(request: Request) -> dict[str, str]:
        """Relay the client's headers minus host (the reference proxy's
        contract) — auth passes through for the REPLICA to enforce; the
        router holds no credential policy of its own."""
        return {k: v for k, v in request.headers.items()
                if k.lower() != "host"}

    def _shed_response() -> JSONResponse:
        # The whole fleet refused a request — exactly the moment an
        # operator wants the router's event ring on disk. dump() is
        # rate-limited per reason, so a shed storm costs one artifact
        # per QUORUM_TPU_FLIGHT_DUMP_INTERVAL, not one per request.
        RECORDER.dump("router-all-dead")
        retry = max([r.breaker.retry_after()
                     for r in mgr.replicas.values()] or [1.0])
        return JSONResponse(
            {"error": {"message": "no replica available "
                       "(all rotated out, breaker-open, or unreachable)",
                       "type": "overloaded_error"}},
            status_code=503,
            headers={"Retry-After": str(max(1, int(retry)))})

    def _pick(body: dict) -> tuple[str | None, list[str]]:
        """(affinity primary, candidate order) under the active policy."""
        if cfg.policy == "random":
            members = sorted(mgr.ring.members)
            random.shuffle(members)
            return None, members
        key = affinity.conversation_key(body, cfg.affinity_chunk)
        return mgr.placement(key, slo_class=_request_slo_class(body))

    def _request_slo_class(body: dict) -> str | None:
        """The request's SLO scoring class for burn-aware placement
        (docs/scheduling.md): the explicit 'priority' body knob mapped
        onto the SLO plane's two classes, else derived from the request's
        timeout exactly like the replicas' own scoring — so the router
        avoids replicas burning the objective THIS request will be scored
        against. None (no knob, no timeout) keeps the configured
        burn_class floor only."""
        prio = body.get("priority")
        if isinstance(prio, str) and prio:
            from quorum_tpu.sched import to_slo_class

            return to_slo_class(prio)
        t = body.get("timeout")
        if isinstance(t, (int, float)) and not isinstance(t, bool) and t > 0:
            return slo.classify(float(t))
        return None

    def _score_affinity(primary: str | None, served_by: str) -> None:
        if primary is not None and served_by == primary:
            ROUTER_AFFINITY_HITS.inc()
        else:
            ROUTER_AFFINITY_MISSES.inc()

    @app.route("POST", "/chat/completions", "/v1/chat/completions")
    async def chat_completions(request: Request) -> Response:
        await mgr.ensure_poller()
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"Invalid JSON body: {e}",
                           "type": "invalid_request_error"}},
                status_code=400)
        # Cross-tier trace identity (docs/observability.md "Fleet
        # plane"): honor the caller's W3C traceparent (header, or the
        # body knob for header-less clients), else mint. The trace-id IS
        # the router's request id — one string joins the router's route
        # events, every replica attempt, and the engines' dispatch/reap
        # timeline, surviving failover (same trace-id, fresh span-id per
        # hop).
        parsed = tracecontext.parse_traceparent(
            request.headers.get("traceparent"))
        if parsed is None:
            parsed = tracecontext.parse_traceparent(
                body.get("traceparent"))
        if parsed is not None:
            trace_id = parsed[0]
            TRACE_PROPAGATED.inc(source="client")
        else:
            trace_id = tracecontext.new_trace_id()
            TRACE_PROPAGATED.inc(source="router")
        rid = trace_id
        headers = _forward_headers(request)
        is_streaming = bool(body.get("stream", False))
        # The timeout knob is READ, not consumed — the replica's server
        # pops and enforces it; the router only bounds its own HTTP waits.
        try:
            timeout = float(body.get("timeout") or cfg.timeout)
        except (TypeError, ValueError):
            timeout = cfg.timeout
        deadline = time.monotonic() + timeout

        # Cross-cell quorum (docs/quorum.md): ``quorum: M`` fans this
        # request out to M distinct ring candidates and combines at THIS
        # tier. The knob is validated and STRIPPED here — a forwarded
        # knob would recurse the fan-out at the replicas. Member deaths
        # degrade the quorum (token-exact resume on a spare first, then
        # served from the survivors), never fail the request while any
        # member holds content.
        q_msg = quorum_fanout.validate_quorum(body)
        if q_msg is not None:
            return JSONResponse(
                {"error": {"message": q_msg,
                           "type": "invalid_request_error"}},
                status_code=400)
        quorum_m = quorum_fanout.pop_quorum(body)
        if quorum_m > 1:
            _, candidates = _pick(body)
            if not candidates:
                return _shed_response()
            span_id, traceparent = tracecontext.child_traceparent(trace_id)
            headers["traceparent"] = traceparent
            assigned, _spares = quorum_fanout.choose_members(
                candidates, quorum_m)
            if is_streaming:
                resp = StreamingResponse(quorum_fanout.quorum_stream(
                    mgr.replicas, candidates, quorum_m, body, headers,
                    deadline, rid, cfg.quorum_separator,
                    journal_limit=cfg.resume_max_tokens,
                    suppress_individual=bool(
                        body.get("suppress_individual_responses", False))))
                # Streamed degradation is visible on the counters/recorder
                # and in the combine, not headers — the member outcomes
                # are unknown when these go out.
                resp.headers["X-Quorum-Members"] = str(quorum_m)
                resp.headers["X-Quorum-Replicas"] = ",".join(assigned)
                resp.headers["X-Request-Id"] = rid
                resp.headers["traceparent"] = traceparent
                return resp
            q_body, q_status, q_headers = await quorum_fanout.quorum_complete(
                mgr.replicas, candidates, quorum_m, body, headers,
                deadline, rid, cfg.quorum_separator)
            q_headers["X-Request-Id"] = rid
            q_headers["traceparent"] = traceparent
            return JSONResponse(q_body, status_code=q_status,
                                headers=q_headers)

        # A stream is resumable when the router may journal it: resume
        # enabled, single choice, no logprobs (replayed tokens carry no
        # records), and the client did not claim the token-id channel for
        # itself (an explicit stream_token_ids passes qt_tokens through
        # untouched — the router must not strip what the client asked
        # for) or supply its own journal.
        resumable = (is_streaming and cfg.stream_resume
                     and not body.get("stream_token_ids")
                     and not body.get("logprobs")
                     and body.get("n") in (None, 1)
                     and body.get("resume_tokens") is None)
        if resumable:
            body = dict(body)
            body["stream_token_ids"] = True

        primary, candidates = _pick(body)
        if not candidates:
            return _shed_response()

        last_err: BackendError | None = None
        last_result = None
        attempt = 0
        for name in candidates:
            r = mgr.replicas[name]
            if not r.breaker.allow():
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            # One hop span per replica attempt: same trace-id all the
            # way down, a fresh span-id on the wire each try — the
            # replica's events nest under the attempt that reached it,
            # and a failed-over request stays ONE trace.
            attempt += 1
            span_id, traceparent = tracecontext.child_traceparent(trace_id)
            headers["traceparent"] = traceparent
            r.inflight += 1
            r.requests += 1
            # A resumed stream migrates mid-flight: the holder names the
            # replica currently carrying it, so the guard's single
            # decrement always lands on the right one (the splice itself
            # moves the count: old -1, new +1, holder re-pointed).
            holder = {"replica": r}
            decremented = [False]
            guard_owns = False  # True once a _StreamGuard took ownership

            def dec(holder=holder, flag=decremented):
                if not flag[0]:
                    flag[0] = True
                    holder["replica"].inflight -= 1

            try:
                if is_streaming:
                    stream = r.backend.stream(body, headers, remaining)
                    try:
                        first = await stream.__anext__()
                    except StopAsyncIteration:
                        first = None
                    # 2xx stream open (or cleanly empty): committed.
                    r.breaker.record_success()
                    ROUTER_REQUESTS.inc(replica=name, outcome="ok")
                    _score_affinity(primary, name)
                    RECORDER.record("router-route", rid=rid, loop="router",
                                    replica=name, stream=True,
                                    affinity=bool(primary == name),
                                    span=span_id,
                                    **({"failover": 1} if attempt > 1
                                       else {}))
                    journal = (_StreamJournal(cfg.resume_max_tokens)
                               if resumable else None)
                    resp = StreamingResponse(_StreamGuard(
                        _passthrough(holder, rid, first, stream,
                                     body=body, headers=dict(headers),
                                     deadline=deadline, journal=journal),
                        dec))
                    guard_owns = True
                    resp.headers["X-Routed-To"] = name
                    resp.headers["X-Request-Id"] = rid
                    resp.headers["traceparent"] = traceparent
                    return resp
                result = await r.backend.complete(body, headers, remaining)
                if result.status_code >= 500:
                    # The replica already burned its own retry budget;
                    # the router's move is the NEXT replica.
                    r.breaker.record_failure()
                    ROUTER_FAILOVERS.inc(replica=name)
                    ROUTER_REQUESTS.inc(replica=name, outcome="failover")
                    RECORDER.record("router-failover", rid=rid,
                                    loop="router", replica=name,
                                    status=result.status_code,
                                    span=span_id)
                    last_result = result
                    continue
                r.breaker.record_success()
                ROUTER_REQUESTS.inc(replica=name, outcome="ok")
                _score_affinity(primary, name)
                RECORDER.record("router-route", rid=rid, loop="router",
                                replica=name, stream=False,
                                affinity=bool(primary == name),
                                span=span_id,
                                **({"failover": 1} if attempt > 1
                                   else {}))
                resp_headers = {
                    k: v for k, v in result.headers.items()
                    if k.lower() not in _PASSTHROUGH_SKIP}
                resp_headers["X-Routed-To"] = name
                resp_headers["X-Request-Id"] = rid
                resp_headers["traceparent"] = traceparent
                return JSONResponse(result.body,
                                    status_code=result.status_code,
                                    headers=resp_headers)
            except BackendError as e:
                if e.status_code < 500:
                    # Client errors are replica-independent: relay
                    # (outcome "ok" — a faithful 4xx relay is the same
                    # series on the stream and non-stream paths).
                    ROUTER_REQUESTS.inc(replica=name, outcome="ok")
                    resp_headers = dict(e.headers)
                    resp_headers["X-Routed-To"] = name
                    resp_headers["traceparent"] = traceparent
                    return JSONResponse(e.body, status_code=e.status_code,
                                        headers=resp_headers)
                r.breaker.record_failure()
                ROUTER_FAILOVERS.inc(replica=name)
                ROUTER_REQUESTS.inc(replica=name, outcome="failover")
                RECORDER.record("router-failover", rid=rid, loop="router",
                                replica=name, status=e.status_code,
                                span=span_id)
                last_err = e
                continue
            finally:
                # Streaming success hands the single decrement to the
                # _StreamGuard; every other exit (non-streaming, any
                # failure, even a non-BackendError) releases here. The
                # once-guard keeps the two hand-offs from double-counting.
                if not guard_owns:
                    dec()
        # Exhausted every candidate: relay the terminal failure with its
        # own status/Retry-After, else shed.
        if last_err is not None:
            ROUTER_REQUESTS.inc(replica="none", outcome="error")
            return JSONResponse(last_err.body,
                                status_code=last_err.status_code,
                                headers=last_err.headers)
        if last_result is not None:
            ROUTER_REQUESTS.inc(replica="none", outcome="error")
            resp_headers = {k: v for k, v in last_result.headers.items()
                            if k.lower() not in _PASSTHROUGH_SKIP}
            return JSONResponse(last_result.body,
                                status_code=last_result.status_code,
                                headers=resp_headers)
        return _shed_response()

    async def _resume_stream(holder: dict, rid: str, body: dict,
                             headers: dict, deadline: float,
                             journal: _StreamJournal):
        """Re-place a broken/parked stream on the next ring candidate
        within the remaining deadline. Commit point is the first NON-role
        event of the replacement stream: a normal chunk splices (returns
        ``("ok", (event, stream))``), a divergence error chunk degrades
        (``("diverged", message)`` — retrying siblings cannot help when
        the replay guard itself refused), any other failure moves to the
        next candidate; ``("exhausted", None)`` when none commit. Every
        outcome lands on the resume counter + recorder."""
        dead = holder["replica"].name
        base = dict(body)
        base["stream"] = True
        base["stream_token_ids"] = True
        base.pop("resume_tokens", None)
        base.pop("resume_chars", None)
        if journal.ids:
            base["resume_tokens"] = list(journal.ids)
            base["resume_chars"] = journal.chars
        _, candidates = _pick(body)
        for name in candidates:
            if name == dead:
                continue
            r2 = mgr.replicas[name]
            if not r2.breaker.allow():
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            span_id, traceparent = tracecontext.child_traceparent(rid)
            h2 = dict(headers)
            h2["traceparent"] = traceparent
            probe = None
            stream2 = None
            try:
                faults.fire("router.resume")
                stream2 = r2.backend.stream(base, h2, remaining)
                probe = await stream2.__anext__()
                while _is_role_only(probe):
                    # The replacement re-announces the role; the client
                    # already has that chunk — swallow, probe deeper.
                    probe = await stream2.__anext__()
            except StopAsyncIteration:
                probe = None
            except Exception as e:
                await _aclose_quiet(stream2)
                r2.breaker.record_failure()
                ROUTER_STREAM_RESUMES.inc(outcome="failed")
                RECORDER.record("router-resume-failed", rid=rid,
                                loop="router", replica=name,
                                error=str(e)[:200], span=span_id)
                continue
            if probe is None or _is_error_chunk(probe):
                # Every non-commit path releases the replacement stream —
                # an abandoned generator would pin the upstream response.
                await _aclose_quiet(stream2)
                text = _error_text(probe) if probe is not None else ""
                if _is_divergence_chunk(probe):
                    ROUTER_STREAM_RESUMES.inc(outcome="divergence")
                    RECORDER.record("router-resume-diverged", rid=rid,
                                    loop="router", replica=name,
                                    span=span_id)
                    return "diverged", text
                r2.breaker.record_failure()
                ROUTER_STREAM_RESUMES.inc(outcome="failed")
                RECORDER.record("router-resume-failed", rid=rid,
                                loop="router", replica=name,
                                error=text[:200] or "empty stream",
                                span=span_id)
                continue
            # Committed: move the in-flight count with the stream. The
            # guard's single decrement follows the holder, so the old
            # replica is released here and the new one at stream end.
            r_old = holder["replica"]
            r2.inflight += 1
            r2.requests += 1
            holder["replica"] = r2
            r_old.inflight -= 1
            r2.breaker.record_success()
            journal.resumed = True
            ROUTER_STREAM_RESUMES.inc(outcome="resumed")
            ROUTER_REQUESTS.inc(replica=name, outcome="resume")
            RECORDER.record("router-stream-resume", rid=rid,
                            loop="router", replica=name,
                            from_replica=dead,
                            replayed=len(journal.ids), span=span_id)
            return "ok", (probe, stream2)
        ROUTER_STREAM_RESUMES.inc(outcome="exhausted")
        RECORDER.record("router-resume-exhausted", rid=rid, loop="router",
                        from_replica=dead)
        return "exhausted", None

    async def _passthrough(
        holder: dict, rid: str,
        first: dict[str, Any] | None,
        rest: AsyncIterator[dict[str, Any]],
        *, body: dict | None = None,
        headers: dict | None = None,
        deadline: float = 0.0,
        journal: _StreamJournal | None = None,
    ) -> AsyncIterator[bytes]:
        """SSE pass-through: re-encode upstream events frame-by-frame (the
        h11 server flushes each yield — TTFT rides the first upstream
        event untouched). The in-flight decrement belongs to the wrapping
        :class:`_StreamGuard`, which fires even when this body never runs.

        With a ``journal``, a mid-stream failure (or a drain-parked
        finish) is a token-exact RESUME on a sibling (module docstring) —
        the continuation splices into this same generator and relaying
        continues (repeat deaths resume again). Without one — resume off,
        or the request isn't journalable — failure degrades to the PR 12
        contract: error chunk + [DONE], never a re-send."""
        model = "unknown"
        current = rest
        pending = first
        while True:
            broke: BackendError | None = None
            parked = False
            try:
                while True:
                    if pending is not None:
                        event, pending = pending, None
                    else:
                        event = await current.__anext__()
                    if isinstance(event, dict):
                        model = event.get("model") or model
                    if journal is not None:
                        if journal.absorb(event) == "parked":
                            parked = True
                            break
                    else:
                        # No journal (resume off / not journalable): the
                        # internal park finish still must not reach the
                        # client — swallow it and degrade below.
                        if isinstance(event, dict):
                            event.pop("qt_error", None)
                        if _is_parked_finish(event):
                            parked = True
                            break
                    yield sse.encode_event(event)
            except StopAsyncIteration:
                break
            except BackendError as e:
                broke = e
            r_old = holder["replica"]
            if parked:
                # The replica is draining: the park finish is the resume
                # signal, not a failure — the breaker stays clean.
                RECORDER.record("router-stream-parked", rid=rid,
                                loop="router", replica=r_old.name)
                await _aclose_quiet(current)
            else:
                r_old.breaker.record_failure()
                RECORDER.record("router-stream-broken", rid=rid,
                                loop="router", replica=r_old.name,
                                error=str(broke)[:200])
            if journal is not None and journal.finished:
                # Death after the finish chunk: the client already has
                # the whole completion — just close cleanly.
                break
            if journal is None or journal.unresumable:
                if journal is not None:
                    ROUTER_STREAM_RESUMES.inc(outcome="unresumable")
                yield sse.encode_event(oai.error_chunk(
                    f"Backend failed: {broke or 'stream parked'}",
                    model=model))
                break
            status, payload = await _resume_stream(
                holder, rid, body or {}, headers or {}, deadline, journal)
            if status == "ok":
                pending, current = payload
                continue
            if status == "diverged":
                # The upstream error chunk already carries the full
                # "Backend failed: ... diverged ..." message — forward it.
                yield sse.encode_event(oai.error_chunk(
                    payload or "Backend failed: resume replay diverged",
                    model=model))
            else:
                yield sse.encode_event(oai.error_chunk(
                    f"Backend failed: {broke or 'stream parked'} "
                    "(resume exhausted)", model=model))
            break
        yield sse.encode_done()

    @app.route("GET", "/health", "/v1/health")
    async def health(request: Request) -> Response:
        await mgr.ensure_poller()
        rows = [r.state() | {"in_ring": r.name in mgr.ring}
                for r in mgr.replicas.values()]
        in_ring = sum(1 for row in rows if row["in_ring"])
        if in_ring == len(rows):
            status = "healthy"
        elif in_ring:
            status = "degraded"
        else:
            status = "unhealthy"
        body = {"status": status, "role": "router", "replicas": rows}
        if status == "unhealthy":
            return JSONResponse(body, status_code=503,
                                headers={"Retry-After": "5"})
        return JSONResponse(body)

    @app.route("GET", "/ready", "/v1/ready")
    async def ready(request: Request) -> Response:
        await mgr.ensure_poller()
        if len(mgr.ring):
            return JSONResponse({"status": "ready"})
        return JSONResponse(
            {"status": "unready", "reason": "no replica in the ring"},
            status_code=503, headers={"Retry-After": "5"})

    @app.route("GET", "/metrics", "/v1/metrics")
    async def metrics(request: Request) -> Response:
        lines = [
            "# TYPE quorum_tpu_uptime_seconds gauge",
            f"quorum_tpu_uptime_seconds {time.monotonic() - started:.3f}",
            "# TYPE quorum_tpu_router_replica_up gauge",
        ]
        for name, r in sorted(mgr.replicas.items()):
            up = 1 if name in mgr.ring else 0
            lines.append(
                f'quorum_tpu_router_replica_up{{replica="{name}"}} {up}')
        lines.append("# TYPE quorum_tpu_router_replicas_in_ring gauge")
        lines.append(
            f"quorum_tpu_router_replicas_in_ring {len(mgr.ring)}")
        lines.append("# TYPE quorum_tpu_router_inflight gauge")
        lines.append(
            f"quorum_tpu_router_inflight "
            f"{sum(r.inflight for r in mgr.replicas.values())}")
        lines.extend(METRICS.expose())
        return Response(("\n".join(lines) + "\n").encode(),
                        media_type="text/plain; version=0.0.4")

    @app.route("GET", "/router/replicas", "/v1/router/replicas")
    async def replicas(request: Request) -> Response:
        """Debug surface: live placement state per replica."""
        await mgr.ensure_poller()
        return JSONResponse({
            "policy": cfg.policy,
            "affinity_chunk": cfg.affinity_chunk,
            "in_ring": sorted(mgr.ring.members),
            "migrations": mgr.n_migrations,
            "burn_threshold": mgr.burn_threshold,
            "burn_class": mgr.burn_class,
            "burn_demoted": sorted(mgr.burn_demoted()),
            "burn_demotions": mgr.n_burn_demotions,
            "replicas": [r.state() | {"in_ring": r.name in mgr.ring}
                         for r in mgr.replicas.values()],
            "telemetry": mgr.telemetry.snapshot(),
        })

    @app.route("GET", "/debug/router/timeline",
               "/v1/debug/router/timeline")
    async def router_timeline(request: Request) -> Response:
        """The router's OWN flight recorder: route/failover/stream-broken
        events, ring rotations, migrations — every event carrying the
        request's cross-tier trace-id as ``rid``. Same contract as a
        replica's /debug/engine/timeline: default JSON, ``?format=
        perfetto`` for Chrome trace-event output; also auto-dumped (rate-
        limited) whenever the router sheds with every replica dead."""
        fmt = request.query_params.get("format", "json")
        if fmt in ("perfetto", "trace", "chrome"):
            return JSONResponse({"displayTimeUnit": "ms",
                                 "traceEvents": RECORDER.to_trace_events()})
        if fmt != "json":
            return JSONResponse(
                {"error": {"message": f"unknown format {fmt!r} "
                           "(json or perfetto)",
                           "type": "invalid_request_error"}},
                status_code=400)
        return JSONResponse({
            "clock": "perf_counter",
            "capacity": RECORDER.capacity,
            "recorded_total": RECORDER.total(),
            "events": RECORDER.snapshot(),
        })

    @app.route("GET", "/debug/fleet/timeline",
               "/v1/debug/fleet/timeline")
    async def fleet_timeline(request: Request) -> Response:
        """One timeline for the whole fleet: the router's recorder plus
        every reachable replica's /debug/engine/timeline, each replica's
        monotonic stamps shifted onto the router's clock by the offset
        estimated from its telemetry polls (midpoint method — good to
        half an RTT). Events join across tiers on the trace-id ``rid``:
        follow one id from the router's route event through the serving
        replica's dispatch/reap spans. ``?format=perfetto`` renders one
        Perfetto process per tier member; default JSON returns the
        merged, time-sorted event list with per-event ``process``."""
        await mgr.ensure_poller()
        rows = await mgr.fetch_timelines()
        fmt = request.query_params.get("format", "json")
        if fmt in ("perfetto", "trace", "chrome"):
            groups = [("router", RECORDER.snapshot(), 0.0)]
            groups += [(row["name"], row["events"], row["offset"] or 0.0)
                       for row in rows]
            return JSONResponse({"displayTimeUnit": "ms",
                                 "traceEvents": merged_trace_events(groups)})
        if fmt != "json":
            return JSONResponse(
                {"error": {"message": f"unknown format {fmt!r} "
                           "(json or perfetto)",
                           "type": "invalid_request_error"}},
                status_code=400)
        merged: list[dict] = []
        for ev in RECORDER.snapshot():
            merged.append({**ev, "process": "router"})
        for row in rows:
            offset = row["offset"] or 0.0
            for ev in row["events"]:
                if not isinstance(ev, dict):
                    continue
                shifted = dict(ev)
                for key in ("t", "t_issue", "t_ready"):
                    if isinstance(shifted.get(key), (int, float)):
                        shifted[key] = round(shifted[key] + offset, 6)
                shifted["process"] = row["name"]
                merged.append(shifted)
        merged.sort(key=lambda e: e.get("t", 0.0))
        return JSONResponse({
            "clock": "router perf_counter",
            "replicas": [{"name": row["name"],
                          "offset": row["offset"],
                          "clock_aligned": row["clock_aligned"],
                          "events": len(row["events"])}
                         for row in rows],
            "events": merged,
        })

    @app.route("POST", "/router/migrate", "/v1/router/migrate")
    async def migrate(request: Request) -> Response:
        """Operator-triggered prefix migration: drain ``?from=NAME``'s hot
        chains to their current ring homes (or pin to ``?to=NAME``) ahead
        of a planned rotation — the same path the /ready poller drives
        automatically when a replica sheds."""
        src = request.query_params.get("from", "")
        dst = request.query_params.get("to") or None
        if src not in mgr.replicas or (dst is not None
                                       and dst not in mgr.replicas):
            return JSONResponse(
                {"error": {"message": f"unknown replica (from={src!r}, "
                           f"to={dst!r}); configured: "
                           f"{sorted(mgr.replicas)}",
                           "type": "invalid_request_error"}},
                status_code=404)
        try:
            out = await mgr.migrate_from(src, to=dst)
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"migration failed: {e}",
                           "type": "proxy_error"}},
                status_code=502)
        return JSONResponse(out)

    @app.route("POST", "/router/drain", "/v1/router/drain")
    async def drain(request: Request) -> Response:
        """Operator-triggered graceful drain of ``?replica=NAME``: rotate
        it out of the ring, park its live streams (which the data plane
        proactively resumes on siblings — zero failed requests), wait for
        residency to hit zero, and migrate its prefix chains to the
        survivors."""
        name = request.query_params.get("replica", "")
        if name not in mgr.replicas:
            return JSONResponse(
                {"error": {"message": f"unknown replica {name!r}; "
                           f"configured: {sorted(mgr.replicas)}",
                           "type": "invalid_request_error"}},
                status_code=404)
        try:
            out = await mgr.drain(name)
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"drain failed: {e}",
                           "type": "proxy_error"}},
                status_code=502)
        return JSONResponse(out)

    return app
