"""A jax-free scripted replica process — the router's test/bench upstream.

``python -m quorum_tpu.router.fake_replica --port 0`` serves a deterministic
OpenAI-compatible surface over the bundled h11 server: completions are a
pure function of the prompt (identical on every replica — the router bench's
token-for-token pinning rides this), and a REAL
:class:`~quorum_tpu.cache.prefix_store.PrefixStore` (tiny dummy payloads,
one uint8 array per chunk) tracks conversation prefixes exactly the way an
engine's host store does — same trie, same chunking, same LRU — so
affinity-vs-random hit rates measured against fake replicas use the same
store code paths as real ones, and ``GET/PUT /debug/prefix/chunks`` speaks
the real migration wire format (``cache/prefix_wire.py``).

Used by ``scripts/router_bench.py`` (fast mode), the chaos harness's
replica-kill drill (a killable process with slow streams), and
``tests/test_router.py``. Admin knobs for drills:

  POST /admin/shed      /ready answers 503 from now on (rotation trigger)
  POST /admin/recover   /ready answers 200 again

Boot prints ``PORT=<bound port>`` to stdout (``--port 0`` → ephemeral) so a
spawning parent can address it.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
from typing import Any, AsyncIterator

import numpy as np

from quorum_tpu import oai, sse
from quorum_tpu.cache import prefix_wire
from quorum_tpu.cache.prefix_store import PrefixStore
from quorum_tpu.engine.tokenizer import ByteTokenizer
from quorum_tpu.server.asgi import (
    App,
    JSONResponse,
    Request,
    Response,
    StreamingResponse,
)

DEFAULT_CHUNK_TOKENS = 16
DEFAULT_TOKENS = 8


def deterministic_completion(prompt: str, n_tokens: int) -> list[str]:
    """The scripted 'generation': a pure function of the prompt, so every
    replica (and a single-replica baseline) emits identical tokens."""
    digest = hashlib.sha256(prompt.encode()).digest()
    return [f"w{digest[i % len(digest)]:02x}" + (" " if i + 1 < n_tokens
                                                 else "")
            for i in range(max(1, n_tokens))]


class FakeReplicaState:
    """One fake replica's store + counters (shared by its routes)."""

    def __init__(self, name: str, chunk_tokens: int = DEFAULT_CHUNK_TOKENS,
                 max_tokens: int = DEFAULT_TOKENS,
                 chunk_delay: float = 0.0):
        self.name = name
        self.chunk_tokens = int(chunk_tokens)
        self.max_tokens = int(max_tokens)
        self.chunk_delay = float(chunk_delay)
        self.tokenizer = ByteTokenizer(259)
        self.store = PrefixStore(self.chunk_tokens, 1 << 24)
        self.shedding = False
        self.requests = 0
        self.prefix_hits = 0
        self.tokens_restored = 0

    def _dummy_payloads(self, n_chunks: int) -> list[list[np.ndarray]]:
        return [[np.zeros((1, 1, self.chunk_tokens), dtype=np.uint8)]
                for _ in range(n_chunks)]

    def observe(self, prompt_text: str, completion: str) -> int:
        """Record the request against the store: a hit when the prompt's
        prefix chain is already held (an earlier turn, or a migrated
        seed), then retain prompt+completion — the engine's
        snapshot-on-release, scripted. Returns matched tokens."""
        self.requests += 1
        ids = self.tokenizer.encode(prompt_text)
        matched, _ = self.store.longest_match(ids)
        if matched >= self.chunk_tokens:
            self.prefix_hits += 1
            self.tokens_restored += matched
        full = ids + self.tokenizer.encode(completion)
        n_chunks = len(full) // self.chunk_tokens
        if n_chunks:
            self.store.import_chain(full, self._dummy_payloads(n_chunks))
        return matched


def create_fake_replica_app(state: FakeReplicaState) -> App:
    app = App()
    app.state["fake"] = state

    @app.route("POST", "/chat/completions", "/v1/chat/completions")
    async def chat(request: Request) -> Response:
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be an object")
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"Invalid JSON body: {e}",
                           "type": "invalid_request_error"}},
                status_code=400)
        if state.shedding:
            return JSONResponse(
                {"error": {"message": "shedding (admin)",
                           "type": "overloaded_error"}},
                status_code=503, headers={"Retry-After": "1"})
        messages = body.get("messages") or []
        prompt = state.tokenizer.render_chat(
            [m for m in messages if isinstance(m, dict)])
        n = int(body.get("max_tokens") or state.max_tokens)
        words = deterministic_completion(prompt, min(n, state.max_tokens))
        completion = "".join(words)
        matched = state.observe(prompt, completion)
        model = body.get("model") or "fake"
        if body.get("stream"):
            return StreamingResponse(
                _stream(model, words, matched))
        resp = oai.completion(
            content=completion, model=model,
            usage={"prompt_tokens": len(prompt),
                   "completion_tokens": len(words),
                   "total_tokens": len(prompt) + len(words)})
        resp["backend"] = state.name
        return JSONResponse(resp, headers={
            "X-Fake-Replica": state.name,
            "X-Prefix-Matched": str(matched)})

    async def _stream(model: str, words: list[str],
                      matched: int) -> AsyncIterator[bytes]:
        cid = f"chatcmpl-{state.name}"
        yield sse.encode_event(
            oai.chunk(id=cid, model=model, delta={"role": "assistant"}))
        for w in words:
            if state.chunk_delay:
                await asyncio.sleep(state.chunk_delay)
            yield sse.encode_event(
                oai.chunk(id=cid, model=model, delta={"content": w}))
        yield sse.encode_event(
            oai.chunk(id=cid, model=model, delta={}, finish_reason="stop"))
        yield sse.encode_done()

    @app.route("GET", "/health", "/v1/health")
    async def health(request: Request) -> Response:
        return JSONResponse({"status": "healthy", "fake": True})

    @app.route("GET", "/ready", "/v1/ready")
    async def ready(request: Request) -> Response:
        if state.shedding:
            return JSONResponse(
                {"status": "unready", "reason": "shedding"},
                status_code=503, headers={"Retry-After": "1"})
        return JSONResponse({"status": "ready"})

    @app.route("POST", "/admin/shed", "/v1/admin/shed")
    async def shed(request: Request) -> Response:
        state.shedding = True
        return JSONResponse({"shedding": True})

    @app.route("POST", "/admin/recover", "/v1/admin/recover")
    async def recover(request: Request) -> Response:
        state.shedding = False
        return JSONResponse({"shedding": False})

    @app.route("GET", "/metrics", "/v1/metrics")
    async def metrics(request: Request) -> Response:
        n = state.name
        lines = [
            "# TYPE quorum_tpu_engine_requests_total counter",
            f'quorum_tpu_engine_requests_total{{backend="{n}"}} '
            f"{state.requests}",
            "# TYPE quorum_tpu_engine_prefix_store_hits_total counter",
            f'quorum_tpu_engine_prefix_store_hits_total{{backend="{n}"}} '
            f"{state.prefix_hits}",
            "# TYPE quorum_tpu_engine_prefix_store_restored_tokens_total "
            "counter",
            f"quorum_tpu_engine_prefix_store_restored_tokens_total"
            f'{{backend="{n}"}} {state.tokens_restored}',
            "# TYPE quorum_tpu_engine_prefix_store_bytes gauge",
            f'quorum_tpu_engine_prefix_store_bytes{{backend="{n}"}} '
            f"{state.store.bytes_held}",
            "# TYPE quorum_tpu_engine_prefix_store_entries gauge",
            f'quorum_tpu_engine_prefix_store_entries{{backend="{n}"}} '
            f"{state.store.n_entries}",
        ]
        return Response(("\n".join(lines) + "\n").encode(),
                        media_type="text/plain; version=0.0.4")

    @app.route("GET", "/debug/prefix/chunks", "/v1/debug/prefix/chunks")
    async def export_chunks(request: Request) -> Response:
        blob = prefix_wire.serialize_chains(
            state.store.export_chains(), state.chunk_tokens)
        return Response(blob, media_type="application/octet-stream",
                        headers={"X-Prefix-Chunk-Tokens":
                                 str(state.chunk_tokens)})

    @app.route("PUT", "/debug/prefix/chunks", "/v1/debug/prefix/chunks")
    async def import_chunks(request: Request) -> Response:
        try:
            chunk_tokens, chains = prefix_wire.parse(await request.body())
            if chunk_tokens != state.chunk_tokens:
                raise prefix_wire.WireError(
                    f"chunk_tokens={chunk_tokens} != "
                    f"{state.chunk_tokens}")
        except prefix_wire.WireError as e:
            return JSONResponse(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status_code=400)
        imported = 0
        for chain in chains:
            imported += state.store.import_chain(chain.tokens,
                                                 chain.payloads)
        return JSONResponse({"chains": len(chains),
                             "tokens_imported": imported,
                             "store_entries": state.store.n_entries})

    return app


async def _serve(args) -> None:
    from quorum_tpu.server.serve import start_server

    state = FakeReplicaState(
        args.name, chunk_tokens=args.chunk_tokens,
        max_tokens=args.tokens, chunk_delay=args.chunk_delay)
    app = create_fake_replica_app(state)
    server = await start_server(app, args.host, args.port)
    port = server.sockets[0].getsockname()[1]
    print(f"PORT={port}", flush=True)
    async with server:
        await server.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="deterministic jax-free fake replica")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--name", default="fake")
    parser.add_argument("--tokens", type=int, default=DEFAULT_TOKENS)
    parser.add_argument("--chunk-tokens", type=int,
                        default=DEFAULT_CHUNK_TOKENS)
    parser.add_argument("--chunk-delay", type=float, default=0.0)
    args = parser.parse_args()
    try:
        asyncio.run(_serve(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
