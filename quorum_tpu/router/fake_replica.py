"""A jax-free scripted replica process — the router's test/bench upstream.

``python -m quorum_tpu.router.fake_replica --port 0`` serves a deterministic
OpenAI-compatible surface over the bundled h11 server: completions are a
pure function of the prompt (identical on every replica — the router bench's
token-for-token pinning rides this), and a REAL
:class:`~quorum_tpu.cache.prefix_store.PrefixStore` (tiny dummy payloads,
one uint8 array per chunk) tracks conversation prefixes exactly the way an
engine's host store does — same trie, same chunking, same LRU — so
affinity-vs-random hit rates measured against fake replicas use the same
store code paths as real ones, and ``GET/PUT /debug/prefix/chunks`` speaks
the real migration wire format (``cache/prefix_wire.py``).

Used by ``scripts/router_bench.py`` (fast mode), the chaos harness's
replica-kill drill (a killable process with slow streams), and
``tests/test_router.py``. Admin knobs for drills:

  POST /admin/shed      /ready answers 503 from now on (rotation trigger)
  POST /admin/recover   /ready answers 200 again
  POST /admin/abort?after=N   next stream dies (raises) after N content
                              chunks — an in-process mid-stream death
  POST /admin/diverge   resume submissions answer a divergence error chunk
                        (the replay-guard-mismatch drill; ?off clears)
  POST /admin/drain?park=0|1  gate admissions (503) + shed /ready; park=1
                              parks live streams (finish "parked") at the
                              next word boundary — GET polls progress,
                              POST /admin/undrain reopens

Resume semantics mirror the real backend (docs/robustness.md "Zero-loss
streams"): ``stream_token_ids`` attaches each chunk's token ids as
``qt_tokens`` (ByteTokenizer: one id per byte), and a ``resume_tokens``
journal is byte-compared against the scripted completion — a mismatch
(or the diverge knob) degrades to an error chunk tagged ``qt_error:
"resume_diverged"``, exactly the real replay guard's failure shape.

Fleet-plane surfaces (docs/observability.md) are scripted too: each state
owns a PRIVATE :class:`~quorum_tpu.telemetry.recorder.FlightRecorder`
(never the process singleton — in-process multi-replica tests would
otherwise pool every replica's events in one ring), requests honor/echo
W3C ``traceparent`` and record dispatch/reap events under the trace-id,
``GET /debug/engine/timeline`` and ``GET /debug/telemetry`` serve the
real endpoints' shapes, ``POST /admin/burn?class=&rate=`` scripts an SLO
burn rate (burn-aware routing drills), and ``--clock-skew`` shifts the
replica's reported monotonic clock AND its event stamps — so the
router's clock-offset estimation has real skew to cancel.

Boot prints ``PORT=<bound port>`` to stdout (``--port 0`` → ephemeral) so a
spawning parent can address it.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import time
from typing import Any, AsyncIterator

import numpy as np

from quorum_tpu import oai, sse
from quorum_tpu.cache import prefix_wire
from quorum_tpu.cache.prefix_store import PrefixStore
from quorum_tpu.engine.tokenizer import ByteTokenizer
from quorum_tpu.server.asgi import (
    App,
    JSONResponse,
    Request,
    Response,
    StreamingResponse,
)
from quorum_tpu.telemetry import tracecontext
from quorum_tpu.telemetry.recorder import FlightRecorder

DEFAULT_CHUNK_TOKENS = 16
DEFAULT_TOKENS = 8


def deterministic_completion(prompt: str, n_tokens: int) -> list[str]:
    """The scripted 'generation': a pure function of the prompt, so every
    replica (and a single-replica baseline) emits identical tokens."""
    digest = hashlib.sha256(prompt.encode()).digest()
    return [f"w{digest[i % len(digest)]:02x}" + (" " if i + 1 < n_tokens
                                                 else "")
            for i in range(max(1, n_tokens))]


class FakeReplicaState:
    """One fake replica's store + counters (shared by its routes)."""

    def __init__(self, name: str, chunk_tokens: int = DEFAULT_CHUNK_TOKENS,
                 max_tokens: int = DEFAULT_TOKENS,
                 chunk_delay: float = 0.0,
                 clock_skew: float = 0.0):
        self.name = name
        self.chunk_tokens = int(chunk_tokens)
        self.max_tokens = int(max_tokens)
        self.chunk_delay = float(chunk_delay)
        # Simulated monotonic-clock skew vs the host: added to the clock
        # /debug/telemetry reports AND to every recorder stamp, so the
        # router's offset estimate has something real to cancel (two
        # in-process fakes with different skews exercise the alignment).
        self.clock_skew = float(clock_skew)
        self.tokenizer = ByteTokenizer(259)
        self.store = PrefixStore(self.chunk_tokens, 1 << 24)
        # Private ring — NEVER the process singleton: in-process
        # multi-replica tests would pool every fake's events otherwise.
        self.recorder = FlightRecorder(capacity=1024, enabled=True)
        self.shedding = False
        # Scripted per-class SLO burn rates (POST /admin/burn) — what
        # /debug/telemetry exports, what burn-aware routing drills on.
        self.burn: dict[str, float] = {}
        self.requests = 0
        # Raw request bodies, in arrival order — tests assert on what the
        # router actually forwarded (e.g. that the quorum= knob never
        # reaches a replica).
        self.seen_bodies: list[dict] = []
        self.prefix_hits = 0
        self.tokens_restored = 0
        # Drill knobs + drain lifecycle (module docstring).
        self.abort_after: int | None = None  # one-shot mid-stream death
        self.diverge_resume = False
        self.draining = False
        self.park_streams = False
        self.active_streams = 0
        self.n_parked = 0

    def clock(self) -> float:
        """This replica's (possibly skewed) monotonic clock."""
        return time.perf_counter() + self.clock_skew

    def _dummy_payloads(self, n_chunks: int) -> list[list[np.ndarray]]:
        return [[np.zeros((1, 1, self.chunk_tokens), dtype=np.uint8)]
                for _ in range(n_chunks)]

    def observe(self, prompt_text: str, completion: str) -> int:
        """Record the request against the store: a hit when the prompt's
        prefix chain is already held (an earlier turn, or a migrated
        seed), then retain prompt+completion — the engine's
        snapshot-on-release, scripted. Returns matched tokens."""
        self.requests += 1
        ids = self.tokenizer.encode(prompt_text)
        matched, _ = self.store.longest_match(ids)
        if matched >= self.chunk_tokens:
            self.prefix_hits += 1
            self.tokens_restored += matched
        full = ids + self.tokenizer.encode(completion)
        n_chunks = len(full) // self.chunk_tokens
        if n_chunks:
            self.store.import_chain(full, self._dummy_payloads(n_chunks))
        return matched


def create_fake_replica_app(state: FakeReplicaState) -> App:
    app = App()
    app.state["fake"] = state

    @app.route("POST", "/chat/completions", "/v1/chat/completions")
    async def chat(request: Request) -> Response:
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be an object")
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"Invalid JSON body: {e}",
                           "type": "invalid_request_error"}},
                status_code=400)
        state.seen_bodies.append(dict(body))
        if state.shedding or state.draining:
            return JSONResponse(
                {"error": {"message": ("engine draining" if state.draining
                                       else "shedding (admin)"),
                           "type": "overloaded_error"}},
                status_code=503, headers={"Retry-After": "1"})
        # Cross-tier trace identity, scripted like the real server:
        # honor the router's traceparent (header first, body knob
        # second), mint when absent, echo on the response, and stamp
        # every recorder event with the trace-id — the fleet-timeline
        # merge joins on it.
        parsed = tracecontext.parse_traceparent(
            request.headers.get("traceparent"))
        if parsed is None:
            parsed = tracecontext.parse_traceparent(
                body.get("traceparent"))
        trace_id = parsed[0] if parsed else tracecontext.new_trace_id()
        span_id, traceparent = tracecontext.child_traceparent(trace_id)
        messages = body.get("messages") or []
        prompt = state.tokenizer.render_chat(
            [m for m in messages if isinstance(m, dict)])
        n = int(body.get("max_tokens") or state.max_tokens)
        words = deterministic_completion(prompt, min(n, state.max_tokens))
        completion = "".join(words)
        matched = state.observe(prompt, completion)
        model = body.get("model") or "fake"
        # Cross-replica resume, scripted like the real replay guard: the
        # journal must be a byte-exact prefix of THIS replica's scripted
        # completion (ByteTokenizer: one id per char), and the delivered
        # char count must land inside it — anything else (or the admin
        # diverge knob) is the distinct divergence failure.
        rt = body.get("resume_tokens")
        skip_chars = 0
        diverged = False
        if rt:
            rc = body.get("resume_chars")
            skip_chars = int(rc) if rc is not None else len(rt)
            full_ids = state.tokenizer.encode(completion)
            if (state.diverge_resume or list(rt) != full_ids[:len(rt)]
                    or skip_chars > len(completion)):
                diverged = True
        want_ids = bool(body.get("stream_token_ids"))
        want_usage = bool(
            (body.get("stream_options") or {}).get("include_usage"))
        t_issue = state.clock()
        state.recorder.record("dispatch", rid=trace_id, engine=state.name,
                              loop="decode", t=t_issue, family="fake",
                              span=span_id)
        if body.get("stream"):
            resp = StreamingResponse(
                _stream(model, words, matched, trace_id, t_issue,
                        skip_chars=skip_chars, want_ids=want_ids,
                        diverged=diverged,
                        prompt_tokens=len(prompt) if want_usage else None))
            resp.headers["X-Fake-Replica"] = state.name
            resp.headers["traceparent"] = traceparent
            return resp
        t_ready = state.clock()
        state.recorder.record("reap", rid=trace_id, engine=state.name,
                              loop="decode", t=t_ready, t_issue=t_issue,
                              t_ready=t_ready, family="fake", depth=0,
                              tokens=len(words))
        resp = oai.completion(
            content=completion, model=model,
            usage={"prompt_tokens": len(prompt),
                   "completion_tokens": len(words),
                   "total_tokens": len(prompt) + len(words)})
        resp["backend"] = state.name
        return JSONResponse(resp, headers={
            "X-Fake-Replica": state.name,
            "X-Prefix-Matched": str(matched),
            "traceparent": traceparent})

    async def _stream(model: str, words: list[str], matched: int,
                      trace_id: str, t_issue: float, *,
                      skip_chars: int = 0, want_ids: bool = False,
                      diverged: bool = False,
                      prompt_tokens: int | None = None,
                      ) -> AsyncIterator[bytes]:
        cid = f"chatcmpl-{state.name}"
        yield sse.encode_event(
            oai.chunk(id=cid, model=model, delta={"role": "assistant"}))
        if diverged:
            # The real replay guard's failure shape: the server wraps the
            # engine's ReplayDivergence in an error chunk carrying the
            # structured ``qt_error: "resume_diverged"`` marker — the
            # router keys its degrade on that, never on message text.
            yield sse.encode_event(oai.error_chunk(
                "Backend failed: resume replay diverged: journal is not "
                "a prefix of this replica's stream", model=model,
                code="resume_diverged"))
            yield sse.encode_done()
            t_ready = state.clock()
            state.recorder.record(
                "reap", rid=trace_id, engine=state.name, loop="decode",
                t=t_ready, t_issue=t_issue, t_ready=t_ready,
                family="fake", depth=0, tokens=0)
            return
        sent = 0
        new_chars = 0
        parked = False
        state.active_streams += 1
        try:
            rem = skip_chars  # delivered prefix: resumed streams skip it
            for w in words:
                if rem >= len(w):
                    rem -= len(w)
                    continue
                piece, rem = w[rem:], 0
                if state.park_streams:
                    # Drain park at a word boundary — the finish tells
                    # the router to resume elsewhere; no error, no tail.
                    parked = True
                    break
                if state.chunk_delay:
                    await asyncio.sleep(state.chunk_delay)
                if state.abort_after is not None \
                        and sent >= state.abort_after:
                    # One-shot scripted mid-stream death (in-process
                    # equivalent of the SIGKILL drill).
                    state.abort_after = None
                    raise RuntimeError("aborted mid-stream (admin)")
                out = oai.chunk(id=cid, model=model,
                                delta={"content": piece})
                if want_ids:
                    out["qt_tokens"] = state.tokenizer.encode(piece)
                yield sse.encode_event(out)
                sent += 1
                new_chars += len(piece)
            if parked:
                state.n_parked += 1
                yield sse.encode_event(
                    oai.chunk(id=cid, model=model, delta={},
                              finish_reason="parked"))
            else:
                yield sse.encode_event(
                    oai.chunk(id=cid, model=model, delta={},
                              finish_reason="stop"))
                if prompt_tokens is not None:
                    # stream_options.include_usage, real-backend shaped:
                    # completion counts NEW tokens only — the router owns
                    # the union across a resume splice.
                    uc = oai.chunk(id=cid, model=model, delta={})
                    uc["choices"] = []
                    uc["usage"] = {
                        "prompt_tokens": prompt_tokens,
                        "completion_tokens": new_chars,
                        "total_tokens": prompt_tokens + new_chars}
                    yield sse.encode_event(uc)
            yield sse.encode_done()
        finally:
            state.active_streams -= 1
            # Reap lands however the stream ends — a killed/broken
            # stream still leaves its span in the ring (the chaos drill
            # asserts the failed-over trace-id appears on the survivor).
            t_ready = state.clock()
            state.recorder.record(
                "reap", rid=trace_id, engine=state.name, loop="decode",
                t=t_ready, t_issue=t_issue, t_ready=t_ready,
                family="fake", depth=0, tokens=sent)

    @app.route("GET", "/health", "/v1/health")
    async def health(request: Request) -> Response:
        return JSONResponse({"status": "healthy", "fake": True})

    @app.route("GET", "/ready", "/v1/ready")
    async def ready(request: Request) -> Response:
        if state.shedding or state.draining:
            return JSONResponse(
                {"status": "unready",
                 "reason": "draining" if state.draining else "shedding"},
                status_code=503, headers={"Retry-After": "1"})
        return JSONResponse({"status": "ready"})

    @app.route("POST", "/admin/shed", "/v1/admin/shed")
    async def shed(request: Request) -> Response:
        state.shedding = True
        return JSONResponse({"shedding": True})

    @app.route("POST", "/admin/recover", "/v1/admin/recover")
    async def recover(request: Request) -> Response:
        state.shedding = False
        return JSONResponse({"shedding": False})

    @app.route("POST", "/admin/abort", "/v1/admin/abort")
    async def admin_abort(request: Request) -> Response:
        """One-shot scripted mid-stream death: the next stream raises
        after ``?after=N`` content chunks (default 1) — the in-process
        stand-in for the SIGKILL drill."""
        raw = request.query_params.get("after", "1")
        try:
            state.abort_after = max(0, int(raw))
        except ValueError:
            return JSONResponse(
                {"error": {"message": f"'after' must be an integer, got "
                           f"{raw!r}", "type": "invalid_request_error"}},
                status_code=400)
        return JSONResponse({"abort_after": state.abort_after})

    @app.route("POST", "/admin/diverge", "/v1/admin/diverge")
    async def admin_diverge(request: Request) -> Response:
        """Make resume submissions fail the scripted replay guard
        (``?off=1`` clears) — the divergence-degrade drill's lever."""
        state.diverge_resume = request.query_params.get("off") is None
        return JSONResponse({"diverge_resume": state.diverge_resume})

    @app.route("POST", "/admin/drain", "/v1/admin/drain")
    async def admin_drain(request: Request) -> Response:
        state.draining = True
        if request.query_params.get("park", "0") not in ("0", "", None):
            state.park_streams = True
        return JSONResponse({"draining": True,
                             "park": state.park_streams,
                             "resident": state.active_streams,
                             "parked_total": state.n_parked})

    @app.route("GET", "/admin/drain", "/v1/admin/drain")
    async def admin_drain_status(request: Request) -> Response:
        return JSONResponse({"draining": state.draining,
                             "park": state.park_streams,
                             "resident": state.active_streams,
                             "parked_total": state.n_parked})

    @app.route("POST", "/admin/undrain", "/v1/admin/undrain")
    async def admin_undrain(request: Request) -> Response:
        state.draining = False
        state.park_streams = False
        return JSONResponse({"draining": False})

    @app.route("POST", "/admin/burn", "/v1/admin/burn")
    async def admin_burn(request: Request) -> Response:
        """Script an SLO burn rate: ``?class=interactive&rate=0.9`` makes
        /debug/telemetry report it until overwritten (rate <= 0 clears) —
        the burn-aware-routing drill's lever."""
        cls = request.query_params.get("class", "interactive")
        raw = request.query_params.get("rate", "")
        try:
            rate = float(raw)
        except ValueError:
            return JSONResponse(
                {"error": {"message": f"'rate' must be a number, got "
                           f"{raw!r}", "type": "invalid_request_error"}},
                status_code=400)
        if rate <= 0:
            state.burn.pop(cls, None)
        else:
            state.burn[cls] = rate
        return JSONResponse({"burn": dict(state.burn)})

    @app.route("GET", "/debug/telemetry", "/v1/debug/telemetry")
    async def telemetry(request: Request) -> Response:
        """The real server's /debug/telemetry shape, with scripted burn
        and (optionally) a skewed clock sample."""
        return JSONResponse({
            "clock": state.clock(),
            "time": time.time(),
            "status": "degraded" if state.shedding else "healthy",
            "slo": {cls: {"burn_rate": rate, "stages": {}}
                    for cls, rate in state.burn.items()},
            "queue_depth": 0,
            "breaker": {state.name: "closed"},
            "latency": {},
            "prefix_store_bytes": state.store.bytes_held,
        })

    @app.route("GET", "/debug/engine/timeline",
               "/v1/debug/engine/timeline")
    async def timeline(request: Request) -> Response:
        """The private recorder, in the real endpoint's JSON/perfetto
        forms — what the router's /debug/fleet/timeline fetches."""
        fmt = request.query_params.get("format", "json")
        if fmt in ("perfetto", "trace", "chrome"):
            return JSONResponse(
                {"displayTimeUnit": "ms",
                 "traceEvents": state.recorder.to_trace_events()})
        if fmt != "json":
            return JSONResponse(
                {"error": {"message": f"unknown format {fmt!r} "
                           "(json or perfetto)",
                           "type": "invalid_request_error"}},
                status_code=400)
        return JSONResponse({
            "clock": "perf_counter",
            "capacity": state.recorder.capacity,
            "recorded_total": state.recorder.total(),
            "events": state.recorder.snapshot(),
            "device_time": {},
            "slo": {},
        })

    @app.route("GET", "/metrics", "/v1/metrics")
    async def metrics(request: Request) -> Response:
        n = state.name
        lines = [
            "# TYPE quorum_tpu_engine_requests_total counter",
            f'quorum_tpu_engine_requests_total{{backend="{n}"}} '
            f"{state.requests}",
            "# TYPE quorum_tpu_engine_prefix_store_hits_total counter",
            f'quorum_tpu_engine_prefix_store_hits_total{{backend="{n}"}} '
            f"{state.prefix_hits}",
            "# TYPE quorum_tpu_engine_prefix_store_restored_tokens_total "
            "counter",
            f"quorum_tpu_engine_prefix_store_restored_tokens_total"
            f'{{backend="{n}"}} {state.tokens_restored}',
            "# TYPE quorum_tpu_engine_prefix_store_bytes gauge",
            f'quorum_tpu_engine_prefix_store_bytes{{backend="{n}"}} '
            f"{state.store.bytes_held}",
            "# TYPE quorum_tpu_engine_prefix_store_entries gauge",
            f'quorum_tpu_engine_prefix_store_entries{{backend="{n}"}} '
            f"{state.store.n_entries}",
        ]
        return Response(("\n".join(lines) + "\n").encode(),
                        media_type="text/plain; version=0.0.4")

    @app.route("GET", "/debug/prefix/chunks", "/v1/debug/prefix/chunks")
    async def export_chunks(request: Request) -> Response:
        blob = prefix_wire.serialize_chains(
            state.store.export_chains(), state.chunk_tokens)
        return Response(blob, media_type="application/octet-stream",
                        headers={"X-Prefix-Chunk-Tokens":
                                 str(state.chunk_tokens)})

    @app.route("PUT", "/debug/prefix/chunks", "/v1/debug/prefix/chunks")
    async def import_chunks(request: Request) -> Response:
        try:
            chunk_tokens, chains = prefix_wire.parse(await request.body())
            if chunk_tokens != state.chunk_tokens:
                raise prefix_wire.WireError(
                    f"chunk_tokens={chunk_tokens} != "
                    f"{state.chunk_tokens}")
        except prefix_wire.WireError as e:
            return JSONResponse(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status_code=400)
        imported = 0
        for chain in chains:
            imported += state.store.import_chain(chain.tokens,
                                                 chain.payloads)
        return JSONResponse({"chains": len(chains),
                             "tokens_imported": imported,
                             "store_entries": state.store.n_entries})

    return app


async def _serve(args) -> None:
    from quorum_tpu.server.serve import start_server

    state = FakeReplicaState(
        args.name, chunk_tokens=args.chunk_tokens,
        max_tokens=args.tokens, chunk_delay=args.chunk_delay,
        clock_skew=args.clock_skew)
    app = create_fake_replica_app(state)
    server = await start_server(app, args.host, args.port)
    port = server.sockets[0].getsockname()[1]
    print(f"PORT={port}", flush=True)
    async with server:
        await server.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="deterministic jax-free fake replica")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--name", default="fake")
    parser.add_argument("--tokens", type=int, default=DEFAULT_TOKENS)
    parser.add_argument("--chunk-tokens", type=int,
                        default=DEFAULT_CHUNK_TOKENS)
    parser.add_argument("--chunk-delay", type=float, default=0.0)
    parser.add_argument("--clock-skew", type=float, default=0.0,
                        help="simulated monotonic-clock skew (seconds) on "
                             "telemetry + recorder stamps")
    args = parser.parse_args()
    try:
        asyncio.run(_serve(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
