"""Per-replica state + the replica set manager (polling, rotation, migration).

Each upstream engine cell is one :class:`Replica`: an
:class:`~quorum_tpu.backends.http_backend.HttpBackend` for the data plane
(pooled client, capped-exponential retries, Retry-After pacing — the PR 4
machinery, reused not reinvented), a per-replica
:class:`~quorum_tpu.breaker.Breaker` (repeated pre-stream failures take the
replica out of contention until a cooldown probe lands), and an in-flight
counter feeding the ring's bounded-load spill.

:class:`ReplicaSet` owns membership: a background poller consumes each
replica's ``GET /ready`` — the engine's truthful shedding signal — and a
replica answering unready ROTATES OUT of the consistent-hash ring (its key
ranges spill to its clockwise successors; everyone else's placement is
untouched). If the rotating replica is still reachable (shedding, not
dead), the poller migrates its hot prefixes first: fetch the serialized
chunk chains (``GET /debug/prefix/chunks``), re-key each chain through the
post-rotation ring, and seed the successors (``PUT``) so the conversations
that spill arrive to a warm tier-1 store instead of paying cold prefill.
A replica answering ready again rejoins the ring — and reclaims its key
ranges, where its own store is still warmest.
"""

from __future__ import annotations

import asyncio
import logging
import time

import httpx

from quorum_tpu.backends.http_backend import HttpBackend
from quorum_tpu.breaker import Breaker
from quorum_tpu.cache import prefix_wire
from quorum_tpu.observability import (
    ROUTER_BURN_DEMOTIONS,
    ROUTER_MIGRATED_BYTES,
    ROUTER_MIGRATED_CHAINS,
    ROUTER_REPLICA_BURN,
    TELEMETRY_POLL_SECONDS,
)
from quorum_tpu.router import affinity
from quorum_tpu.router.ring import BoundedLoadRing
from quorum_tpu.router.telemetry_view import TelemetryView
from quorum_tpu.telemetry.recorder import RECORDER

logger = logging.getLogger(__name__)

# Control-plane timeouts (data-plane calls carry the request's own budget).
READY_TIMEOUT_S = 3.0
MIGRATE_TIMEOUT_S = 30.0
TIMELINE_TIMEOUT_S = 10.0  # recorder snapshots can be ~1 MB of JSON


class Replica:
    """One engine cell behind the router."""

    def __init__(self, name: str, url: str, *, retries: int = 1,
                 breaker: Breaker | None = None,
                 client: httpx.AsyncClient | None = None):
        self.name = name
        self.url = url.rstrip("/")
        self.backend = HttpBackend(name, url, model="", client=client,
                                   retries=retries)
        self.breaker = breaker or Breaker()
        self.ready = True          # last /ready verdict (optimistic start)
        self.reachable = True      # the last probe got ANY HTTP answer
        self.inflight = 0          # router-side in-flight (bounded load)
        self.requests = 0

    def state(self) -> dict:
        return {
            "name": self.name,
            "url": self.url,
            "ready": self.ready,
            "reachable": self.reachable,
            "breaker": self.breaker.state,
            "inflight": self.inflight,
            "requests": self.requests,
        }


class ReplicaSet:
    """Membership + placement + rotation for a set of replicas."""

    def __init__(self, replicas: list[Replica], *,
                 vnodes: int = 64, load_factor: float = 1.25,
                 affinity_chunk: int = affinity.DEFAULT_AFFINITY_CHUNK,
                 ready_interval: float = 2.0,
                 migrate_on_rotation: bool = True,
                 burn_threshold: float = 0.5,
                 burn_class: str = "interactive",
                 telemetry_max_age: float = 10.0,
                 control_client: httpx.AsyncClient | None = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas: dict[str, Replica] = {r.name: r for r in replicas}
        self.ring = BoundedLoadRing(vnodes=vnodes, load_factor=load_factor)
        for r in replicas:
            self.ring.add(r.name)
        self.affinity_chunk = int(affinity_chunk)
        self.ready_interval = float(ready_interval)
        self.migrate_on_rotation = bool(migrate_on_rotation)
        # Burn-aware placement (docs/observability.md "Fleet plane"): a
        # replica whose ``burn_class`` SLO burn rate exceeds
        # ``burn_threshold`` (fraction of scored objectives breached over
        # the replica's sliding window) is demoted per placement;
        # ``burn_threshold <= 0`` disables the behavior entirely.
        self.burn_threshold = float(burn_threshold)
        self.burn_class = str(burn_class)
        self.telemetry = TelemetryView(max_age_s=telemetry_max_age)
        self.n_burn_demotions = 0
        self._control = control_client or httpx.AsyncClient()
        self._poll_task: asyncio.Task | None = None
        self._transition_lock = asyncio.Lock()
        self.n_migrations = 0

    # ---- placement ---------------------------------------------------------

    def loads(self) -> dict[str, int]:
        return {name: r.inflight for name, r in self.replicas.items()}

    def burn_demoted(self, slo_class: str | None = None) -> set[str]:
        """Ring members whose burn rate, per the LAST absorbed telemetry,
        exceeds the threshold. Scored classes are the UNION of the
        configured ``burn_class`` and the request's own SLO class
        (``slo_class`` — the QoS dispatch class mapped onto the SLO
        plane's two scoring classes): an interactive-burning replica is
        demoted for everyone (the configured floor), and a batch request
        additionally avoids replicas burning their batch objective.
        Fail-open: absent or stale telemetry (``None`` burn) never
        demotes — a replica that stops exporting telemetry keeps plain
        bounded-load routing, it does not lose placements to an
        observability outage."""
        if self.burn_threshold <= 0:
            return set()
        classes = {self.burn_class}
        if slo_class:
            classes.add(slo_class)
        demoted: set[str] = set()
        for name in self.ring.members:
            for cls in classes:
                rate = self.telemetry.burn_rate(name, cls)
                if rate is not None and rate > self.burn_threshold:
                    demoted.add(name)
                    break
        return demoted

    def placement(self, key: int,
                  slo_class: str | None = None) -> tuple[str | None, list[str]]:
        """``(affinity primary, candidate order)`` for a conversation key.
        The primary is membership-pure (what the hit/miss accounting
        compares against); the candidate order additionally folds in
        bounded load and SLO-burn demotion (both per-request reorderings
        — membership, and every other key's placement, untouched).
        ``slo_class`` widens burn demotion to the request's own class
        (see :meth:`burn_demoted`)."""
        demoted = self.burn_demoted(slo_class)
        candidates = self.ring.candidates(key, self.loads(),
                                          demoted=demoted)
        for name in demoted:
            # Counted per placement in which the replica actually lost
            # its position — only when it would otherwise have been a
            # candidate at all.
            if name in candidates:
                self.n_burn_demotions += 1
                ROUTER_BURN_DEMOTIONS.inc(replica=name)
        return (self.ring.primary(key), candidates)

    # ---- readiness polling -------------------------------------------------

    async def ensure_poller(self) -> None:
        """Start the background /ready poller lazily (the app has no
        lifespan hook under the bundled h11 server); idempotent, no-op
        when polling is disabled (``ready_interval <= 0``)."""
        if self.ready_interval <= 0:
            return
        if self._poll_task is None or self._poll_task.done():
            self._poll_task = asyncio.get_running_loop().create_task(
                self._poll_loop())

    async def _poll_loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception:
                logger.exception("replica readiness poll failed")
            await asyncio.sleep(self.ready_interval)

    async def poll_once(self) -> None:
        """One readiness sweep: probe every replica's /ready, rotate the
        ring on transitions (unready → out + migrate; ready → back in)."""
        async with self._transition_lock:
            for r in list(self.replicas.values()):
                try:
                    resp = await self._control.get(
                        f"{r.url}/ready", timeout=READY_TIMEOUT_S)
                    now_ready = resp.status_code == 200
                    reachable = True
                except Exception:
                    now_ready = False
                    reachable = False
                was_in = r.name in self.ring
                r.reachable = reachable
                r.ready = now_ready
                if was_in and not now_ready:
                    self.ring.remove(r.name)
                    RECORDER.record("router-replica-out", loop="router",
                                    replica=r.name, reachable=reachable)
                    logger.warning(
                        "replica %s rotated OUT of the ring (%s)", r.name,
                        "shedding" if reachable else "unreachable")
                    if reachable and self.migrate_on_rotation and \
                            len(self.ring):
                        try:
                            await self.migrate_from(r.name)
                        except Exception:
                            logger.exception(
                                "prefix migration from %s failed (best "
                                "effort — spilled conversations prefill "
                                "cold)", r.name)
                elif not was_in and now_ready:
                    self.ring.add(r.name)
                    RECORDER.record("router-replica-in", loop="router",
                                    replica=r.name)
                    logger.info("replica %s rejoined the ring", r.name)
                if reachable:
                    await self._pull_telemetry(r)

    async def _pull_telemetry(self, r: Replica) -> None:
        """Absorb one replica's /debug/telemetry into the view. Strictly
        best-effort: replicas predating the endpoint (404) or timing out
        just leave their entry to go stale — burn demotion then fails
        open and the fleet timeline falls back to raw timebases."""
        t0 = time.perf_counter()
        try:
            resp = await self._control.get(
                f"{r.url}/debug/telemetry", timeout=READY_TIMEOUT_S)
            t1 = time.perf_counter()
            if resp.status_code != 200:
                return
            body = resp.json()
        except Exception:
            return
        TELEMETRY_POLL_SECONDS.observe(t1 - t0)
        self.telemetry.absorb(r.name, body, t0, t1)
        for cls, rate in self.telemetry.burn_rates(r.name).items():
            ROUTER_REPLICA_BURN.set(rate, replica=r.name, slo_class=cls)

    # ---- fleet timeline ----------------------------------------------------

    async def fetch_timelines(self) -> list[dict]:
        """Pull every reachable replica's flight-recorder snapshot
        (``GET /debug/engine/timeline``) for the fleet-timeline merge.
        Returns one row per replica that answered:
        ``{"name", "events", "offset", "clock_aligned"}`` — ``offset``
        is the TelemetryView's clock-offset estimate (router
        perf_counter − replica perf_counter; None when telemetry is
        stale, in which case the merger leaves that replica's events on
        their raw timebase and flags ``clock_aligned: false``).
        Best-effort per replica: one slow or dead replica costs its own
        rows, never the merge."""
        rows: list[dict] = []
        for name, r in sorted(self.replicas.items()):
            if not r.reachable:
                continue
            try:
                resp = await self._control.get(
                    f"{r.url}/debug/engine/timeline",
                    timeout=TIMELINE_TIMEOUT_S)
                if resp.status_code != 200:
                    continue
                body = resp.json()
            except Exception:
                continue
            events = body.get("events") if isinstance(body, dict) else None
            if not isinstance(events, list):
                continue
            offset = self.telemetry.offset(name)
            rows.append({
                "name": name,
                "events": events,
                "offset": offset,
                "clock_aligned": offset is not None,
            })
        return rows

    # ---- prefix migration --------------------------------------------------

    async def migrate_from(self, name: str,
                           to: str | None = None) -> dict:
        """Move ``name``'s hot prefix chains to their post-rotation homes:
        fetch the serialized store, re-key every chain through the CURRENT
        ring (which no longer contains ``name`` when it was rotated out —
        or pin everything to ``to``), and seed each target replica.
        Best-effort by design: any failure loses warmth, never
        correctness (the successor simply prefills cold)."""
        src = self.replicas[name]
        resp = await self._control.get(
            f"{src.url}/debug/prefix/chunks", timeout=MIGRATE_TIMEOUT_S)
        if resp.status_code != 200:
            return {"migrated_chains": 0, "migrated_bytes": 0,
                    "skipped": f"source export HTTP {resp.status_code}"}
        blob = resp.content
        chunk_tokens, chains = prefix_wire.parse(blob)
        groups: dict[str, list] = {}
        for chain in chains:
            target = to or self.ring.primary(
                affinity.chain_key(chain.tokens, self.affinity_chunk))
            if target is None or target == name:
                continue
            groups.setdefault(target, []).append(chain)
        moved_chains = 0
        moved_bytes = 0
        t0 = time.perf_counter()
        for target, group in groups.items():
            dst = self.replicas.get(target)
            if dst is None:
                continue
            out = prefix_wire.serialize_chains(
                [(c.tokens, c.payloads) for c in group], chunk_tokens)
            try:
                put = await self._control.put(
                    f"{dst.url}/debug/prefix/chunks", content=out,
                    headers={"Content-Type": "application/octet-stream"},
                    timeout=MIGRATE_TIMEOUT_S)
            except Exception:
                logger.exception("prefix seed PUT to %s failed", target)
                continue
            if put.status_code == 200:
                moved_chains += len(group)
                moved_bytes += len(out)
        dt = time.perf_counter() - t0
        ROUTER_MIGRATED_BYTES.inc(moved_bytes)
        ROUTER_MIGRATED_CHAINS.inc(moved_chains)
        self.n_migrations += 1
        RECORDER.record("router-migrate", loop="router", replica=name,
                        chains=moved_chains, bytes=moved_bytes,
                        targets=sorted(groups), seconds=round(dt, 4))
        logger.info(
            "migrated %d prefix chains (%d bytes) from %s to %s in %.3fs",
            moved_chains, moved_bytes, name, sorted(groups), dt)
        return {"migrated_chains": moved_chains,
                "migrated_bytes": moved_bytes,
                "targets": sorted(groups)}

    # ---- graceful drain ----------------------------------------------------

    DRAIN_POLL_S = 0.1
    DRAIN_TIMEOUT_S = 30.0

    async def drain(self, name: str) -> dict:
        """Gracefully drain one replica with zero failed requests
        (docs/robustness.md "Zero-loss streams"): (1) remove it from the
        ring FIRST — pre-removal, under the transition lock, so the
        /ready poller's was_in→unready transition never fires and
        double-migrates; (2) ``POST /admin/drain?park=1`` — the replica
        sheds admissions and parks its live streams, each of which the
        data plane proactively resumes on a sibling (the ``parked``
        finish is the signal); (3) poll ``GET /admin/drain`` until no
        stream is resident (bounded); (4) migrate its prefix chains to
        the ring survivors (the PR 12 path). The replica stays configured
        (undrain + /ready recovery bring it back)."""
        r = self.replicas[name]
        async with self._transition_lock:
            if name in self.ring:
                self.ring.remove(name)
                RECORDER.record("router-replica-out", loop="router",
                                replica=name, reachable=r.reachable,
                                drain=True)
        resp = await self._control.post(
            f"{r.url}/admin/drain", params={"park": "1"},
            timeout=READY_TIMEOUT_S)
        if resp.status_code != 200:
            return {"replica": name, "drained": False,
                    "error": f"drain request HTTP {resp.status_code}"}
        r.ready = False
        deadline = time.perf_counter() + self.DRAIN_TIMEOUT_S
        resident = None
        while time.perf_counter() < deadline:
            try:
                status = await self._control.get(
                    f"{r.url}/admin/drain", timeout=READY_TIMEOUT_S)
                resident = (status.json() or {}).get("resident")
            except Exception:
                resident = None
            if resident == 0:
                break
            await asyncio.sleep(self.DRAIN_POLL_S)
        migrated: dict = {}
        if len(self.ring):
            try:
                migrated = await self.migrate_from(name)
            except Exception:
                logger.exception(
                    "prefix migration from draining %s failed (best "
                    "effort)", name)
        out = {"replica": name, "drained": resident == 0,
               "resident": resident, **migrated}
        RECORDER.record("router-drain", loop="router", replica=name,
                        drained=resident == 0, resident=resident,
                        chains=migrated.get("migrated_chains", 0))
        logger.info("drained replica %s: %s", name, out)
        return out

    # ---- teardown ----------------------------------------------------------

    async def aclose(self) -> None:
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except (asyncio.CancelledError, Exception):
                pass
            self._poll_task = None
        for r in self.replicas.values():
            await r.backend.aclose()
        await self._control.aclose()
