"""Bounded-load consistent hashing over replica names.

The placement primitive of the router tier: conversation keys hash onto a
ring of virtual nodes (many per replica, so key ranges are fine-grained),
each key's *primary* is the first replica clockwise from its hash point,
and a replica leaving the ring spills exactly its own key ranges onto the
clockwise successors — every other conversation keeps its replica, which
is the whole point (a naive ``hash % N`` remap would cold-start (N−1)/N of
all conversations' prefix caches on every membership change).

Bounded load (the consistent-hashing-with-bounded-loads construction,
Mirrokni et al. — Google's Maglev/Vimeo production variant): a hot key
range must not melt one replica while its neighbors idle, so a candidate
already carrying more than ``load_factor ×`` the mean in-flight load is
skipped and the key spills to the next candidate FOR THIS REQUEST ONLY —
membership, and therefore every other key's placement, is untouched. The
spill is a deliberate affinity miss under overload: a cold prefill beats
queueing behind the hot spot.

Pure data structure — no I/O, no clocks; the router's replica manager owns
membership transitions and feeds in live loads.
"""

from __future__ import annotations

import bisect
import hashlib
import math

DEFAULT_VNODES = 64
DEFAULT_LOAD_FACTOR = 1.25


def hash_key(data: bytes) -> int:
    """Stable 64-bit ring position for a key (blake2b — fast, stdlib,
    uniform; NOT Python's hash(), which is salted per process)."""
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


class BoundedLoadRing:
    """Consistent-hash ring with bounded-load candidate ordering."""

    def __init__(self, vnodes: int = DEFAULT_VNODES,
                 load_factor: float = DEFAULT_LOAD_FACTOR):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        if load_factor < 1.0:
            raise ValueError(
                f"load_factor must be >= 1.0, got {load_factor}")
        self.vnodes = int(vnodes)
        self.load_factor = float(load_factor)
        self._points: list[tuple[int, str]] = []  # sorted (position, name)
        self._names: set[str] = set()

    def __len__(self) -> int:
        return len(self._names)

    def __contains__(self, name: str) -> bool:
        return name in self._names

    @property
    def members(self) -> set[str]:
        return set(self._names)

    def add(self, name: str) -> None:
        if name in self._names:
            return
        self._names.add(name)
        for i in range(self.vnodes):
            point = hash_key(f"{name}#{i}".encode())
            bisect.insort(self._points, (point, name))

    def remove(self, name: str) -> None:
        if name not in self._names:
            return
        self._names.discard(name)
        self._points = [(p, n) for p, n in self._points if n != name]

    def primary(self, key: int) -> str | None:
        """The replica ``key`` hashes to with membership alone — no load
        bound, no failover. This is the affinity home the hit/miss
        accounting compares against."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, (key, "￿"))
        return self._points[i % len(self._points)][1]

    def candidates(self, key: int,
                   loads: dict[str, int] | None = None,
                   demoted: set[str] | None = None) -> list[str]:
        """Every ring member, ordered for this key: the clockwise walk
        from the key's hash point (primary first, then the successors its
        range would spill to), with members past the bounded-load capacity
        demoted to the tail — still eligible (a failover target of last
        resort beats a 503) but only after every underloaded member.

        ``loads`` is in-flight requests per replica; capacity is
        ``ceil(load_factor × (total + 1) / n)`` counting the request being
        placed, so with uniform load nothing is ever demoted.

        ``demoted`` names members to push behind every non-demoted one —
        the burn-aware placement hook (docs/observability.md): a replica
        whose SLO burn exceeds the router's threshold loses first-choice
        placements exactly like an overloaded one, per request, with
        membership untouched. Applied after the load bound, preserving
        relative order within each partition, so a replica both overloaded
        AND burning sinks to the very tail."""
        if not self._points:
            return []
        order: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_right(self._points, (key, "￿"))
        n_points = len(self._points)
        for off in range(n_points):
            name = self._points[(start + off) % n_points][1]
            if name not in seen:
                seen.add(name)
                order.append(name)
                if len(order) == len(self._names):
                    break
        if loads:
            total = sum(loads.get(n, 0) for n in order) + 1
            cap = math.ceil(self.load_factor * total / len(order))
            fits = [n for n in order if loads.get(n, 0) < cap]
            over = [n for n in order if loads.get(n, 0) >= cap]
            order = fits + over
        if demoted:
            keep = [n for n in order if n not in demoted]
            burn = [n for n in order if n in demoted]
            order = keep + burn
        return order
