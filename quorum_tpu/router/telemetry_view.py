"""Per-replica telemetry absorbed by the router's /ready poller.

The fleet plane's router-side state (docs/observability.md "Fleet
plane"): every poll of a live replica also pulls ``GET /debug/telemetry``
— per-class SLO burn, queue depth, breaker state, latency models, and a
sample of the replica's ``perf_counter`` clock — and absorbs it here.
Two consumers read the view:

  - **burn-aware placement** (`ReplicaSet.placement`): a replica whose
    interactive-class burn rate exceeds the router's ``burn_threshold``
    is demoted to the tail of the candidate order — per-request
    reordering exactly like bounded-load demotion, membership untouched.
  - **fleet-timeline merging** (`GET /debug/fleet/timeline`): each
    replica's flight-recorder stamps ride its own monotonic clock; the
    estimated ``offset`` (router perf_counter minus replica perf_counter,
    midpoint method over the poll's request/response stamps) aligns them
    onto the router's timebase.

Staleness-bounded and fail-open by design: entries older than
``max_age_s`` (default 10 s — a few poll intervals) answer ``None`` for
everything, and a ``None`` burn rate never demotes. A replica that stops
answering telemetry quietly returns to plain bounded-load routing — the
observability plane must not become a novel way to shed healthy
capacity.

jax-free (imported by the router tier, which never loads jax).
"""

from __future__ import annotations

import threading
import time
from typing import Any


class TelemetryView:
    """Staleness-bounded map of replica name -> last absorbed telemetry."""

    def __init__(self, max_age_s: float = 10.0):
        self.max_age_s = float(max_age_s)
        self._lock = threading.Lock()
        # name -> {"snapshot": dict, "offset": float, "rtt": float,
        #          "at": monotonic stamp of absorption}
        self._entries: dict[str, dict[str, Any]] = {}

    def absorb(self, name: str, snapshot: dict, t0: float,
               t1: float) -> None:
        """Fold in one replica's /debug/telemetry body. ``t0``/``t1`` are
        the router's ``perf_counter`` immediately before/after the HTTP
        round trip; the replica sampled its own clock somewhere inside
        that window, so the midpoint estimates the cross-process offset
        to within half the RTT (good to well under a millisecond on
        loopback — tighter than any engine dispatch we'd want to order).
        """
        if not isinstance(snapshot, dict):
            return
        clock = snapshot.get("clock")
        offset = None
        if isinstance(clock, (int, float)):
            offset = (t0 + t1) / 2.0 - float(clock)
        with self._lock:
            self._entries[name] = {
                "snapshot": snapshot,
                "offset": offset,
                "rtt": max(0.0, t1 - t0),
                "at": time.monotonic(),
            }

    def forget(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)

    def _fresh_entry(self, name: str) -> dict[str, Any] | None:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            return None
        if time.monotonic() - entry["at"] > self.max_age_s:
            return None
        return entry

    def fresh(self, name: str) -> bool:
        """True while ``name`` has telemetry young enough to act on."""
        return self._fresh_entry(name) is not None

    def get(self, name: str) -> dict | None:
        """The last absorbed snapshot, or None when absent/stale."""
        entry = self._fresh_entry(name)
        return entry["snapshot"] if entry is not None else None

    def burn_rate(self, name: str, slo_class: str) -> float | None:
        """``slo_class``'s burn rate on ``name`` — None (never a zero:
        the caller must fail open, and 0.0 would read as 'measured
        healthy') when telemetry is absent, stale, or shapeless."""
        snapshot = self.get(name)
        if snapshot is None:
            return None
        try:
            rate = snapshot["slo"][slo_class]["burn_rate"]
        except (KeyError, TypeError):
            return None
        return float(rate) if isinstance(rate, (int, float)) else None

    def burn_rates(self, name: str) -> dict[str, float]:
        """All classes' burn rates on ``name`` (empty when stale) — the
        gauge-export helper."""
        snapshot = self.get(name)
        if snapshot is None:
            return {}
        slo = snapshot.get("slo")
        if not isinstance(slo, dict):
            return {}
        out: dict[str, float] = {}
        for cls, row in slo.items():
            rate = row.get("burn_rate") if isinstance(row, dict) else None
            if isinstance(rate, (int, float)):
                out[str(cls)] = float(rate)
        return out

    def offset(self, name: str) -> float | None:
        """Estimated (router clock − replica clock), or None when
        absent/stale/unestimable — the fleet-timeline merger then leaves
        that replica's events on its raw timebase rather than inventing
        an alignment."""
        entry = self._fresh_entry(name)
        return entry["offset"] if entry is not None else None

    def snapshot(self) -> dict[str, dict]:
        """Debug export: per-replica absorbed state with freshness."""
        now = time.monotonic()
        with self._lock:
            entries = dict(self._entries)
        return {
            name: {
                "age_s": round(now - entry["at"], 3),
                "fresh": now - entry["at"] <= self.max_age_s,
                "offset": entry["offset"],
                "rtt": round(entry["rtt"], 6),
                "telemetry": entry["snapshot"],
            }
            for name, entry in entries.items()
        }
