"""QoS scheduling (docs/scheduling.md): priority classes + weighted-fair
admission ordering (:mod:`policy`), the single shed-decision point
(:mod:`cost`), and the mid-decode preemption controller (:mod:`preempt`).

The subsystem is pure host-side policy: it reorders which pending request
the engine admits next, decides rejections at submit time, and chooses
preemption victims — it never adds a device program (the snapshot /
restore / staged-injection families the engine already compiles are what
a parked victim resumes through; analysis/compile_budget.json pins this).
"""

from quorum_tpu.sched.cost import CostModel, ShedDecision
from quorum_tpu.sched.policy import (
    PRIORITY_CLASSES,
    SchedPolicy,
    class_rank,
    to_slo_class,
)
from quorum_tpu.sched.preempt import PreemptionController

__all__ = [
    "CostModel",
    "PRIORITY_CLASSES",
    "PreemptionController",
    "SchedPolicy",
    "ShedDecision",
    "class_rank",
    "to_slo_class",
]
