"""The engine's ONE shed-decision point (docs/scheduling.md).

Every submit-time rejection and the per-turn deadline sweep route through
this model, so there is exactly one place that decides "refuse now" and
one Retry-After heuristic (the tidy half of ISSUE 18 — previously the
breaker, the queue-full check, the paged pool-span check, and the sweep
each carried their own fragment of the decision).

Decisions, in evaluation order:

- ``deadline``   — already past its deadline at submit (or, with QoS on
  and warm evidence, provably unable to SURVIVE THE QUEUE: the predictive
  shed that turns a guaranteed minute-3 timeout into an immediate honest
  503 + Retry-After).
- ``breaker``    — the failure breaker is rejecting admissions.
- ``queue_full`` — the admission queue is at capacity.
- ``pool_span``  — (paged engines) the request's full page span exceeds
  the pool; no amount of waiting admits it.

The predictive shed is deliberately conservative: it needs QoS enabled, a
deadline, live queue pressure, warm EWMAs (≥ MIN_OBS observations of both
queue wait and service time), and the estimate to exceed the remaining
headroom by MARGIN×. Idle engines and cold starts never predictive-shed,
so FIFO-era behaviour is preserved bit for bit until there is evidence.
"""

from __future__ import annotations

from dataclasses import dataclass

# Predictive-shed gates: both EWMAs warm, estimate > MARGIN x remaining.
MIN_OBS = 5
MARGIN = 2.0
EWMA_ALPHA = 0.3


@dataclass
class ShedDecision:
    kind: str          # "deadline" | "breaker" | "queue_full" | "pool_span"
    retry_after: float  # seconds — the honest backoff hint (503 header)
    detail: str         # operator-facing reason (error message text)


class CostModel:
    """Shed decisions fed by queue depth, observed queue-wait / service
    EWMAs, and remaining deadline. Observation calls run on the engine's
    scheduler threads (single writer per field under the scheduler lock's
    turn order); reads are snapshots — exactness across a race is not
    needed, same contract as the engine's /metrics counters."""

    def __init__(self, latency=None):
        # Per-family device-time model (telemetry/latency.py) — kept for
        # operators reading estimates out of /debug/telemetry; the shed
        # gates below use the coarser whole-request EWMAs, which include
        # host turnaround and therefore bound the device model from above.
        self.latency = latency
        self.queue_wait_ewma = 0.0
        self.n_queue_obs = 0
        self.service_ewma = 0.0
        self.n_service_obs = 0
        self.n_predictive_sheds = 0

    # ---- observations ------------------------------------------------------

    def observe_queue_wait(self, seconds: float) -> None:
        self.n_queue_obs += 1
        self.queue_wait_ewma = seconds if self.n_queue_obs == 1 else (
            (1 - EWMA_ALPHA) * self.queue_wait_ewma + EWMA_ALPHA * seconds)

    def observe_service(self, seconds: float) -> None:
        """Whole-request wall time (admission to slot release)."""
        self.n_service_obs += 1
        self.service_ewma = seconds if self.n_service_obs == 1 else (
            (1 - EWMA_ALPHA) * self.service_ewma + EWMA_ALPHA * seconds)

    # ---- the decision points ----------------------------------------------

    def retry_hint(self) -> float:
        """The honest Retry-After for capacity sheds: the observed queue
        drain estimate when warm, else the 1-second floor the HTTP layer
        has always advertised."""
        if self.n_queue_obs >= MIN_OBS and self.queue_wait_ewma > 0:
            return max(1.0, self.queue_wait_ewma)
        return 1.0

    def presubmit(self, *, now: float, deadline: float | None,
                  breaker) -> ShedDecision | None:
        """Lock-free checks before the request touches the queue."""
        if deadline is not None and now >= deadline:
            return ShedDecision("deadline", self.retry_hint(),
                                "request deadline expired at submission")
        if breaker is not None and not breaker.allow(now):
            return ShedDecision("breaker", breaker.retry_after(now),
                                "engine circuit breaker is open")
        return None

    def queue_check(self, *, now: float, deadline: float | None,
                    n_pending: int, max_pending: int, qos: bool,
                    page_need: int = 0,
                    pool_pages: int = 0) -> ShedDecision | None:
        """Checks under the scheduler lock, against live queue state.
        Message text for the capacity kinds is kept verbatim from the
        pre-QoS engine — clients and tests key on it."""
        if n_pending >= max_pending:
            return ShedDecision(
                "queue_full", self.retry_hint(),
                f"engine admission queue full ({max_pending} waiting)")
        if pool_pages and page_need > pool_pages:
            return ShedDecision(
                "pool_span", self.retry_hint(),
                f"request span of {page_need} pages exceeds the kv page "
                f"pool ({pool_pages} pages)")
        if qos and deadline is not None and n_pending > 0:
            est = self.estimated_queue_wait(n_pending)
            if est is not None and est > MARGIN * max(0.0, deadline - now):
                self.n_predictive_sheds += 1
                return ShedDecision(
                    "deadline", max(1.0, est),
                    f"deadline infeasible under current load (estimated "
                    f"queue wait {est:.1f}s behind {n_pending} pending)")
        return None

    def estimated_queue_wait(self, n_pending: int) -> float | None:
        """Expected wait behind ``n_pending`` queued requests, or None
        while the evidence is cold. The head of the queue waits about one
        observed queue-wait; each request behind it adds a service time."""
        if self.n_queue_obs < MIN_OBS or self.n_service_obs < MIN_OBS:
            return None
        return self.queue_wait_ewma + max(0, n_pending - 1) \
            * self.service_ewma

    # ---- the sweep's predicate --------------------------------------------

    @staticmethod
    def expired(req, now: float) -> bool:
        """The per-turn deadline sweep's single expiry predicate."""
        return (req.deadline is not None and now > req.deadline
                and not req.cancel.is_set())

    def snapshot(self) -> dict:
        """/debug/telemetry block."""
        return {
            "queue_wait_ewma_s": round(self.queue_wait_ewma, 6),
            "service_ewma_s": round(self.service_ewma, 6),
            "queue_obs": self.n_queue_obs,
            "service_obs": self.n_service_obs,
            "predictive_sheds": self.n_predictive_sheds,
        }
