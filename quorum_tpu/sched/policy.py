"""Priority classes and weighted-fair admission ordering.

Three dispatch classes (docs/scheduling.md):

- ``interactive`` — a human is waiting. Explicit ``priority`` body knob,
  or derived from the request's deadline headroom exactly like the SLO
  plane (telemetry/slo.py: timeout ≤ QUORUM_TPU_SLO_INTERACTIVE_S).
- ``batch`` — throughput work; the default for undeadlined / long-timeout
  requests.
- ``background`` — explicitly opt-in best-effort work, admitted only
  through its weighted-fair share and first in line for preemption.

The SLO plane keeps its two scoring classes (``SLO_CLASSES`` is pinned by
the burn-rate metrics and the router's TelemetryView); ``background`` maps
onto ``batch`` for SLO accounting via :func:`to_slo_class`.

Admission order is weighted-fair queueing across classes with
earliest-deadline-headroom-first inside a class: each class accrues
virtual time as its requests are admitted, inversely to its weight (and
to the request's per-tenant weight), and the next admission comes from
the backlogged class with the LEAST virtual time. A backlogged class with
weight w therefore receives at least w/Σw of admissions over any window —
the starvation bound docs/scheduling.md documents — while within a class
the request closest to missing its deadline goes first (preempted victims
re-enter at the head of their class: their queue age is preserved and the
resume credit breaks ties ahead of fresh arrivals).
"""

from __future__ import annotations

import os

PRIORITY_CLASSES = ("interactive", "batch", "background")
_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}

# Admission shares when every class is backlogged (overridable via
# QUORUM_TPU_SCHED_WEIGHTS="interactive=4,batch=2,background=1").
DEFAULT_WEIGHTS = {"interactive": 4.0, "batch": 2.0, "background": 1.0}


def class_rank(cls: str) -> int:
    """0 = most urgent. Unknown strings rank as batch (defense in depth —
    the knob is validated at the HTTP edge and in engine.submit)."""
    return _RANK.get(cls, _RANK["batch"])


def to_slo_class(cls: str) -> str:
    """Map a dispatch class onto the SLO plane's two scoring classes
    (telemetry/slo.py SLO_CLASSES — pinned by the burn metrics)."""
    return "interactive" if cls == "interactive" else "batch"


def _env_weights(var: str, base: dict[str, float]) -> dict[str, float]:
    """Parse ``a=2,b=0.5`` weight overrides; malformed entries are a loud
    skip (serving must not crash on an env typo), non-positive clamped."""
    raw = os.environ.get(var, "")
    out = dict(base)
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            w = float(val)
        except ValueError:
            continue
        if w > 0:
            out[name.strip()] = w
    return out


class SchedPolicy:
    """Admission-order policy. All mutating calls (:meth:`charge`) happen
    with the engine's scheduler lock held — the policy carries no lock of
    its own (same discipline as the engine's _paged_* helpers)."""

    def __init__(self, weights: dict[str, float] | None = None,
                 tenant_weights: dict[str, float] | None = None):
        self.weights = dict(weights) if weights else _env_weights(
            "QUORUM_TPU_SCHED_WEIGHTS", DEFAULT_WEIGHTS)
        for c in PRIORITY_CLASSES:
            self.weights.setdefault(c, DEFAULT_WEIGHTS[c])
        self.tenant_weights = dict(tenant_weights) if tenant_weights \
            else _env_weights("QUORUM_TPU_TENANT_WEIGHTS", {})
        # Per-class virtual time: admissions advance a class's clock by
        # cost/weight, and the least clock among backlogged classes is
        # served next (classic WFQ; idle classes are re-synced forward on
        # their next admission so a long-idle class cannot bank unbounded
        # credit and then monopolize the queue).
        self._vtime = {c: 0.0 for c in PRIORITY_CLASSES}
        # System virtual clock: the largest start tag served so far. A
        # class that went idle falls behind this floor; its next charge
        # clamps it back up, bounding banked credit to ~one admission.
        self._vfloor = 0.0

    # ---- classification ----------------------------------------------------

    def classify(self, priority: str | None, deadline: float | None,
                 now: float) -> str:
        """The request's dispatch class: the explicit ``priority`` knob
        wins; otherwise deadline headroom decides via the SLO plane's
        threshold (no deadline → batch; ``background`` is never derived)."""
        if priority in PRIORITY_CLASSES:
            return priority
        from quorum_tpu.telemetry import slo

        timeout = None if deadline is None else max(0.0, deadline - now)
        return slo.classify(timeout)

    # ---- ordering ----------------------------------------------------------

    @staticmethod
    def _headroom(req, now: float) -> float:
        d = getattr(req, "deadline", None)
        return float("inf") if d is None else d - now

    def _key(self, req, now: float):
        """Within-class order: resumed victims first (preemption credit),
        then earliest deadline headroom, then queue age (FIFO)."""
        return (0 if getattr(req, "n_preempts", 0) > 0 else 1,
                self._headroom(req, now), req.t_submit)

    def pick(self, pending: list, now: float) -> int:
        """Index of the next request to admit. Pure — call :meth:`charge`
        once the pick is actually popped (a pick that finds no free slot
        must not advance any class's clock)."""
        if len(pending) <= 1:
            return 0
        by_class: dict[str, list[int]] = {}
        for i, r in enumerate(pending):
            by_class.setdefault(
                getattr(r, "sched_class", "batch") or "batch", []).append(i)
        cls = min(by_class,
                  key=lambda c: (self._vtime.get(c, 0.0), class_rank(c)))
        return min(by_class[cls], key=lambda i: self._key(pending[i], now))

    def order(self, pending: list, now: float) -> list:
        """Full policy order of ``pending`` (stacked-members admission
        scans heads in this order). Repeatedly simulating WFQ picks over a
        snapshot of the clocks keeps the two entry points consistent."""
        if len(pending) <= 1:
            return list(pending)
        saved, saved_floor = dict(self._vtime), self._vfloor
        rest, out = list(pending), []
        try:
            while rest:
                i = self.pick(rest, now)
                req = rest.pop(i)
                out.append(req)
                self.charge(req)
        finally:
            self._vtime, self._vfloor = saved, saved_floor
        return out

    def charge(self, req, cost: float = 1.0) -> None:
        """Advance the admitted request's class clock by cost/weight
        (tenant weight scales the effective weight, so a heavy tenant's
        requests space out within their class). Caller holds the engine
        scheduler lock; also re-syncs an idle class's clock forward."""
        cls = getattr(req, "sched_class", "batch") or "batch"
        w = self.weights.get(cls, 1.0) * self.tenant_weights.get(
            getattr(req, "tenant", None) or "", 1.0)
        start = max(self._vtime.get(cls, 0.0), self._vfloor)
        self._vtime[cls] = start + cost / max(w, 1e-6)
        self._vfloor = max(self._vfloor, start)

    def queue_depths(self, pending: list) -> dict[str, int]:
        """Pending-queue depth per class (the sched_queue_depth gauge)."""
        out = {c: 0 for c in PRIORITY_CLASSES}
        for r in pending:
            cls = getattr(r, "sched_class", "batch") or "batch"
            out[cls] = out.get(cls, 0) + 1
        return out
