"""Mid-decode preemption: victim selection + budgets (docs/scheduling.md).

When a higher-class admission finds no free slot, the controller picks a
lower-class resident row to park. The ENGINE performs the parking at its
next reap boundary (engine.py ``_sweep_preemptions``): the victim's slot
is released exactly like a finished stream — its K/V prefix stays
slot-resident (dense) or parked as retained page references (paged), and
with a host prefix store the prefix is additionally snapshotted — then
the victim re-enters the pending queue with resume credit. Re-admission
rides the ordinary admission machinery (chunked register / staged
zero-drain injection), so the decode ring never clamps and no new device
program exists for preemption; the victim's already-delivered tokens are
regenerated deterministically (one RNG split per emitted token — the
engine's pinned discipline) and swallowed by the replay guard in
``_emit``, byte-compared against what the consumer already received.

Selection order: lowest class first, then cheapest replay (fewest
generated tokens), then most recent admission. Budgets prevent livelock:
a victim is preempted at most ``max_preempts`` times (then it becomes
ineligible and batch work degrades gracefully instead of starving), and
only one preemption may be outstanding per free-slot shortfall.
"""

from __future__ import annotations

import os

from quorum_tpu.sched.policy import class_rank

DEFAULT_MAX_PREEMPTS = 2


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class PreemptionController:
    """Pure host-side victim selection; owns no engine state. The engine
    calls :meth:`pick_victim` under its scheduler lock and performs the
    actual park/requeue itself."""

    def __init__(self, max_preempts: int | None = None):
        self.max_preempts = max_preempts if max_preempts is not None \
            else _env_int("QUORUM_TPU_SCHED_MAX_PREEMPTS",
                          DEFAULT_MAX_PREEMPTS)
        self.n_considered = 0

    def eligible(self, req) -> bool:
        """May this resident request be parked at a reap boundary?

        Logprobs streams are excluded (their per-token lp records were
        already delivered; replay would have to suppress re-records across
        every emit path — not worth the risk for an observability knob).
        Everything else replays exactly: penalties rebuild from history,
        constrained rows re-advance their DFA on device, speculative rows
        verify with the same per-token RNG chain.
        """
        return (req is not None
                and not req.cancel.is_set()
                and not req.preempt_flag
                and req.want_lp < 0
                and req.n_preempts < self.max_preempts)

    def pick_victim(self, beneficiary, slots, lo: int, hi: int):
        """(row, victim) for ``beneficiary`` among ``slots[lo:hi]``, or
        None. Strictly lower class only — equal-class requests never
        preempt each other (that would just thrash the slot)."""
        self.n_considered += 1
        ben_rank = class_rank(beneficiary.sched_class)
        best = None
        for i in range(lo, hi):
            r = slots[i]
            if not self.eligible(r):
                continue
            rank = class_rank(r.sched_class)
            if rank <= ben_rank:
                continue
            # Lowest class first; cheapest replay next; newest last.
            key = (-rank, r.emitted, -r.t_submit)
            if best is None or key < best[0]:
                best = (key, i, r)
        if best is None:
            return None
        return best[1], best[2]
