"""API server layer: ASGI app + standalone HTTP server.

The reference served a FastAPI app with uvicorn (/root/reference/Makefile:3-7).
Neither is present in this environment, so quorum_tpu ships:

  asgi.py    a minimal ASGI toolkit (request/response/router) — the app is a
             standard ASGI callable, testable with httpx.ASGITransport and
             servable by any ASGI server;
  app.py     the OpenAI-compatible application (routes, auth, dispatch);
  serve.py   an h11-based asyncio HTTP/1.1 server + CLI entry point.
"""

from quorum_tpu.server.app import create_app

__all__ = ["create_app"]
