"""The OpenAI-compatible application: routes, auth, validation, dispatch.

Endpoint parity with /root/reference/src/quorum/oai_proxy.py:959-1414:

  POST /chat/completions   (and /v1/chat/completions — the reference had no
                            /v1 alias, quirk 10; both are served here)
  GET  /health             → {"status": "healthy"}

Request handling parity:
  - all request headers forwarded minus ``host`` (:973);
  - missing Authorization → fall back to $OPENAI_API_KEY, else 401
    ``auth_error`` with the reference's exact message (:976-998); header
    casing normalized to ``Authorization`` (:1000-1004);
  - no valid backends → 500 ``configuration_error`` (:1010-1024);
  - no model in request and none in config → 400 ``invalid_request_error``
    (:1026-1040);
  - parallel mode iff strategy config present AND >1 valid backend (:1043-1044);
  - non-streaming non-parallel: all backends still called concurrently, first
    success returned verbatim (:1356-1380);
  - all backends failed → 500 "All backends failed. First error: …" (:1140-1162).

Difference: malformed request JSON returns 400 (the reference's blanket
handler turned it into a 500).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import time
import uuid
from typing import Any, AsyncIterator

from quorum_tpu import oai, sse
from quorum_tpu.observability import (
    FLIGHT_RECORDER_EVENTS,
    METRICS,
    TRACE_PROPAGATED,
    TRACES,
    ProfilerBusy,
    RequestTrace,
    finish_request_trace,
    maybe_profile,
    profile_process,
    use_trace,
)
from quorum_tpu.telemetry import slo as slo_mod
from quorum_tpu.telemetry import tracecontext
from quorum_tpu.telemetry.recorder import RECORDER
from quorum_tpu.backends.base import Backend, BackendError
from quorum_tpu.backends.registry import BackendRegistry, build_registry
from quorum_tpu.config import Config, load_config
from quorum_tpu.server.asgi import (
    App,
    JSONResponse,
    Request,
    Response,
    StreamingResponse,
)
from quorum_tpu.strategies.combine import combine_outcomes, degraded_headers
from quorum_tpu.strategies.fanout import fanout_complete
from quorum_tpu.strategies.streaming import StreamPlan, parallel_stream

logger = logging.getLogger(__name__)

# content-encoding must be dropped too: httpx decompresses upstream bodies, so
# forwarding the upstream's "gzip" label over our identity-encoded JSON would
# corrupt the response for compression-aware clients.
_PASSTHROUGH_SKIP = {"content-length", "content-type", "transfer-encoding", "content-encoding", "connection"}


def _auth_error() -> JSONResponse:
    return JSONResponse(
        {
            "error": {
                "message": (
                    "Authorization header is required and OPENAI_API_KEY "
                    "environment variable is not set"
                ),
                "type": "auth_error",
            }
        },
        status_code=401,
    )


def _resolve_headers(request_headers: dict[str, str]) -> dict[str, str] | None:
    """Forward headers minus host; normalize/inject Authorization.

    Returns None when no credential is available (→ 401).
    """
    headers = {k: v for k, v in request_headers.items() if k.lower() != "host"}
    lower_to_orig = {k.lower(): k for k in headers}
    if "authorization" not in lower_to_orig:
        api_key = os.environ.get("OPENAI_API_KEY", "")
        if not api_key:
            return None
        headers["Authorization"] = f"Bearer {api_key}"
    elif "Authorization" not in headers:
        orig = lower_to_orig["authorization"]
        headers["Authorization"] = headers.pop(orig)
    if "content-type" not in lower_to_orig:
        headers["Content-Type"] = "application/json"
    return headers


class _SSECoalescer:
    """The MoreChunk buffer-and-flush contract, shared by both stream
    generators: frames of chunks marked ``oai.MoreChunk`` (the backend saw
    further events already queued — one decode chunk's k tokens) buffer and
    ship with the next unmarked chunk's flush — k events, ONE socket write.
    ``add`` returns the bytes to write now (b"" while buffering); ``drain``
    returns whatever is still buffered and must be called before emitting
    an error frame or [DONE] so a stream never strands marked frames."""

    def __init__(self) -> None:
        self._buf: list[bytes] = []

    def add(self, chunk: dict[str, Any], frame: bytes | None) -> bytes:
        if frame is not None:
            self._buf.append(frame)
        if not oai.has_more(chunk) and self._buf:
            return self.drain()
        return b""

    def drain(self) -> bytes:
        out = b"".join(self._buf)
        self._buf.clear()
        return out


async def _stream_with_role(
    first_chunk: dict[str, Any] | None,
    rest: AsyncIterator[dict[str, Any]],
    model: str,
) -> AsyncIterator[bytes]:
    """Single-backend SSE normalization (oai_proxy.py:888-956 parity):
    synthetic role chunk first, duplicate upstream role-only chunk skipped,
    trailing [DONE] guaranteed, MoreChunk runs coalesced per flush."""
    yield sse.encode_event(oai.chunk(id="chatcmpl-role", model=model, delta={"role": "assistant"}))
    co = _SSECoalescer()
    try:
        if first_chunk is not None:
            delta = (first_chunk.get("choices") or [{}])[0].get("delta") or {}
            is_dup_role = bool(delta.get("role")) and not delta.get("content")
            if not is_dup_role:
                if out := co.add(first_chunk, sse.encode_event(first_chunk)):
                    yield out
        async for chunk in rest:
            if out := co.add(chunk, sse.encode_event(chunk)):
                yield out
    except BackendError as e:
        # Mid-stream failure: flush anything buffered, then surface as an
        # SSE error chunk and terminate.
        if out := co.drain():
            yield out
        yield sse.encode_event(oai.error_chunk(
            f"Backend failed: {e}", model=model,
            code=getattr(e, "code", None)))
    if out := co.drain():
        yield out
    yield sse.encode_done()


def _validate_speculative_aggregation(cfg: Config, reg) -> None:
    """Boot-time check for ``speculative_aggregation: true`` (docs/quorum.md).

    There is no per-request speculation lever — spec_decode is an engine
    boot knob — so the opt-in is an assertion: the aggregator must be a
    local ``tpu://`` backend whose engine runs prompt-lookup speculation
    (the aggregation prompt quotes the members' tails verbatim, which is
    exactly what prompt lookup drafts the aggregate from). Failing at boot
    beats silently aggregating unaccelerated."""
    try:
        if cfg.strategy_name != "aggregate" or not cfg.aggregate.speculative_aggregation:
            return
    except ValueError:
        raise  # invalid aggregate block: let from_dict's error surface
    p = cfg.aggregate
    agg = reg.get(p.aggregator_backend) if p.aggregator_backend else None
    if agg is None:
        raise ValueError(
            "speculative_aggregation: true requires an aggregator_backend "
            f"(got {p.aggregator_backend!r})")
    engine = getattr(agg, "engine", None)
    if engine is None:
        raise ValueError(
            f"speculative_aggregation: true requires a tpu:// aggregator "
            f"(backend {agg.name!r} is {type(agg).__name__}; an HTTP "
            "upstream's speculation cannot be asserted from here)")
    if int(getattr(engine, "spec_decode", 0) or 0) <= 0:
        raise ValueError(
            f"speculative_aggregation: true but aggregator {agg.name!r} "
            "runs no speculation (spec_decode=0). Add spec_decode=G "
            "(e.g. spec_decode=4) to its tpu:// URL — the aggregation "
            "prompt quotes the members' outputs, which is what "
            "prompt-lookup speculation drafts from.")


def create_app(
    config: Config | None = None,
    registry: BackendRegistry | None = None,
    watch_config: bool | None = None,
    **backend_overrides: Backend,
) -> App:
    """Build the ASGI application.

    Tests inject deterministic backends via ``backend_overrides`` (name →
    Backend) or a fully custom ``registry``.

    ``watch_config`` enables dev-mode hot reload (default: the
    ``QUORUM_TPU_CONFIG_WATCH`` env toggle): on each request the config
    file's mtime is checked (rate-limited) and edits swap in a rebuilt
    registry without dropping untouched live backends — see
    ``quorum_tpu.server.reload``. Requires a file-backed config.
    """
    cfg = config if config is not None else load_config()
    reg = registry if registry is not None else build_registry(cfg, **backend_overrides)
    _validate_speculative_aggregation(cfg, reg)

    from quorum_tpu.server.reload import ConfigWatcher, Runtime

    rt = Runtime(cfg, reg)
    if watch_config is None:
        watch_config = os.environ.get("QUORUM_TPU_CONFIG_WATCH", "") == "1"
    watcher = (ConfigWatcher(cfg.source_path, rt, backend_overrides)
               if watch_config and cfg.source_path is not None
               and registry is None else None)

    app = App()
    app.state["runtime"] = rt
    app.state["config"] = cfg
    app.state["registry"] = reg

    async def current() -> tuple[Config, BackendRegistry]:
        """The live (config, registry) pair — post-reload when watching."""
        if watcher is not None:
            await watcher.poll()
            app.state["config"], app.state["registry"] = rt.cfg, rt.reg
        return rt.cfg, rt.reg

    def _distinct_engines(reg: BackendRegistry, need: str):
        """(backend name, engine) per DISTINCT engine exposing ``need`` —
        backends sharing a cached engine must not double-count it. The one
        iteration /metrics and /health both build on (HTTP relay backends
        hold no local state and contribute nothing)."""
        seen: set[int] = set()
        for backend in reg.backends:
            engine = getattr(backend, "engine", None)
            if engine is None or not hasattr(engine, need):
                continue
            if id(engine) in seen:
                continue
            seen.add(id(engine))
            yield backend.name, engine

    def _engine_health() -> tuple[str, list[dict]]:
        """Aggregate health from real signals (docs/robustness.md): one
        check row per distinct tpu:// engine — scheduler / snapshot-worker
        thread liveness, breaker state, queue depth vs capacity.
        ``unhealthy``: a serving thread is dead (only a restart recovers).
        ``degraded``: the failure breaker is open/half-open or the
        admission queue is saturated — alive, but shedding.

        Group-aware under disaggregated serving (``disagg=P+D``): the
        engine runs TWO cooperating scheduler loops, and a dead
        decode-group loop must not report healthy because the prefill loop
        is still alive (or vice versa) — /ready then sheds whenever either
        group would."""
        checks: list[dict] = []
        for name, engine in _distinct_engines(rt.reg, "health"):
            row = engine.health()
            row["backend"] = name
            checks.append(row)
        status = "healthy"
        for row in checks:
            if (not row["scheduler_alive"]
                    or not row.get("prefill_scheduler_alive", True)
                    or not row["snapshot_worker_alive"]):
                return "unhealthy", checks
            if (row["breaker"] != "closed"
                    or row["pending"] >= row["queue_limit"]
                    or row.get("draining")):
                # Draining: admissions are gated shut (POST /admin/drain)
                # but residents still finish — degraded sheds /ready so
                # the fleet rotates the replica out while they do.
                status = "degraded"
        # SLO burn-rate degradation (telemetry/slo.py): opt-in via
        # QUORUM_TPU_SLO_READY_BURN — while a class burns objectives past
        # the threshold the process reports degraded (and /ready sheds),
        # so a load balancer rotates the replica before more clients eat
        # the breaches. Only meaningful for engine-backed processes.
        if status == "healthy" and checks \
                and slo_mod.burning_class() is not None:
            status = "degraded"
        return status, checks

    @app.route("GET", "/health", "/v1/health")
    async def health(request: Request) -> Response:
        """Truthful liveness: ``healthy`` / ``degraded`` (200 — the process
        still serves, possibly shedding) / ``unhealthy`` (503 — rotate it
        out). With no engine-backed backends the body stays the reference's
        exact ``{"status": "healthy"}``."""
        await current()
        status, checks = _engine_health()
        body: dict = {"status": status}
        if checks:
            body["checks"] = checks
            # Per-class SLO accounting (good/breached by stage + burn
            # rate over the sliding window) — the degradation signal's
            # raw numbers, only for engine-backed processes (the bare
            # reference body stays exact without them).
            body["slo"] = slo_mod.SLO.snapshot()
        if status == "unhealthy":
            return JSONResponse(body, status_code=503,
                                headers={"Retry-After": "5"})
        return JSONResponse(body)

    @app.route("GET", "/ready", "/v1/ready")
    async def ready(request: Request) -> Response:
        """Readiness: 200 only while NEW work would be admitted — a dead
        serving thread, an open/half-open breaker, or a saturated queue all
        503 so load balancers stop routing here before clients eat the
        rejections."""
        await current()
        status, checks = _engine_health()
        if status == "healthy":
            return JSONResponse({"status": "ready"})
        return JSONResponse(
            {"status": "unready", "reason": status,
             **({"checks": checks} if checks else {})},
            status_code=503, headers={"Retry-After": "5"})

    started = time.monotonic()

    @app.route("GET", "/models", "/v1/models")
    async def models(request: Request) -> Response:
        """OpenAI model-discovery surface: one entry per distinct configured
        model id (SDKs and UIs probe this before chatting). The reference
        exposes no discovery endpoint — clients had to know the model name
        out of band; a local serving framework can simply list what it
        loaded. ``owned_by`` carries the backend name(s) serving the id."""
        _, reg = await current()
        owners: dict[str, list[str]] = {}
        for backend in reg.backends:
            mid = getattr(backend, "model", "") or getattr(
                backend, "model_id", "")
            if mid:
                owners.setdefault(mid, []).append(backend.name)
        data = [{"id": mid, "object": "model", "created": 0,
                 "owned_by": ",".join(names)}
                for mid, names in sorted(owners.items())]
        return JSONResponse({"object": "list", "data": data})

    @app.route("GET", "/metrics", "/v1/metrics")
    async def metrics(request: Request) -> Response:
        """Prometheus text exposition of engine/scheduler state — the
        metrics-export gap the reference leaves open (SURVEY.md §5.5: two
        log channels, no metrics). One line set per tpu:// backend; HTTP
        backends have no local state to export."""
        _, reg = await current()
        lines = [
            "# TYPE quorum_tpu_uptime_seconds gauge",
            f"quorum_tpu_uptime_seconds {time.monotonic() - started:.3f}",
        ]
        gauges = ("slots", "members", "busy_slots", "admitting", "pending",
                  "queue_limit", "decode_pipeline", "decode_loop",
                  "inflight_chunks",
                  "prefix_store_bytes", "prefix_store_entries",
                  "disagg", "decode_pp", "prefill_sp",
                  "prefill_group_devices", "decode_group_devices",
                  "prefill_group_active", "decode_group_active",
                  "zero_drain", "breaker_state",
                  "kv_pages", "kv_page_size",
                  "kv_pages_allocated", "kv_pages_free",
                  "qos", "draining")
        # One snapshot per distinct engine (_distinct_engines). Each
        # family's TYPE line appears exactly once, with all its samples
        # grouped — the Prometheus text format rejects repeated TYPE lines.
        snapshots = [(name, engine.metrics())
                     for name, engine in _distinct_engines(reg, "metrics")]
        if snapshots:
            for key in snapshots[0][1]:
                kind = "gauge" if key in gauges else "counter"
                lines.append(f"# TYPE quorum_tpu_engine_{key} {kind}")
                for name, m in snapshots:
                    lines.append(
                        f'quorum_tpu_engine_{key}{{backend="{name}"}} {m[key]}'
                    )
        # Latency histogram families (request duration, TTFT, inter-token,
        # queue wait, prefill, decode chunk) — recorded by the tracing spine
        # across server/strategy/engine layers (observability.METRICS).
        FLIGHT_RECORDER_EVENTS.set(RECORDER.depth())  # scrape-time truth
        lines.extend(METRICS.expose())
        return Response(
            ("\n".join(lines) + "\n").encode(),
            media_type="text/plain; version=0.0.4",
        )

    @app.route("GET", "/debug/traces", "/v1/debug/traces")
    async def debug_traces(request: Request) -> Response:
        """Ring buffer of completed request traces plus the in-flight set:
        per-request span timelines (queue-wait → prefill → decode →
        aggregate → sse-flush), TTFT, and per-token wire timings — the
        drill-down surface behind the aggregate histograms on /metrics."""
        return JSONResponse(TRACES.snapshot())

    @app.route("GET", "/debug/traces/{request_id}",
               "/v1/debug/traces/{request_id}")
    async def debug_trace_one(request: Request) -> Response:
        trace = TRACES.get(request.path_params["request_id"])
        if trace is None:
            return JSONResponse(
                {"error": {"message": "trace not found (expired from the "
                           "ring buffer, or the id was never traced)",
                           "type": "invalid_request_error"}},
                status_code=404,
            )
        return JSONResponse(trace.to_dict())

    @app.route("GET", "/debug/engine/timeline", "/v1/debug/engine/timeline")
    async def debug_timeline(request: Request) -> Response:
        """The engine flight recorder (quorum_tpu/telemetry/recorder.py):
        the bounded ring of structured engine events — dispatches tagged
        with their compile-budget program family, admissions/injections/
        handoffs/registers, clamp transitions, deadline expiries, breaker
        and containment events — correlated across the prefill and decode
        loops by request id. ``?format=perfetto`` returns Chrome
        trace-event JSON (save it and open in ui.perfetto.dev); the
        default JSON form additionally carries each engine's per-family
        device-time statistics and the SLO accounting snapshot."""
        _, reg = await current()
        fmt = request.query_params.get("format", "json")
        if fmt in ("perfetto", "trace", "chrome"):
            return JSONResponse({"displayTimeUnit": "ms",
                                 "traceEvents": RECORDER.to_trace_events()})
        if fmt != "json":
            return JSONResponse(
                {"error": {"message": f"unknown format {fmt!r} "
                           "(json or perfetto)",
                           "type": "invalid_request_error"}},
                status_code=400)
        device_time = {
            name: engine.latency.snapshot()
            for name, engine in _distinct_engines(reg, "latency")}
        return JSONResponse({
            "clock": "perf_counter",
            "capacity": RECORDER.capacity,
            "recorded_total": RECORDER.total(),
            "events": RECORDER.snapshot(),
            "device_time": device_time,
            "slo": slo_mod.SLO.snapshot(),
        })

    @app.route("GET", "/debug/telemetry", "/v1/debug/telemetry")
    async def debug_telemetry(request: Request) -> Response:
        """Compact telemetry snapshot for the fleet plane
        (docs/observability.md): per-class SLO burn, queue depth, breaker
        state, per-family latency models, prefix-store footprint, and a
        sample of this process's monotonic clock. The router's /ready
        poller absorbs one of these per replica per poll into its
        ``TelemetryView`` — burn-aware placement and fleet-timeline clock
        alignment both read from it — so this must stay CHEAP (no jax,
        no device sync; everything here is host-side counters)."""
        _, reg = await current()
        status, checks = _engine_health()
        queue_depth = sum(int(row.get("pending", 0) or 0)
                          for row in checks)
        breakers = {row["backend"]: row.get("breaker", "closed")
                    for row in checks}
        latency = {name: engine.latency.snapshot()
                   for name, engine in _distinct_engines(reg, "latency")}
        prefix_store_bytes = 0
        for _name, engine in _distinct_engines(reg, "prefix_store"):
            store = getattr(engine, "prefix_store", None)
            if store is not None:
                prefix_store_bytes += int(store.bytes_held or 0)
        # QoS scheduler plane (docs/scheduling.md): cost-model EWMAs and
        # shed counters per distinct engine, plus the per-class pending
        # breakdown — all host-side counters, same cost rule as above.
        sched = {}
        for name, engine in _distinct_engines(reg, "cost_model"):
            cm = getattr(engine, "cost_model", None)
            if cm is None:
                continue
            entry = dict(cm.snapshot())
            entry["qos"] = bool(getattr(engine, "qos", False))
            policy = getattr(engine, "_policy", None)
            if policy is not None:
                with engine._cond:
                    entry["queue_depths"] = policy.queue_depths(
                        engine._pending)
            sched[name] = entry
        return JSONResponse({
            # perf_counter sample: the fleet-timeline merger estimates
            # this process's clock offset from (poll request, response,
            # this sample) — same timebase as every flight-recorder "t".
            "clock": time.perf_counter(),
            "time": time.time(),
            "status": status,
            "slo": slo_mod.SLO.snapshot(),
            "queue_depth": queue_depth,
            "breaker": breakers,
            "latency": latency,
            "prefix_store_bytes": prefix_store_bytes,
            "sched": sched,
        })

    @app.route("POST", "/debug/profile", "/v1/debug/profile")
    async def debug_profile(request: Request) -> Response:
        """On-demand whole-process jax device profile
        (``?seconds=N``, default 1, capped at 60): runs
        ``jax.profiler.trace`` over everything the process dispatches for
        N seconds and returns the trace directory (TensorBoard/XProf-
        readable). Single-flight — the jax profiler is process-global and
        cannot nest, so a second request while one runs gets 409
        ``conflict_error`` (the same guard per-request
        QUORUM_TPU_PROFILE_DIR tracing shares; its losers are counted in
        ``quorum_tpu_profile_skipped_total``)."""
        raw = request.query_params.get("seconds", "1")
        try:
            seconds = float(raw)
        except ValueError:
            seconds = -1.0
        if not 0.0 < seconds <= 60.0:
            return JSONResponse(
                {"error": {"message": f"'seconds' must be a number in "
                           f"(0, 60], got {raw!r}",
                           "type": "invalid_request_error"}},
                status_code=400)
        try:
            out_dir = await asyncio.to_thread(profile_process, seconds)
        except ProfilerBusy:
            return JSONResponse(
                {"error": {"message": "profiler busy: another profile "
                           "(on-demand or per-request) is in flight",
                           "type": "conflict_error"}},
                status_code=409, headers={"Retry-After": "5"})
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"profiling failed: {e}",
                           "type": "proxy_error"}},
                status_code=500)
        return JSONResponse({"profile_dir": out_dir, "seconds": seconds})

    def _prefix_store_engine(reg: BackendRegistry, name: str | None):
        """The engine whose host prefix store /debug/prefix/chunks serves:
        ``?backend=`` selects by backend name; default is the first
        store-backed engine in config order. None when no engine carries a
        store."""
        rows = [(n, e) for n, e in _distinct_engines(reg, "prefix_store")
                if getattr(e, "prefix_store", None) is not None]
        if name:
            rows = [(n, e) for n, e in rows if n == name]
        return rows[0] if rows else (None, None)

    @app.route("GET", "/debug/prefix/chunks", "/v1/debug/prefix/chunks")
    async def prefix_chunks_export(request: Request) -> Response:
        """Serialize the host prefix store's restorable chunk chains (the
        migration wire format, quorum_tpu/cache/prefix_wire.py) — the
        router tier fetches this from a replica rotating out of the ring
        and seeds its ring successors, so spilled conversations restore a
        warm tier-1 prefix instead of paying cold prefill. ``?backend=``
        selects among engines; ``?max_bytes=`` bounds the export."""
        _, reg = await current()
        name, engine = _prefix_store_engine(
            reg, request.query_params.get("backend"))
        if engine is None:
            return JSONResponse(
                {"error": {"message": "no engine with a host prefix store "
                           "(prefix_store=host) is configured",
                           "type": "invalid_request_error"}},
                status_code=404)
        raw_max = request.query_params.get("max_bytes")
        max_bytes = None
        if raw_max is not None:
            # A caller who asked for a bound must GET a bound: an
            # unparseable or non-positive value is a 400, never a silent
            # full-store export (the whole point of the knob is capping
            # payload size).
            try:
                max_bytes = int(raw_max)
            except ValueError:
                max_bytes = -1
            if max_bytes < 1:
                return JSONResponse(
                    {"error": {"message": f"'max_bytes' must be a "
                               f"positive integer, got {raw_max!r}",
                               "type": "invalid_request_error"}},
                    status_code=400)
        blob = await asyncio.to_thread(engine.export_prefix_chunks,
                                       max_bytes)
        return Response(
            blob, media_type="application/octet-stream",
            headers={"X-Prefix-Chunk-Tokens":
                     str(engine.prefix_store.chunk_tokens),
                     "X-Prefix-Backend": name})

    @app.route("PUT", "/debug/prefix/chunks", "/v1/debug/prefix/chunks")
    async def prefix_chunks_import(request: Request) -> Response:
        """Seed the host prefix store from a peer replica's export. The
        engine validates the blob against its own cache layout (chunk
        granularity, leaf count, per-leaf dtype/shape) — a mismatched blob
        is a 400, never a poisoned store."""
        _, reg = await current()
        name, engine = _prefix_store_engine(
            reg, request.query_params.get("backend"))
        if engine is None:
            return JSONResponse(
                {"error": {"message": "no engine with a host prefix store "
                           "(prefix_store=host) is configured",
                           "type": "invalid_request_error"}},
                status_code=404)
        blob = await request.body()
        try:
            stats = await asyncio.to_thread(engine.import_prefix_chunks,
                                            blob)
        except ValueError as e:
            return JSONResponse(
                {"error": {"message": f"prefix-chunk import rejected: {e}",
                           "type": "invalid_request_error"}},
                status_code=400)
        stats["backend"] = name
        return JSONResponse(stats)

    @app.route("POST", "/admin/drain", "/v1/admin/drain")
    async def admin_drain(request: Request) -> Response:
        """Begin a graceful drain of every engine-backed backend
        (docs/robustness.md "Zero-loss streams"): admissions shed with a
        retryable 503 (the router fails the shed requests over
        pre-first-byte) and /ready goes unready so the fleet rotates the
        replica out. Default lets residents finish; ``?park=1``
        additionally parks them — each active stream ends with a
        ``parked`` finish the router proactively resumes on a sibling,
        and a parked NON-streaming request sheds as a retryable 503
        (no resume journal — truncated text must never ship as a 200).
        Idempotent; returns per-engine drain status."""
        _, reg = await current()
        park = request.query_params.get("park", "0") not in ("0", "", None)
        rows = []
        for name, engine in _distinct_engines(reg, "drain"):
            row = await asyncio.to_thread(engine.drain, park)
            row["backend"] = name
            rows.append(row)
        if not rows:
            return JSONResponse(
                {"error": {"message": "no engine-backed backend to drain",
                           "type": "invalid_request_error"}},
                status_code=404)
        return JSONResponse({"draining": True, "engines": rows})

    @app.route("GET", "/admin/drain", "/v1/admin/drain")
    async def admin_drain_status(request: Request) -> Response:
        """Drain progress: ``resident`` per engine counts every stream
        still attached (active + admitting + queued) — all zeros means
        the process holds no client state and is safe to take down."""
        _, reg = await current()
        rows = []
        for name, engine in _distinct_engines(reg, "drain_status"):
            row = engine.drain_status()
            row["backend"] = name
            rows.append(row)
        return JSONResponse({
            "draining": any(r["draining"] for r in rows),
            "resident": sum(r["resident"] for r in rows),
            "engines": rows,
        })

    @app.route("POST", "/admin/undrain", "/v1/admin/undrain")
    async def admin_undrain(request: Request) -> Response:
        """Reopen admissions after a drain (the rollback knob for an
        aborted rotation); idempotent."""
        _, reg = await current()
        rows = []
        for name, engine in _distinct_engines(reg, "undrain"):
            row = engine.undrain()
            row["backend"] = name
            rows.append(row)
        return JSONResponse({"draining": False, "engines": rows})

    @app.route("POST", "/chat/completions", "/v1/chat/completions")
    async def chat_completions(request: Request) -> Response:
        """Request-id + tracing + profiling wrapper around the dispatch
        logic. Every request gets a :class:`RequestTrace` (id echoed in
        X-Request-Id; spans land on /debug/traces; latencies land on the
        /metrics histograms). For SSE the trace/profiler scope must cover
        the *stream* — the device work happens while the ASGI server drives
        the iterator, after this handler returns — so the scope is closed
        from the iterator's finally, not here.

        Cross-tier trace propagation (docs/observability.md "Fleet
        plane"): a W3C ``traceparent`` from the caller (header, or body
        knob for header-less clients — ``Request.body()`` caches, so the
        peek costs nothing extra) is honored — its trace-id becomes the
        flight-recorder correlation key for every engine event this
        request causes, and the router's route/failover events carry the
        same id. No (valid) traceparent → this tier mints one. Either
        way the response echoes ``traceparent`` so callers can join
        their logs to the fleet timeline."""
        rid = f"req-{uuid.uuid4().hex[:16]}"
        parsed = tracecontext.parse_traceparent(
            request.headers.get("traceparent"))
        if parsed is None:
            with contextlib.suppress(Exception):
                raw = await request.json()
                if isinstance(raw, dict):
                    parsed = tracecontext.parse_traceparent(
                        raw.get("traceparent"))
        if parsed is not None:
            trace_id = parsed[0]
            TRACE_PROPAGATED.inc(source="client")
        else:
            trace_id = tracecontext.new_trace_id()
            TRACE_PROPAGATED.inc(source="server")
        span_id = tracecontext.new_span_id()
        trace = TRACES.start(RequestTrace(rid, trace_id=trace_id,
                                          span_id=span_id))
        scope = contextlib.ExitStack()
        scope.enter_context(maybe_profile(rid))
        try:
            with use_trace(trace):
                response = await _chat_impl(request, trace)
        except (asyncio.CancelledError, GeneratorExit):
            # Client disconnect, not a server error: 499 (the nginx
            # client-closed-request convention) keeps impatient clients out
            # of the 5xx request-duration series on dashboards.
            scope.close()
            finish_request_trace(trace, status=499)
            raise
        except BaseException:
            scope.close()
            finish_request_trace(trace, status=500)
            raise
        response.headers.setdefault("X-Request-Id", rid)
        response.headers.setdefault(
            "traceparent", tracecontext.format_traceparent(trace_id,
                                                           span_id))
        if isinstance(response, StreamingResponse):
            response.iterator = _finish_scope_after(
                sse.instrument_stream(response.iterator, trace),
                scope, trace, response.status_code,
            )
        else:
            scope.close()
            finish_request_trace(trace, status=response.status_code,
                                 mode="complete")
        return response

    async def _finish_scope_after(
        iterator: AsyncIterator[bytes],
        scope: contextlib.ExitStack,
        trace: RequestTrace,
        status: int,
    ) -> AsyncIterator[bytes]:
        try:
            async for chunk in iterator:
                yield chunk
        except (GeneratorExit, asyncio.CancelledError):
            status = 499  # client left mid-stream (see chat_completions)
            raise
        finally:
            scope.close()
            finish_request_trace(trace, status=status, mode="stream")

    async def _chat_impl(request: Request, trace: RequestTrace) -> Response:
        cfg, reg = await current()
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"Invalid JSON body: {e}", "type": "invalid_request_error"}},
                status_code=400,
            )
        # Internal plan field (the /completions raw-prompt path) — never
        # accepted from the wire: it would bypass chat templating.
        body.pop("_raw_prompt_ids", None)

        headers = _resolve_headers(request.headers)
        if headers is None:
            return _auth_error()

        # After auth (reference ordering, oai_proxy.py:976 then :1026): a
        # malformed knob is one 400 up front, not N backend failures → 500.
        invalid = oai.validate_request_body(body)
        if invalid is not None:
            return JSONResponse(
                {"error": {"message": invalid, "type": "invalid_request_error"}},
                status_code=400,
            )

        if len(reg) == 0:
            return JSONResponse(
                {"error": {"message": "No valid backends configured", "type": "configuration_error"}},
                status_code=500,
            )

        # Trace identity rides the RequestTrace (stamped by the wrapper);
        # the body knob was only a carrier for header-less clients — never
        # forwarded (upstreams would reject an unknown field).
        body.pop("traceparent", None)

        # Cross-cell quorum is the ROUTER's job (docs/quorum.md): this
        # server is one cell. quorum=1 is a no-op (stripped); quorum>1
        # reaching a cell directly is a topology error, not something to
        # silently serve at 1/M strength.
        if (body.pop("quorum", None) or 1) > 1:
            return JSONResponse(
                {"error": {"message": "'quorum' requires the router tier "
                           "(python -m quorum_tpu.router): this server is "
                           "a single cell and cannot fan out across "
                           "replicas", "type": "invalid_request_error"}},
                status_code=400,
            )

        is_streaming = bool(body.get("stream", False))
        is_parallel = cfg.parallel_enabled(len(reg))
        # Per-request deadline override (validated above): a client that
        # knows its own budget caps the whole request — engine deadline AND
        # every HTTP backend hop inherit it; the knob is consumed here, not
        # forwarded (upstreams would reject an unknown field). ``deadline``
        # anchors the budget so SEQUENTIAL hops (fan-out then aggregator)
        # split one allowance instead of each getting a fresh full one.
        timeout = float(body.pop("timeout", None) or cfg.timeout)
        deadline = time.monotonic() + timeout
        # SLO class from deadline headroom (telemetry/slo.py): tagged on
        # the trace now, scored once against the class's TTFT/inter-token/
        # deadline objectives at teardown (finish_request_trace).
        trace.meta["slo"] = slo_mod.classify(timeout)

        # Resolve the actual fan-out targets first: in aggregate strategy only
        # the configured source_backends are called (fix of quirk 4), and both
        # the model check and the empty-selection guard must look at *them*,
        # not the whole registry.
        if is_parallel and cfg.strategy_name == "aggregate":
            targets = reg.select(cfg.aggregate.source_backends)
            if not targets:
                return JSONResponse(
                    {
                        "error": {
                            "message": "source_backends matches no configured backend",
                            "type": "configuration_error",
                        }
                    },
                    status_code=500,
                )
        else:
            targets = reg.backends

        # 400 only when every target call would fail the model check; with a
        # mixed config (some backends carry a model) partial success applies.
        if "model" not in body and not any(b.model for b in targets):
            return JSONResponse(
                {
                    "error": {
                        "message": "Model must be specified when config.yaml model is blank",
                        "type": "invalid_request_error",
                    }
                },
                status_code=400,
            )

        trace.meta["mode"] = (
            ("parallel-" if is_parallel else "single-")
            + ("stream" if is_streaming else "complete"))
        trace.meta["backends"] = [b.name for b in targets]

        if is_streaming:
            if is_parallel:
                plan = StreamPlan.from_config(cfg, reg, body)
                return StreamingResponse(
                    parallel_stream(plan, body, headers, timeout,
                                    trace=trace)
                )
            return await _single_stream(targets[0], body, headers, timeout)

        # Non-streaming. Parity: every backend is called even in non-parallel
        # mode (oai_proxy.py:1132-1137).
        with trace.span("fanout", backends=len(targets)):
            outcomes = await fanout_complete(targets, body, headers, timeout)
        successes = [o for o in outcomes if o.ok]
        if not successes:
            # When EVERY backend rejected the request as a client error
            # (e.g. 'tools' on a tpu:// backend, docs/api.md knob table) or
            # reported overload (503 queue-full), the status is meaningful to
            # the client: relay the first error verbatim instead of
            # collapsing it into a 500 proxy_error (which breaks retry logic
            # keyed on 4xx-vs-503).
            def relayable(o):
                return o.error is not None and (
                    400 <= o.error.status_code < 500
                    or o.error.status_code in (503, 504)
                )

            if all(relayable(o) for o in outcomes):
                first_err = outcomes[0].error
                return JSONResponse(first_err.body,
                                    status_code=first_err.status_code,
                                    headers=first_err.headers)
            return JSONResponse(
                {
                    "error": {
                        "message": f"All backends failed. First error: {outcomes[0].error_message}",
                        "type": "proxy_error",
                    }
                },
                status_code=500,
            )

        if is_parallel:
            with trace.span("aggregate", strategy=cfg.strategy_name):
                combined, agg_outcome = await combine_outcomes(
                    cfg, reg, outcomes, body, headers,
                    # The aggregator hop runs AFTER the fan-out: it gets the
                    # remaining budget, not a second full one, so the
                    # request's declared deadline bounds the whole chain.
                    aggregator_timeout=max(
                        0.001, deadline - time.monotonic()),
                )
            # A degraded aggregate (separator-join fallback) is marked in
            # response headers so clients can tell it from a real synthesis
            # (docs/quorum.md). Streaming can't do this — headers are gone
            # by the time the final hop runs — so it relies on the counter
            # + recorder event instead.
            return JSONResponse(combined, headers=degraded_headers(agg_outcome))

        # Non-parallel: first successful response verbatim (oai_proxy.py:1356-1380).
        first = successes[0]
        resp_headers = {
            k: v
            for k, v in first.result.headers.items()
            if k.lower() not in _PASSTHROUGH_SKIP
        }
        return JSONResponse(first.result.body, status_code=first.result.status_code, headers=resp_headers)

    def _relay_backend_error(e: BackendError) -> Response:
        """Typed client errors keep their body verbatim; everything else
        normalizes to proxy_error (the chat error contract — docs/api.md).
        Either way the error's response headers ride along — 503/504s carry
        Retry-After (docs/robustness.md)."""
        err = e.body.get("error")
        if isinstance(err, dict) and err.get("type") not in (None, "proxy_error"):
            return JSONResponse(e.body, status_code=e.status_code,
                                headers=e.headers)
        msg = err.get("message", str(e)) if isinstance(err, dict) else str(e)
        return JSONResponse(
            {"error": {"message": f"Backend failed: {msg}",
                       "type": "proxy_error"}},
            status_code=e.status_code,
            headers=e.headers,
        )

    async def _single_backend_request(
        request: Request, capability: str, what: str
    ):
        """Shared preamble for the no-fan-out endpoints (/embeddings,
        /completions): parse + auth, strip internal-only fields, pick the
        single target — the backend whose configured model matches the
        request model; with no model in the request, the first capable one
        in config order. A requested model no capable backend is pinned to
        falls to a blank-model backend (it forwards/serves whatever the
        request names) or — with every candidate pinned elsewhere — 404s
        with OpenAI's ``model_not_found``. Returns
        ``(cfg, body, headers, target)`` or an error Response."""
        cfg, reg = await current()
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        except Exception as e:
            return JSONResponse(
                {"error": {"message": f"Invalid JSON body: {e}",
                           "type": "invalid_request_error"}},
                status_code=400,
            )
        # Internal plan field (raw-prompt path) — never accepted from the
        # wire, or a client could bypass chat templating with it.
        body.pop("_raw_prompt_ids", None)
        headers = _resolve_headers(request.headers)
        if headers is None:
            return _auth_error()
        candidates = [b for b in reg.backends if hasattr(b, capability)]
        if not candidates:
            return JSONResponse(
                {"error": {"message": f"No backend supports {what}",
                           "type": "configuration_error"}},
                status_code=500,
            )
        req_model = body.get("model")
        target = next(
            (b for b in candidates if req_model and b.model == req_model),
            None)
        if target is None and req_model:
            # A typo'd or unserved model must NOT silently fall to a
            # different model's backend — eval harnesses key results on
            # `model`, and OpenAI answers model_not_found here. A backend
            # with a blank configured model is the exception: only
            # http(s):// relays can be blank (TpuBackend.model falls back
            # to its model_id — pinned by test_embeddings), and a relay
            # forwards the requested name for the UPSTREAM to validate.
            target = next((b for b in candidates if not b.model), None)
            if target is None:
                return JSONResponse(
                    {"error": {
                        "message": f"The model '{req_model}' does not "
                                   "exist or is not served by any "
                                   f"backend with {what} support",
                        "type": "invalid_request_error",
                        "param": "model",
                        "code": "model_not_found"}},
                    status_code=404)
        if target is None:
            target = candidates[0]
        return (cfg, body, headers, target)

    @app.route("POST", "/embeddings", "/v1/embeddings")
    async def embeddings(request: Request) -> Response:
        """OpenAI embeddings surface, served from the chat models' resident
        weights (quorum_tpu/engine/embed.py) or relayed to an ``http(s)://``
        upstream. NOT a fan-out: one embedding space per response is the
        only coherent contract. (Beyond reference: it serves only
        /chat/completions and /health.)"""
        got = await _single_backend_request(request, "embed", "embeddings")
        if isinstance(got, Response):
            return got
        cfg, body, headers, target = got
        try:
            result = await target.embed(body, headers, cfg.timeout)
        except BackendError as e:
            return _relay_backend_error(e)
        return JSONResponse(result.body, status_code=result.status_code)

    @app.route("POST", "/completions", "/v1/completions")
    async def completions(request: Request) -> Response:
        """Legacy OpenAI text completions (beyond reference): raw-prompt
        generation plus the ``echo+logprobs`` teacher-forced scoring mode
        eval harnesses use. Routes like /embeddings — one backend, no
        fan-out. Streaming is supported on ``tpu://`` backends for a single
        prompt without echo/logprobs; ``http(s)://`` backends relay
        non-streaming only."""
        got = await _single_backend_request(
            request, "text_complete", "/completions")
        if isinstance(got, Response):
            return got
        cfg, body, headers, target = got

        if body.get("stream"):
            if not hasattr(target, "plan_text_stream"):
                return JSONResponse(
                    {"error": {"message": "streaming /completions is only "
                               "served by tpu:// backends",
                               "type": "invalid_request_error"}},
                    status_code=400,
                )
            # Validation lives with the backend (shared with the flat
            # path) — the route only converts chunk shapes.
            try:
                sbody, model = target.plan_text_stream(body)
            except BackendError as e:
                return _relay_backend_error(e)
            stream = target.stream(sbody, headers, cfg.timeout)
            try:
                first_chunk = await stream.__anext__()
            except StopAsyncIteration:
                first_chunk = None
            except BackendError as e:
                return _relay_backend_error(e)
            return StreamingResponse(
                _completions_stream(first_chunk, stream, model))

        try:
            result = await target.text_complete(body, headers, cfg.timeout)
        except BackendError as e:
            return _relay_backend_error(e)
        return JSONResponse(result.body, status_code=result.status_code)

    async def _completions_stream(
        first_chunk: dict[str, Any] | None,
        rest: AsyncIterator[dict[str, Any]],
        model: str,
    ) -> AsyncIterator[bytes]:
        """chat.completion.chunk frames → text_completion SSE frames (the
        legacy wire shape: choices[].text, no role/delta), [DONE]-terminated."""
        cid = f"cmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())

        def convert(chunk: dict[str, Any]) -> dict[str, Any] | None:
            choice = (chunk.get("choices") or [{}])[0]
            delta = choice.get("delta") or {}
            content = delta.get("content")
            finish = choice.get("finish_reason")
            if content or finish:
                return {"id": cid, "object": "text_completion",
                        "created": created, "model": model,
                        "choices": [{"index": 0, "text": content or "",
                                     "logprobs": None,
                                     "finish_reason": finish}]}
            if chunk.get("usage") is not None and not chunk.get("choices"):
                return {"id": cid, "object": "text_completion",
                        "created": created, "model": model,
                        "choices": [], "usage": chunk["usage"]}
            return None  # role-only chunks have no legacy-wire analog

        def encode(chunk: dict[str, Any]) -> bytes | None:
            out = convert(chunk)
            return sse.encode_event(out) if out is not None else None

        co = _SSECoalescer()
        try:
            if first_chunk is not None:
                if flushed := co.add(first_chunk, encode(first_chunk)):
                    yield flushed
            async for chunk in rest:
                if flushed := co.add(chunk, encode(chunk)):
                    yield flushed
        except BackendError as e:
            if flushed := co.drain():
                yield flushed
            yield sse.encode_event(
                {"id": cid, "object": "text_completion", "created": created,
                 "model": model,
                 "choices": [{"index": 0, "text": f"Backend failed: {e}",
                              "logprobs": None, "finish_reason": "error"}]})
        if flushed := co.drain():
            yield flushed
        yield sse.encode_done()

    async def _single_stream(
        backend: Backend, body: dict[str, Any], headers: dict[str, str], timeout: float
    ) -> Response:
        model = body.get("model") or backend.model or "unknown"
        stream = backend.stream(body, headers, timeout)
        try:
            first_chunk = await stream.__anext__()
        except StopAsyncIteration:
            first_chunk = None
        except BackendError as e:
            # Failure before any token: JSON error with upstream status
            # (oai_proxy.py:1107-1128 parity); typed errors keep their body
            # verbatim — stream and non-stream must present the same error
            # contract (docs/api.md error table).
            return _relay_backend_error(e)
        return StreamingResponse(_stream_with_role(first_chunk, stream, model))

    return app
