"""Minimal ASGI toolkit: Request, Response types, and a method+path router.

Scope: exactly what the API layer needs (JSON bodies, JSON responses, SSE
streaming responses). The app remains a standard ASGI3 callable so it works
under httpx.ASGITransport (tests), the bundled h11 server (production), or any
external ASGI server.
"""

from __future__ import annotations

import json
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

logger = logging.getLogger(__name__)


class Request:
    def __init__(self, scope: dict[str, Any], receive: Callable):
        self.scope = scope
        self._receive = receive
        self._body: bytes | None = None
        # Filled by the router for routes registered with a trailing
        # ``/{param}`` segment (e.g. /debug/traces/{request_id}).
        self.path_params: dict[str, str] = {}

    @property
    def method(self) -> str:
        return self.scope["method"].upper()

    @property
    def path(self) -> str:
        return self.scope["path"]

    @property
    def query_params(self) -> dict[str, str]:
        """Decoded query-string parameters (last value wins on repeats) —
        the router matches on ``path`` alone, so ``?seconds=5`` style knobs
        (POST /debug/profile) read from here."""
        if not hasattr(self, "_query_params"):
            from urllib.parse import parse_qsl

            raw = self.scope.get("query_string", b"") or b""
            self._query_params = dict(
                parse_qsl(raw.decode("latin-1"), keep_blank_values=True))
        return self._query_params

    @property
    def headers(self) -> dict[str, str]:
        """Headers with original casing preserved (the reference forwards
        header casing through to upstreams; latin-1 per ASGI spec)."""
        if not hasattr(self, "_headers"):
            self._headers = {
                k.decode("latin-1"): v.decode("latin-1")
                for k, v in self.scope.get("headers", [])
            }
        return self._headers

    async def body(self) -> bytes:
        if self._body is None:
            chunks = []
            while True:
                message = await self._receive()
                chunks.append(message.get("body", b""))
                if not message.get("more_body"):
                    break
            self._body = b"".join(chunks)
        return self._body

    async def json(self) -> Any:
        return json.loads(await self.body())


class Response:
    media_type = "application/octet-stream"

    def __init__(
        self,
        content: bytes | str = b"",
        status_code: int = 200,
        headers: dict[str, str] | None = None,
        media_type: str | None = None,
    ):
        self.body = content.encode() if isinstance(content, str) else content
        self.status_code = status_code
        self.headers = dict(headers or {})
        if media_type is not None:
            self.media_type = media_type

    def _header_list(self, extra: dict[str, str]) -> list[tuple[bytes, bytes]]:
        merged = {**extra, **self.headers}
        merged.setdefault("content-type", self.media_type)
        return [(k.encode("latin-1"), v.encode("latin-1")) for k, v in merged.items()]

    async def __call__(self, scope, receive, send) -> None:
        await send(
            {
                "type": "http.response.start",
                "status": self.status_code,
                "headers": self._header_list({"content-length": str(len(self.body))}),
            }
        )
        await send({"type": "http.response.body", "body": self.body})


class JSONResponse(Response):
    media_type = "application/json"

    def __init__(self, content: Any, status_code: int = 200, headers: dict[str, str] | None = None):
        super().__init__(json.dumps(content), status_code, headers)


class StreamingResponse(Response):
    """Streams an async byte iterator; used for SSE (``text/event-stream``)."""

    media_type = "text/event-stream"

    def __init__(
        self,
        iterator: AsyncIterator[bytes],
        status_code: int = 200,
        headers: dict[str, str] | None = None,
        media_type: str | None = None,
    ):
        super().__init__(b"", status_code, headers, media_type)
        self.iterator = iterator

    async def __call__(self, scope, receive, send) -> None:
        await send(
            {
                "type": "http.response.start",
                "status": self.status_code,
                "headers": self._header_list({"cache-control": "no-cache"}),
            }
        )
        try:
            async for chunk in self.iterator:
                if chunk:
                    await send(
                        {"type": "http.response.body", "body": chunk, "more_body": True}
                    )
        finally:
            # Client disconnects surface as send() raising: close the
            # iterator NOW so its finally blocks (backend cancellation,
            # profiler scope, timing) run deterministically, not at GC.
            aclose = getattr(self.iterator, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
            try:
                await send({"type": "http.response.body", "body": b""})
            except Exception:
                pass  # peer already gone; the original exception propagates


Handler = Callable[[Request], Awaitable[Response]]


class App:
    """Method+path router implementing the ASGI3 interface."""

    def __init__(self) -> None:
        self._routes: dict[tuple[str, str], Handler] = {}
        # (method, prefix, param name, handler) for ``.../{param}`` routes —
        # matched when the exact table misses and the remainder after the
        # prefix is one non-empty segment.
        self._param_routes: list[tuple[str, str, str, Handler]] = []
        self.state: dict[str, Any] = {}

    def route(self, method: str, *paths: str) -> Callable[[Handler], Handler]:
        def register(handler: Handler) -> Handler:
            for p in paths:
                if p.endswith("}") and "/{" in p:
                    prefix, _, param = p.rpartition("/")
                    self._param_routes.append(
                        (method.upper(), prefix + "/", param[1:-1], handler))
                else:
                    self._routes[(method.upper(), p)] = handler
            return handler

        return register

    @staticmethod
    def _tail_segment(path: str, prefix: str) -> str | None:
        """The single non-empty segment after ``prefix``, or None — the one
        param-route matching predicate (shared by dispatch and the
        405-vs-404 decision, so the two can never drift)."""
        if not path.startswith(prefix):
            return None
        rest = path[len(prefix):]
        return rest if rest and "/" not in rest else None

    def _match_param_route(self, request: Request) -> Handler | None:
        for method, prefix, param, handler in self._param_routes:
            if method != request.method:
                continue
            rest = self._tail_segment(request.path, prefix)
            if rest is not None:
                request.path_params[param] = rest
                return handler
        return None

    async def __call__(self, scope, receive, send) -> None:
        if scope["type"] == "lifespan":
            # Drain lifespan events so ASGI servers that emit them work.
            while True:
                message = await receive()
                if message["type"] == "lifespan.startup":
                    await send({"type": "lifespan.startup.complete"})
                elif message["type"] == "lifespan.shutdown":
                    await send({"type": "lifespan.shutdown.complete"})
                    return
        if scope["type"] != "http":
            return
        request = Request(scope, receive)
        handler = self._routes.get((request.method, request.path))
        if handler is None:
            handler = self._match_param_route(request)
        if handler is None:
            known_paths = {p for (_, p) in self._routes}
            # A param route of another method still makes the path "known":
            # POST /debug/traces/abc must 405 like POST /metrics does.
            param_known = any(
                self._tail_segment(request.path, prefix) is not None
                for (_, prefix, _, _) in self._param_routes)
            if request.path in known_paths or param_known:
                response: Response = JSONResponse(
                    {"error": {"message": "Method not allowed", "type": "invalid_request_error"}},
                    status_code=405,
                )
            else:
                response = JSONResponse(
                    {"error": {"message": "Not found", "type": "invalid_request_error"}},
                    status_code=404,
                )
        else:
            try:
                response = await handler(request)
            except Exception as e:  # last-resort normalization (oai_proxy.py:1395-1408)
                logger.exception("Unhandled error in %s %s", request.method, request.path)
                response = JSONResponse(
                    {"error": {"message": f"Error processing request: {e}", "type": "proxy_error"}},
                    status_code=500,
                )
        # Every response carries a request id (docs/api.md, api/openapi.yaml);
        # the chat handler sets its own richer id first — setdefault keeps it.
        import uuid

        response.headers.setdefault("X-Request-Id",
                                    f"req-{uuid.uuid4().hex[:16]}")
        await response(scope, receive, send)
