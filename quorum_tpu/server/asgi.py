"""Minimal ASGI toolkit: Request, Response types, and a method+path router.

Scope: exactly what the API layer needs (JSON bodies, JSON responses, SSE
streaming responses). The app remains a standard ASGI3 callable so it works
under httpx.ASGITransport (tests), the bundled h11 server (production), or any
external ASGI server.
"""

from __future__ import annotations

import json
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

logger = logging.getLogger(__name__)


class Request:
    def __init__(self, scope: dict[str, Any], receive: Callable):
        self.scope = scope
        self._receive = receive
        self._body: bytes | None = None

    @property
    def method(self) -> str:
        return self.scope["method"].upper()

    @property
    def path(self) -> str:
        return self.scope["path"]

    @property
    def headers(self) -> dict[str, str]:
        """Headers with original casing preserved (the reference forwards
        header casing through to upstreams; latin-1 per ASGI spec)."""
        if not hasattr(self, "_headers"):
            self._headers = {
                k.decode("latin-1"): v.decode("latin-1")
                for k, v in self.scope.get("headers", [])
            }
        return self._headers

    async def body(self) -> bytes:
        if self._body is None:
            chunks = []
            while True:
                message = await self._receive()
                chunks.append(message.get("body", b""))
                if not message.get("more_body"):
                    break
            self._body = b"".join(chunks)
        return self._body

    async def json(self) -> Any:
        return json.loads(await self.body())


class Response:
    media_type = "application/octet-stream"

    def __init__(
        self,
        content: bytes | str = b"",
        status_code: int = 200,
        headers: dict[str, str] | None = None,
        media_type: str | None = None,
    ):
        self.body = content.encode() if isinstance(content, str) else content
        self.status_code = status_code
        self.headers = dict(headers or {})
        if media_type is not None:
            self.media_type = media_type

    def _header_list(self, extra: dict[str, str]) -> list[tuple[bytes, bytes]]:
        merged = {**extra, **self.headers}
        merged.setdefault("content-type", self.media_type)
        return [(k.encode("latin-1"), v.encode("latin-1")) for k, v in merged.items()]

    async def __call__(self, scope, receive, send) -> None:
        await send(
            {
                "type": "http.response.start",
                "status": self.status_code,
                "headers": self._header_list({"content-length": str(len(self.body))}),
            }
        )
        await send({"type": "http.response.body", "body": self.body})


class JSONResponse(Response):
    media_type = "application/json"

    def __init__(self, content: Any, status_code: int = 200, headers: dict[str, str] | None = None):
        super().__init__(json.dumps(content), status_code, headers)


class StreamingResponse(Response):
    """Streams an async byte iterator; used for SSE (``text/event-stream``)."""

    media_type = "text/event-stream"

    def __init__(
        self,
        iterator: AsyncIterator[bytes],
        status_code: int = 200,
        headers: dict[str, str] | None = None,
        media_type: str | None = None,
    ):
        super().__init__(b"", status_code, headers, media_type)
        self.iterator = iterator

    async def __call__(self, scope, receive, send) -> None:
        await send(
            {
                "type": "http.response.start",
                "status": self.status_code,
                "headers": self._header_list({"cache-control": "no-cache"}),
            }
        )
        try:
            async for chunk in self.iterator:
                if chunk:
                    await send(
                        {"type": "http.response.body", "body": chunk, "more_body": True}
                    )
        finally:
            # Client disconnects surface as send() raising: close the
            # iterator NOW so its finally blocks (backend cancellation,
            # profiler scope, timing) run deterministically, not at GC.
            aclose = getattr(self.iterator, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
            try:
                await send({"type": "http.response.body", "body": b""})
            except Exception:
                pass  # peer already gone; the original exception propagates


Handler = Callable[[Request], Awaitable[Response]]


class App:
    """Method+path router implementing the ASGI3 interface."""

    def __init__(self) -> None:
        self._routes: dict[tuple[str, str], Handler] = {}
        self.state: dict[str, Any] = {}

    def route(self, method: str, *paths: str) -> Callable[[Handler], Handler]:
        def register(handler: Handler) -> Handler:
            for p in paths:
                self._routes[(method.upper(), p)] = handler
            return handler

        return register

    async def __call__(self, scope, receive, send) -> None:
        if scope["type"] == "lifespan":
            # Drain lifespan events so ASGI servers that emit them work.
            while True:
                message = await receive()
                if message["type"] == "lifespan.startup":
                    await send({"type": "lifespan.startup.complete"})
                elif message["type"] == "lifespan.shutdown":
                    await send({"type": "lifespan.shutdown.complete"})
                    return
        if scope["type"] != "http":
            return
        request = Request(scope, receive)
        handler = self._routes.get((request.method, request.path))
        if handler is None:
            known_paths = {p for (_, p) in self._routes}
            if request.path in known_paths:
                response: Response = JSONResponse(
                    {"error": {"message": "Method not allowed", "type": "invalid_request_error"}},
                    status_code=405,
                )
            else:
                response = JSONResponse(
                    {"error": {"message": "Not found", "type": "invalid_request_error"}},
                    status_code=404,
                )
        else:
            try:
                response = await handler(request)
            except Exception as e:  # last-resort normalization (oai_proxy.py:1395-1408)
                logger.exception("Unhandled error in %s %s", request.method, request.path)
                response = JSONResponse(
                    {"error": {"message": f"Error processing request: {e}", "type": "proxy_error"}},
                    status_code=500,
                )
        # Every response carries a request id (docs/api.md, api/openapi.yaml);
        # the chat handler sets its own richer id first — setdefault keeps it.
        import uuid

        response.headers.setdefault("X-Request-Id",
                                    f"req-{uuid.uuid4().hex[:16]}")
        await response(scope, receive, send)
