"""Dev-mode config hot reload.

Reference parity target: the reference dev server restarts the whole uvicorn
process when ``config.yaml`` changes (/root/reference/Makefile:4,
``--reload-include "*.yaml"``) — losing all in-process state. A restart is
the one thing a TPU serving process must avoid: its engines hold compiled
programs and resident weights (minutes to rebuild at 7B scale). So reload
here is *in-process and incremental*: the watcher stats the config file on
request arrival (rate-limited), and on a change re-parses the YAML and swaps
in a rebuilt registry that REUSES every backend whose (name, url, model)
identity is unchanged — live ``tpu://`` engines keep serving across edits to
strategy blocks, separators, timeouts, or other backends. Only backends the
edit actually touched are constructed (and even those re-attach to cached
weights when their URL is unchanged — ``engine.get_engine`` keys on weight
identity).

A malformed edit must not take down a serving process: parse failures keep
the previous config/registry and log the error (the next successful parse
applies cleanly). Watching is opt-in (``--watch`` / ``QUORUM_TPU_CONFIG_WATCH=1``)
and requires a file-backed config.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from pathlib import Path
from typing import Any

import yaml

from quorum_tpu.backends.base import Backend
from quorum_tpu.backends.registry import BackendRegistry, rebuild_registry
from quorum_tpu.config import Config

logger = logging.getLogger(__name__)

# Floor between stat() calls: request-driven polling must stay ~free under
# load (one os.stat per window, not per request).
_POLL_INTERVAL_S = 0.5


class Runtime:
    """Mutable holder for the app's (config, registry) pair — handlers read
    through it so a reload swap is atomic for subsequent requests."""

    __slots__ = ("cfg", "reg")

    def __init__(self, cfg: Config, reg: BackendRegistry):
        self.cfg = cfg
        self.reg = reg


class ConfigWatcher:
    def __init__(self, path: str | os.PathLike, runtime: Runtime,
                 overrides: dict[str, Backend]):
        self.path = Path(path)
        self._runtime = runtime
        self._overrides = dict(overrides)
        self._sig = self._stat_sig()
        self._next_check = 0.0
        self._busy = False

    def _stat_sig(self) -> tuple[int, int] | None:
        try:
            st = os.stat(self.path)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    async def poll(self) -> None:
        """Reload if the file changed; called at request arrival."""
        if self._busy:
            return  # a rebuild is in flight; serve on the previous config
        now = time.monotonic()
        if now < self._next_check:
            return
        self._next_check = now + _POLL_INTERVAL_S
        sig = self._stat_sig()
        if sig == self._sig:
            return
        self._sig = sig
        self._busy = True
        try:
            await self._reload()
        finally:
            self._busy = False

    async def _reload(self) -> None:
        rt = self._runtime

        def build() -> tuple[Config, Any, list[Backend]]:
            raw: Any = yaml.safe_load(self.path.read_text())
            if not isinstance(raw, dict):
                raise ValueError(
                    f"config root must be a mapping, got {type(raw).__name__}")
            new_cfg = Config(raw=raw, source_path=self.path)
            new_reg, dropped = rebuild_registry(new_cfg, rt.reg,
                                                self._overrides)
            return new_cfg, new_reg, dropped

        # Off the event loop: constructing a changed tpu:// backend loads
        # weights and compiles (minutes at 7B) — in-flight streams must
        # keep draining on the previous registry meanwhile. The build must
        # also never take down serving: ANY failure (YAML typo, valid YAML
        # with a malformed backends shape, a bad tpu:// URL) keeps the
        # previous config and logs; the next successful edit applies.
        try:
            new_cfg, new_reg, dropped = await asyncio.to_thread(build)
        except Exception as e:
            logger.error("Config reload from %s failed (%s); keeping the "
                         "previous configuration", self.path, e)
            return
        rt.cfg, rt.reg = new_cfg, new_reg
        logger.info(
            "Config reloaded from %s: %d backend(s) active, %d dropped",
            self.path, len(new_reg), len(dropped))
        # Release what the edit dropped: HTTP clients close; tpu:// engines
        # shut down and leave the shared cache UNLESS a kept backend still
        # serves from the same engine (engines are shared by weight
        # identity).
        kept_engines = {id(getattr(b, "engine", None))
                        for b in new_reg.backends} - {id(None)}
        released: set[int] = set()
        for b in dropped:
            close = getattr(b, "aclose", None)
            if close is not None:
                try:
                    await close()
                except Exception:
                    logger.exception("Closing dropped backend %s failed",
                                     b.name)
            engine = getattr(b, "engine", None)
            if (engine is not None and id(engine) not in kept_engines
                    and id(engine) not in released):
                released.add(id(engine))
                from quorum_tpu.engine.engine import release_engine

                try:
                    await asyncio.to_thread(release_engine, engine)
                except Exception:
                    logger.exception(
                        "Releasing dropped backend %s's engine failed",
                        b.name)
