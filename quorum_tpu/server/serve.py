"""Standalone HTTP/1.1 server for the ASGI app, built on h11 + asyncio.

The reference ran under uvicorn (/root/reference/Makefile:3-7); uvicorn is not
available in this environment, so quorum_tpu bundles a small ASGI server. It
supports exactly what the API needs: request bodies, JSON responses, and
incrementally-flushed streaming (SSE) responses with chunked transfer encoding.

Run:  python -m quorum_tpu.server.serve --port 8000 [--config config.yaml]
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Any

import h11

from quorum_tpu.config import load_config
from quorum_tpu.observability import setup_aggregation_log
from quorum_tpu.server.app import create_app

logger = logging.getLogger(__name__)


class _ConnectionHandler:
    def __init__(self, app, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.app = app
        self.reader = reader
        self.writer = writer
        self.conn = h11.Connection(h11.SERVER)

    async def run(self) -> None:
        try:
            while True:
                request = await self._next_request()
                if request is None:
                    return
                await self._handle(request)
                if self.conn.our_state is h11.MUST_CLOSE or self.conn.their_state is h11.MUST_CLOSE:
                    return
                try:
                    self.conn.start_next_cycle()
                except h11.ProtocolError:
                    return
        except (ConnectionResetError, BrokenPipeError, asyncio.IncompleteReadError):
            pass
        except Exception:
            logger.exception("Connection handler error")
        finally:
            self.writer.close()

    async def _next_event(self):
        while True:
            event = self.conn.next_event()
            if event is h11.NEED_DATA:
                data = await self.reader.read(65536)
                self.conn.receive_data(data)
                if data == b"" and self.conn.their_state is h11.IDLE:
                    return None
                continue
            return event

    async def _next_request(self) -> h11.Request | None:
        while True:
            event = await self._next_event()
            if event is None or isinstance(event, h11.ConnectionClosed):
                return None
            if isinstance(event, h11.Request):
                return event

    async def _read_body(self) -> bytes:
        chunks = []
        while True:
            event = await self._next_event()
            if isinstance(event, h11.Data):
                chunks.append(bytes(event.data))
            elif isinstance(event, h11.EndOfMessage) or event is None:
                return b"".join(chunks)

    async def _handle(self, request: h11.Request) -> None:
        body = await self._read_body()
        path, _, query = request.target.partition(b"?")
        scope: dict[str, Any] = {
            "type": "http",
            "asgi": {"version": "3.0"},
            "http_version": "1.1",
            "method": request.method.decode(),
            "path": path.decode(),
            "raw_path": bytes(request.target),
            "query_string": query,
            "headers": [(k.lower(), v) for k, v in request.headers],
            "client": self.writer.get_extra_info("peername"),
            "server": self.writer.get_extra_info("sockname"),
            "scheme": "http",
        }

        body_sent = False

        async def receive():
            nonlocal body_sent
            if body_sent:
                return {"type": "http.disconnect"}
            body_sent = True
            return {"type": "http.request", "body": body, "more_body": False}

        started = False

        async def send(message: dict[str, Any]) -> None:
            nonlocal started
            if message["type"] == "http.response.start":
                started = True
                headers = [(k, v) for k, v in message.get("headers", [])]
                self._send(
                    h11.Response(status_code=message["status"], headers=headers)
                )
            elif message["type"] == "http.response.body":
                data = message.get("body", b"")
                if data:
                    self._send(h11.Data(data=data))
                if not message.get("more_body", False):
                    self._send(h11.EndOfMessage())
                await self.writer.drain()

        try:
            await self.app(scope, receive, send)
        except Exception:
            logger.exception("ASGI app error")
            if not started:
                self._send(
                    h11.Response(
                        status_code=500,
                        headers=[(b"content-type", b"application/json")],
                    )
                )
                self._send(h11.Data(data=b'{"error":{"message":"internal error"}}'))
                self._send(h11.EndOfMessage())
                await self.writer.drain()

    def _send(self, event) -> None:
        data = self.conn.send(event)
        if data:
            self.writer.write(data)


async def start_server(app, host: str = "127.0.0.1", port: int = 0) -> asyncio.Server:
    """Bind and return the asyncio server (port 0 → ephemeral; read the bound
    port from ``server.sockets[0].getsockname()[1]``). Used by bench.py and the
    socket-level tests, which need a real TCP socket — httpx.ASGITransport
    buffers entire responses and cannot observe streaming incrementality."""

    async def on_connect(reader, writer):
        await _ConnectionHandler(app, reader, writer).run()

    return await asyncio.start_server(on_connect, host, port)


async def serve(app, host: str = "0.0.0.0", port: int = 8000) -> None:
    server = await start_server(app, host, port)
    addrs = ", ".join(str(s.getsockname()) for s in server.sockets)
    logger.info("quorum_tpu serving on %s", addrs)
    async with server:
        await server.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser(description="quorum_tpu OpenAI-compatible server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--config", default=None, help="path to config.yaml")
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument(
        "--log-dir", default="logs",
        help="directory for the aggregation log channel (logs/aggregation.log)",
    )
    parser.add_argument(
        "--watch", action="store_true",
        help="dev mode: hot-reload config.yaml edits in-process without "
             "dropping live tpu:// engines (reference parity with its "
             "uvicorn --reload-include '*.yaml' dev server)",
    )
    args = parser.parse_args()

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(levelname)s:%(asctime)s:%(name)s: %(message)s",
    )
    setup_aggregation_log(args.log_dir)
    # Multi-host deployments: join the jax process group before any backend
    # initializes a device client (no-op for single-process runs — laptop,
    # one chip, CPU). Env-driven: JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES
    # / JAX_PROCESS_ID, or TPU-pod metadata inference.
    from quorum_tpu.parallel.distributed import initialize

    initialize()
    cfg = load_config(args.config)
    app = create_app(cfg, watch_config=True if args.watch else None)
    try:
        asyncio.run(serve(app, args.host, args.port))
    except KeyboardInterrupt:
        pass
    finally:
        # Graceful teardown: cancel in-flight generations, join scheduler
        # threads, release HBM — not strictly needed on process exit, but it
        # makes embedding (and Ctrl-C during local runs) clean.
        from quorum_tpu.engine.engine import shutdown_all_engines

        shutdown_all_engines()


if __name__ == "__main__":
    main()
