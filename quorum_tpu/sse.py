"""Server-Sent Events wire format: encoding and incremental parsing.

The reference splits SSE frames ad hoc inside its streaming aggregator
(/root/reference/src/quorum/oai_proxy.py:595-615) and its tests build frames by
hand (tests/conftest.py:213-249). Here the wire format is one shared module used
by the server (emit), the HTTP backend (consume upstream streams), and the test
suite (golden transcripts).

Frames follow the OpenAI streaming contract: each event is a single
``data: <json>`` line terminated by a blank line; the stream ends with
``data: [DONE]``.
"""

from __future__ import annotations

import json
from typing import Any, AsyncIterator, Iterator

DONE = "[DONE]"


def encode_event(payload: dict[str, Any] | str) -> bytes:
    """Encode one SSE ``data:`` event (JSON dict or raw sentinel string)."""
    if isinstance(payload, str):
        return f"data: {payload}\n\n".encode()
    return f"data: {json.dumps(payload, separators=(',', ':'))}\n\n".encode()


def encode_done() -> bytes:
    return encode_event(DONE)


async def instrument_stream(iterator: AsyncIterator[bytes],
                            trace) -> AsyncIterator[bytes]:
    """Wire-level latency capture: pass bytes through, marking every flush
    on the request's trace (observability.RequestTrace.mark_flush).

    TTFT and inter-token gaps are measured HERE — at the last point before
    the ASGI send — not in the engine, so they include detokenization,
    strategy merging, and JSON encoding: what the client actually waits
    for. A flush counts as token-bearing when it carries a content delta
    (role-only chunks and ``[DONE]`` never set TTFT); an sse-flush span
    covering first-to-last write lands on the trace at close.

    One yielded byte chunk = one socket flush, but since SSE write
    coalescing it may carry SEVERAL ``data:`` frames (one decode chunk's k
    tokens ship in one write) — the content count per flush is taken
    per-frame, so ``trace.n_tokens`` still counts delivered deltas while
    ``n_flushes`` counts actual writes."""
    if trace is None:
        async for chunk in iterator:
            yield chunk
        return
    span = None
    try:
        async for chunk in iterator:
            if span is None:
                span = trace.add_span("sse-flush", trace.now())
            # Every frame on this stream is encode_event's compact JSON
            # (separators=(",", ":")), so a non-empty content delta always
            # serializes with text after '"content":"' — an upstream's
            # empty-content warm-up frame must not set TTFT.
            n_content = sum(
                1 for frame in chunk.split(b"\n\n")
                if (b'"content":' in frame
                    and b'"content":""' not in frame
                    and b'"content":null' not in frame))
            trace.mark_flush(n_content)
            yield chunk
    finally:
        if span is not None:
            span.end = trace.now()


class SSEParser:
    """Incremental parser: feed raw bytes, yield decoded ``data:`` payloads.

    Handles events split across arbitrary chunk boundaries and both ``\\n\\n``
    and ``\\r\\n\\r\\n`` separators. Yields parsed JSON dicts; the ``[DONE]``
    sentinel is yielded as the string ``"[DONE]"``. Non-JSON data lines are
    yielded as raw strings (the reference logs-and-skips these,
    oai_proxy.py:612-615 — callers decide).
    """

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> Iterator[dict[str, Any] | str]:
        self._buf += chunk
        while True:
            # Find the earliest event terminator of either flavor.
            idx_n = self._buf.find(b"\n\n")
            idx_r = self._buf.find(b"\r\n\r\n")
            if idx_n == -1 and idx_r == -1:
                return
            if idx_r != -1 and (idx_n == -1 or idx_r < idx_n):
                raw, self._buf = self._buf[:idx_r], self._buf[idx_r + 4 :]
            else:
                raw, self._buf = self._buf[:idx_n], self._buf[idx_n + 2 :]
            payload = self._parse_event(raw)
            if payload is not None:
                yield payload

    def flush(self) -> Iterator[dict[str, Any] | str]:
        """Parse any trailing event not followed by a blank line."""
        if self._buf.strip():
            payload = self._parse_event(self._buf)
            if payload is not None:
                yield payload
        self._buf = b""

    @staticmethod
    def _parse_event(raw: bytes) -> dict[str, Any] | str | None:
        data_lines = []
        for line in raw.splitlines():
            line = line.strip()
            if line.startswith(b"data:"):
                data_lines.append(line[5:].strip())
        if not data_lines:
            return None
        data = b"\n".join(data_lines).decode("utf-8", errors="replace")
        if data == DONE:
            return DONE
        try:
            return json.loads(data)
        except json.JSONDecodeError:
            return data


def iter_data_events(body: bytes) -> Iterator[dict[str, Any] | str]:
    """Parse a complete SSE body at once (testing convenience)."""
    p = SSEParser()
    yield from p.feed(body)
    yield from p.flush()
