"""Response-combination strategies: ``concatenate`` and ``aggregate``.

Layer L3 of the framework (SURVEY.md §1). Operates purely on the Backend
protocol — works identically over HTTP upstreams and in-process TPU models.

  fanout.py     parallel dispatch to N backends (non-streaming + streaming)
  aggregate.py  the LLM-synthesis second hop with degrade-to-concatenation
  streaming.py  the SSE parallel streaming aggregator (live interleaving)
"""

from quorum_tpu.strategies.aggregate import (
    AggregateOutcome,
    aggregate_responses,
    aggregate_with_status,
    stream_aggregate_deltas,
)
from quorum_tpu.strategies.combine import combine_outcomes, degraded_headers
from quorum_tpu.strategies.fanout import BackendOutcome, fanout_complete
from quorum_tpu.strategies.streaming import StreamPlan, parallel_stream

__all__ = [
    "AggregateOutcome",
    "BackendOutcome",
    "StreamPlan",
    "aggregate_responses",
    "aggregate_with_status",
    "combine_outcomes",
    "degraded_headers",
    "fanout_complete",
    "parallel_stream",
    "stream_aggregate_deltas",
]
