"""Response-combination strategies: ``concatenate`` and ``aggregate``.

Layer L3 of the framework (SURVEY.md §1). Operates purely on the Backend
protocol — works identically over HTTP upstreams and in-process TPU models.

  fanout.py     parallel dispatch to N backends (non-streaming + streaming)
  aggregate.py  the LLM-synthesis second hop with degrade-to-concatenation
  streaming.py  the SSE parallel streaming aggregator (live interleaving)
"""

from quorum_tpu.strategies.aggregate import aggregate_responses
from quorum_tpu.strategies.combine import combine_outcomes
from quorum_tpu.strategies.fanout import BackendOutcome, fanout_complete
from quorum_tpu.strategies.streaming import StreamPlan, parallel_stream

__all__ = [
    "BackendOutcome",
    "StreamPlan",
    "aggregate_responses",
    "combine_outcomes",
    "fanout_complete",
    "parallel_stream",
]
