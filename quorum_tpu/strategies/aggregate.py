"""The LLM-aggregation second hop.

Parity with ``aggregate_responses`` (/root/reference/src/quorum/oai_proxy.py:374-486):
label + join source responses, build the synthesis prompt, call the aggregator
backend non-streaming with sanitized headers (Authorization + Content-Type
only, with OPENAI_API_KEY env fallback), and degrade to a separator-join of the
raw sources on *any* failure.

Deliberate fixes over the reference:
  - source labels use the real backend names (the reference substituted
    synthetic ``LLM{i+1}`` names, oai_proxy.py:409-411);
  - the prompt template accepts ``{intermediate_results}``,
    ``{{intermediate_results}}``, or the legacy ``{responses}`` placeholder
    (the reference only replaced ``{responses}`` while its shipped config used
    ``{{intermediate_results}}``, so substitution silently never happened —
    oai_proxy.py:424 vs config.yaml:66-73);
  - the aggregator timeout is configurable instead of hardcoded 60 s
    (quirk 12, oai_proxy.py:472).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Any, AsyncIterator, Sequence

from quorum_tpu import oai
from quorum_tpu.backends.base import Backend
from quorum_tpu.config import AggregateParams
from quorum_tpu.observability import AGGREGATE_DEGRADED, current_trace, trace_span
from quorum_tpu.telemetry.recorder import RECORDER

logger = logging.getLogger(__name__)
aggregation_logger = logging.getLogger("aggregation")


@dataclass
class AggregateOutcome:
    """One combine's result + how it was produced.

    ``degraded_reason`` is None for a real LLM aggregation; otherwise one
    of no_aggregator / no_credentials / error / empty — the separator-join
    fallback the reference produced SILENTLY. ``error`` carries the first
    underlying failure message so the serving layer can surface it
    (X-Quorum-Aggregate-Error, docs/quorum.md) and a client can tell a
    degraded combine from a real aggregate."""

    content: str
    degraded_reason: str | None = None
    error: str | None = None

    @property
    def degraded(self) -> bool:
        return self.degraded_reason is not None

_PLACEHOLDERS = ("{{intermediate_results}}", "{intermediate_results}", "{responses}")


def build_aggregation_prompt(
    labeled_sources: Sequence[tuple[str, str]],
    params: AggregateParams,
    user_query: str,
) -> str:
    """Format the synthesis prompt from (backend_name, response_text) pairs."""
    formatted = []
    for name, text in labeled_sources:
        if params.include_source_names:
            formatted.append(params.source_label_format.format(backend_name=name) + text)
        else:
            formatted.append(text)
    intermediate_results = params.intermediate_separator.join(formatted)

    prompt = ""
    if params.include_original_query:
        prompt += params.query_format.format(query=user_query)
    template = params.prompt_template
    for ph in _PLACEHOLDERS:
        if ph in template:
            template = template.replace(ph, intermediate_results)
            break
    else:
        # No placeholder at all: append the sources so they are never dropped.
        template = template + "\n\n" + intermediate_results
    return prompt + template


def clean_aggregator_headers(headers: dict[str, str] | None) -> dict[str, str] | None:
    """Authorization (header case-normalized, env fallback) + Content-Type only.

    Returns None when no credential can be found — the caller must then skip
    the aggregation hop (oai_proxy.py:446-466).
    """
    clean: dict[str, str] = {}
    headers = headers or {}
    auth = headers.get("Authorization") or headers.get("authorization")
    if not auth:
        for k, v in headers.items():
            if k.lower() == "authorization":
                auth = v
                break
    if not auth:
        api_key = os.environ.get("OPENAI_API_KEY", "")
        if api_key:
            auth = f"Bearer {api_key}"
    if not auth:
        return None
    clean["Authorization"] = auth
    clean["Content-Type"] = "application/json"
    return clean


def aggregation_body(prompt: str, aggregator: Backend,
                     params: AggregateParams) -> dict[str, Any]:
    """The aggregation hop's request body. The hop is a first-class engine
    request (docs/quorum.md): ``aggregator_priority`` pins its QoS dispatch
    class on qos=1 engines (the aggregate IS the client's response — it
    defaults to interactive, never queued behind batch prefills) and is
    harmless on HTTP aggregators, which drop unknown knobs upstream."""
    body: dict[str, Any] = {
        "model": aggregator.model or "",
        "messages": [{"role": "user", "content": prompt}],
        "stream": False,
    }
    if params.aggregator_priority:
        body["priority"] = params.aggregator_priority
    return body


def _degrade(reason: str, fallback: str,
             error: str | None = None) -> AggregateOutcome:
    """Count + record the fallback the reference produced silently."""
    AGGREGATE_DEGRADED.inc(reason=reason)
    RECORDER.record("aggregate-degraded", reason=reason,
                    **({"error": error[:200]} if error else {}))
    return AggregateOutcome(fallback, degraded_reason=reason, error=error)


async def aggregate_with_status(
    labeled_sources: Sequence[tuple[str, str]],
    aggregator: Backend | None,
    params: AggregateParams,
    user_query: str,
    headers: dict[str, str] | None,
    timeout: float = 60.0,
) -> AggregateOutcome:
    """Synthesize N source responses via the aggregator backend.

    Any failure (no aggregator, no credentials, HTTP error, exception)
    degrades to ``intermediate_separator.join(raw sources)``
    (oai_proxy.py:479-486) — but VISIBLY: every fallback ticks
    ``quorum_tpu_aggregate_degraded_total{reason=}``, lands a recorder
    event, and carries the first underlying error in the outcome.
    """
    fallback = params.intermediate_separator.join(t for _, t in labeled_sources)
    if aggregator is None:
        aggregation_logger.error("Aggregator backend not configured/found")
        return _degrade("no_aggregator", fallback)

    prompt = build_aggregation_prompt(labeled_sources, params, user_query)
    aggregation_logger.info("Prompt for aggregator: %s", prompt)

    clean_headers = clean_aggregator_headers(headers)
    if clean_headers is None:
        # Local (tpu://) aggregators need no upstream credential; remote ones
        # keep the reference's skip-on-missing-auth behavior.
        if getattr(aggregator, "requires_auth", True):
            aggregation_logger.error("No authorization header or OPENAI_API_KEY found")
            return _degrade("no_credentials", fallback)
        clean_headers = {"Content-Type": "application/json"}

    body = aggregation_body(prompt, aggregator, params)
    try:
        # The synthesis hop is usually the tail-latency dominator of an
        # aggregate-strategy request — span it with the aggregator's name so
        # /debug/traces shows where the time went.
        with trace_span(current_trace(), "aggregator-call",
                        backend=aggregator.name):
            result = await aggregator.complete(body, clean_headers, timeout)
        if result.ok:
            content = result.content
            aggregation_logger.info("Aggregator response: %s", content)
            if not content:
                return _degrade("empty", fallback)
            return AggregateOutcome(content)
        aggregation_logger.error("Aggregator backend failed: %s", result.body)
        err = result.body.get("error") if isinstance(result.body, dict) else None
        msg = (err or {}).get("message") if isinstance(err, dict) else None
        return _degrade("error", fallback,
                        error=str(msg or result.body)[:500])
    except Exception as e:
        aggregation_logger.error("Error calling aggregator backend: %s", e)
        return _degrade("error", fallback, error=str(e)[:500])


async def stream_aggregate_deltas(
    labeled_sources: Sequence[tuple[str, str]],
    aggregator: Backend | None,
    params: AggregateParams,
    user_query: str,
    headers: dict[str, str] | None,
    timeout: float = 60.0,
) -> AsyncIterator[str | AggregateOutcome]:
    """The live aggregation hop (``stream_aggregate: true``, docs/quorum.md):
    yields the aggregator's text deltas AS THEY DECODE, then exactly one
    terminal :class:`AggregateOutcome` whose content is the joined stream.

    Degrade contract: a failure *before* the first delta yields the
    separator-join fallback as one delta (the client still gets content,
    same as the buffered path); a failure *after* deltas already streamed
    cannot be unsent, so the stream just ends and the outcome carries the
    degrade reason — the counter + recorder event fire either way.
    """
    fallback = params.intermediate_separator.join(t for _, t in labeled_sources)
    if aggregator is None:
        aggregation_logger.error("Aggregator backend not configured/found")
        yield fallback
        yield _degrade("no_aggregator", fallback)
        return

    prompt = build_aggregation_prompt(labeled_sources, params, user_query)
    aggregation_logger.info("Prompt for aggregator: %s", prompt)

    clean_headers = clean_aggregator_headers(headers)
    if clean_headers is None:
        if getattr(aggregator, "requires_auth", True):
            aggregation_logger.error("No authorization header or OPENAI_API_KEY found")
            yield fallback
            yield _degrade("no_credentials", fallback)
            return
        clean_headers = {"Content-Type": "application/json"}

    body = aggregation_body(prompt, aggregator, params)
    body["stream"] = True
    sent: list[str] = []
    try:
        with trace_span(current_trace(), "aggregator-call",
                        backend=aggregator.name, streamed=1):
            async for chunk in aggregator.stream(body, clean_headers, timeout):
                text = oai.extract_delta_content(chunk)
                if text:
                    sent.append(text)
                    yield text
    except Exception as e:
        aggregation_logger.error("Error streaming aggregator backend: %s", e)
        if not sent:
            yield fallback
        yield _degrade("error", "".join(sent) or fallback, error=str(e)[:500])
        return
    if not sent:
        yield fallback
        yield _degrade("empty", fallback)
        return
    aggregation_logger.info("Aggregator response: %s", "".join(sent))
    yield AggregateOutcome("".join(sent))


async def aggregate_responses(
    labeled_sources: Sequence[tuple[str, str]],
    aggregator: Backend | None,
    params: AggregateParams,
    user_query: str,
    headers: dict[str, str] | None,
    timeout: float = 60.0,
) -> str:
    """Back-compat text-only wrapper around :func:`aggregate_with_status`."""
    out = await aggregate_with_status(
        labeled_sources, aggregator, params, user_query, headers, timeout)
    return out.content
