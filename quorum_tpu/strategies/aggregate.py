"""The LLM-aggregation second hop.

Parity with ``aggregate_responses`` (/root/reference/src/quorum/oai_proxy.py:374-486):
label + join source responses, build the synthesis prompt, call the aggregator
backend non-streaming with sanitized headers (Authorization + Content-Type
only, with OPENAI_API_KEY env fallback), and degrade to a separator-join of the
raw sources on *any* failure.

Deliberate fixes over the reference:
  - source labels use the real backend names (the reference substituted
    synthetic ``LLM{i+1}`` names, oai_proxy.py:409-411);
  - the prompt template accepts ``{intermediate_results}``,
    ``{{intermediate_results}}``, or the legacy ``{responses}`` placeholder
    (the reference only replaced ``{responses}`` while its shipped config used
    ``{{intermediate_results}}``, so substitution silently never happened —
    oai_proxy.py:424 vs config.yaml:66-73);
  - the aggregator timeout is configurable instead of hardcoded 60 s
    (quirk 12, oai_proxy.py:472).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Sequence

from quorum_tpu.backends.base import Backend
from quorum_tpu.config import AggregateParams
from quorum_tpu.observability import current_trace, trace_span

logger = logging.getLogger(__name__)
aggregation_logger = logging.getLogger("aggregation")

_PLACEHOLDERS = ("{{intermediate_results}}", "{intermediate_results}", "{responses}")


def build_aggregation_prompt(
    labeled_sources: Sequence[tuple[str, str]],
    params: AggregateParams,
    user_query: str,
) -> str:
    """Format the synthesis prompt from (backend_name, response_text) pairs."""
    formatted = []
    for name, text in labeled_sources:
        if params.include_source_names:
            formatted.append(params.source_label_format.format(backend_name=name) + text)
        else:
            formatted.append(text)
    intermediate_results = params.intermediate_separator.join(formatted)

    prompt = ""
    if params.include_original_query:
        prompt += params.query_format.format(query=user_query)
    template = params.prompt_template
    for ph in _PLACEHOLDERS:
        if ph in template:
            template = template.replace(ph, intermediate_results)
            break
    else:
        # No placeholder at all: append the sources so they are never dropped.
        template = template + "\n\n" + intermediate_results
    return prompt + template


def clean_aggregator_headers(headers: dict[str, str] | None) -> dict[str, str] | None:
    """Authorization (header case-normalized, env fallback) + Content-Type only.

    Returns None when no credential can be found — the caller must then skip
    the aggregation hop (oai_proxy.py:446-466).
    """
    clean: dict[str, str] = {}
    headers = headers or {}
    auth = headers.get("Authorization") or headers.get("authorization")
    if not auth:
        for k, v in headers.items():
            if k.lower() == "authorization":
                auth = v
                break
    if not auth:
        api_key = os.environ.get("OPENAI_API_KEY", "")
        if api_key:
            auth = f"Bearer {api_key}"
    if not auth:
        return None
    clean["Authorization"] = auth
    clean["Content-Type"] = "application/json"
    return clean


async def aggregate_responses(
    labeled_sources: Sequence[tuple[str, str]],
    aggregator: Backend | None,
    params: AggregateParams,
    user_query: str,
    headers: dict[str, str] | None,
    timeout: float = 60.0,
) -> str:
    """Synthesize N source responses via the aggregator backend.

    Any failure (no aggregator, no credentials, HTTP error, exception) degrades
    to ``intermediate_separator.join(raw sources)`` (oai_proxy.py:479-486).
    """
    fallback = params.intermediate_separator.join(t for _, t in labeled_sources)
    if aggregator is None:
        aggregation_logger.error("Aggregator backend not configured/found")
        return fallback

    prompt = build_aggregation_prompt(labeled_sources, params, user_query)
    aggregation_logger.info("Prompt for aggregator: %s", prompt)

    clean_headers = clean_aggregator_headers(headers)
    if clean_headers is None:
        # Local (tpu://) aggregators need no upstream credential; remote ones
        # keep the reference's skip-on-missing-auth behavior.
        if getattr(aggregator, "requires_auth", True):
            aggregation_logger.error("No authorization header or OPENAI_API_KEY found")
            return fallback
        clean_headers = {"Content-Type": "application/json"}

    body: dict[str, Any] = {
        "model": aggregator.model or "",
        "messages": [{"role": "user", "content": prompt}],
        "stream": False,
    }
    try:
        # The synthesis hop is usually the tail-latency dominator of an
        # aggregate-strategy request — span it with the aggregator's name so
        # /debug/traces shows where the time went.
        with trace_span(current_trace(), "aggregator-call",
                        backend=aggregator.name):
            result = await aggregator.complete(body, clean_headers, timeout)
        if result.ok:
            content = result.content
            aggregation_logger.info("Aggregator response: %s", content)
            return content
        aggregation_logger.error("Aggregator backend failed: %s", result.body)
        return fallback
    except Exception as e:
        aggregation_logger.error("Error calling aggregator backend: %s", e)
        return fallback
