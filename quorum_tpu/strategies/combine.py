"""Non-streaming parallel combination.

Parity with the reference's inline combine block
(/root/reference/src/quorum/oai_proxy.py:1164-1355): strip thinking per
``hide_final_think``, aggregate or separator-join, sum usage across backends,
and rebuild one ``chat.completion`` object reusing the first successful
response's id/created/model (oai_proxy.py:1315-1335).

Same deliberate fixes as the streaming path (strategy cross-talk, honored
``source_backends``, configurable aggregator timeout) — see
:mod:`quorum_tpu.strategies.streaming`.
"""

from __future__ import annotations

import logging
from typing import Any

from quorum_tpu import oai
from quorum_tpu.backends.registry import BackendRegistry
from quorum_tpu.config import Config
from quorum_tpu.filtering import strip_thinking_tags
from quorum_tpu.strategies.aggregate import AggregateOutcome, aggregate_with_status
from quorum_tpu.strategies.fanout import BackendOutcome

logger = logging.getLogger(__name__)
aggregation_logger = logging.getLogger("aggregation")


def degraded_headers(outcome: AggregateOutcome | None) -> dict[str, str]:
    """Response headers marking a degraded combine (docs/quorum.md): the
    reason plus the first underlying error, so a client can tell the
    separator-join fallback from a real aggregate without diffing text.
    Header values must be latin-1-encodable single lines (h11 enforces
    both); error text is sanitized, not trusted."""
    if outcome is None or not outcome.degraded:
        return {}
    out = {"X-Quorum-Aggregate-Degraded": outcome.degraded_reason or "error"}
    if outcome.error:
        clean = " ".join(str(outcome.error).split())
        out["X-Quorum-Aggregate-Error"] = clean.encode(
            "latin-1", "replace").decode("latin-1")[:200]
    return out


async def combine_outcomes(
    cfg: Config,
    registry: BackendRegistry,
    outcomes: list[BackendOutcome],
    body: dict[str, Any],
    headers: dict[str, str],
    aggregator_timeout: float,
) -> tuple[dict[str, Any], AggregateOutcome | None]:
    """Combine successful outcomes into one chat.completion dict.

    Returns ``(completion, aggregate_outcome)`` — the outcome is None for
    the concatenate strategy and carries the degrade reason/error for the
    aggregate strategy (the server surfaces it as response headers)."""
    successes = [o for o in outcomes if o.ok]
    strategy = cfg.strategy_name
    agg_outcome: AggregateOutcome | None = None

    if strategy == "aggregate":
        p = cfg.aggregate
        thinking_tags = p.thinking_tags
        hide_sources = p.strip_intermediate_thinking
        labeled = [
            (o.backend.name, strip_thinking_tags(o.content, thinking_tags, hide=hide_sources))
            for o in successes
        ]
        aggregation_logger.info("Individual LLM responses for aggregation:")
        for name, text in labeled:
            aggregation_logger.info("%s response: %s", name, text)
        aggregator = registry.get(p.aggregator_backend) if p.aggregator_backend else None
        agg_outcome = await aggregate_with_status(
            labeled,
            aggregator,
            p,
            oai.first_user_message(body),
            headers,
            aggregator_timeout,
        )
        combined = agg_outcome.content
        if p.hide_aggregator_thinking:
            combined = strip_thinking_tags(combined, thinking_tags, hide=True)
    else:
        p = cfg.concatenate
        processed = [
            strip_thinking_tags(o.content, p.thinking_tags, hide=p.hide_final_think)
            for o in successes
        ]
        combined = p.separator.join(processed)

    aggregation_logger.info("Final aggregated content: %s", combined)

    usage = oai.sum_usage([o.usage for o in successes])
    first = successes[0].result.body
    return {
        "id": first.get("id", oai.new_request_id()),
        "object": "chat.completion",
        "created": first.get("created", oai.now()),
        "model": first.get("model", "parallel-proxy"),
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": combined},
                "finish_reason": "stop",
            }
        ],
        "usage": usage,
    }, agg_outcome
