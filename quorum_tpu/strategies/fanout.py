"""Parallel fan-out of one request to N backends (non-streaming).

Parity with the reference's ``asyncio.gather`` dispatch
(/root/reference/src/quorum/oai_proxy.py:1132-1137) and its failure
normalization: a failed backend yields an error outcome, never an exception
(partial failure degrades to serving the survivors, oai_proxy.py:1138-1162).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any

from quorum_tpu.backends.base import Backend, BackendError, CompletionResult
from quorum_tpu.observability import current_trace, trace_span, use_trace


@dataclass
class BackendOutcome:
    backend: Backend
    result: CompletionResult | None = None
    error: BackendError | None = None

    @property
    def ok(self) -> bool:
        return self.result is not None and self.result.ok

    @property
    def content(self) -> str:
        return self.result.content if self.result else ""

    @property
    def usage(self) -> dict[str, Any] | None:
        return self.result.usage if self.result else None

    @property
    def error_message(self) -> str:
        """First-error extraction parity (oai_proxy.py:1141-1150)."""
        if self.error is not None:
            err = self.error.body.get("error")
            if isinstance(err, dict):
                return err.get("message", "Unknown error")
            return str(self.error.body)
        if self.result is not None:
            err = self.result.body.get("error")
            if isinstance(err, dict):
                return err.get("message", "Unknown error")
            return str(self.result.body)
        return "Unknown error"


async def _call_one(
    backend: Backend, body: dict[str, Any], headers: dict[str, str],
    timeout: float, trace=None,
) -> BackendOutcome:
    try:
        # The per-backend hop span (tagged with the backend name) plus the
        # trace re-bind: gather() runs each call as its own task, so the
        # request context must travel explicitly for a tpu:// backend's
        # engine submission to attach its scheduler spans.
        with use_trace(trace), trace_span(trace, "fanout-call",
                                          backend=backend.name) as span:
            result = await backend.complete(body, headers, timeout)
            if span is not None:
                span.meta["status"] = result.status_code
        return BackendOutcome(backend=backend, result=result)
    except BackendError as e:
        return BackendOutcome(backend=backend, error=e)
    except Exception as e:  # normalize anything else (oai_proxy.py:252-259)
        return BackendOutcome(backend=backend, error=BackendError(str(e)))


async def fanout_complete(
    backends: list[Backend],
    body: dict[str, Any],
    headers: dict[str, str],
    timeout: float,
) -> list[BackendOutcome]:
    """Call every backend concurrently; outcomes in backend order."""
    trace = current_trace()
    return list(
        await asyncio.gather(
            *[_call_one(b, body, headers, timeout, trace) for b in backends]
        )
    )
