"""Parallel streaming aggregator: N backend streams → one SSE stream.

Re-design of the reference's ``progress_streaming_aggregator``
(/root/reference/src/quorum/oai_proxy.py:489-885) around a merge queue: each
backend stream runs as its own task pushing deltas into one queue, so chunks
from different backends interleave **live**. The reference instead polled task
completion every 0.1 s and replayed fully-buffered responses one backend at a
time (quirks 1+3, oai_proxy.py:554, 747).

SSE contract preserved (asserted by the reference test suite and ours):
  - initial role chunk   id "chatcmpl-parallel",  model "parallel-proxy";
  - per-backend deltas   id "chatcmpl-parallel-{i}" (i = backend index);
  - final combined chunk id "chatcmpl-parallel-final", finish_reason "stop";
  - all-failed error chunk id "error", content
    "Error: All backends failed to provide content", finish_reason "error";
  - terminating "data: [DONE]".

Deliberate fixes over the reference (SURVEY.md §2 quirk list):
  - quirk 4: ``source_backends`` is honored — in aggregate strategy only the
    configured sources are fanned out to;
  - quirk 5: ``suppress_individual_responses`` suppresses per-backend deltas;
  - quirk 7: final fallback join uses ``separator.join`` (the reference used
    ``f"\\n{separator}".join`` in streaming but ``separator.join`` elsewhere);
  - quirk 8: ``created`` is epoch time, not the event-loop clock;
  - quirk 9: the aggregation hop runs only when the *selected* strategy is
    ``aggregate`` (the reference triggered it whenever an aggregator was
    configured, regardless of strategy);
  - ``strip_intermediate_thinking`` / ``hide_aggregator_thinking`` are honored
    in aggregate strategy (documented in docs/aggregate_behaviour.md:113-151
    but never read by the reference).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator

from quorum_tpu import oai, sse
from quorum_tpu.backends.base import Backend
from quorum_tpu.backends.registry import BackendRegistry
from quorum_tpu.config import AggregateParams, Config
from quorum_tpu.filtering import strip_thinking_tags
from quorum_tpu.native import make_thinking_filter
from quorum_tpu.observability import trace_span, use_trace
from quorum_tpu.strategies.aggregate import (
    AggregateOutcome,
    aggregate_with_status,
    stream_aggregate_deltas,
)

logger = logging.getLogger(__name__)
aggregation_logger = logging.getLogger("aggregation")

PROXY_MODEL_NAME = "parallel-proxy"

_DONE = object()


@dataclass
class StreamPlan:
    """Fan-out parameters resolved from config + request body."""

    backends: list[Backend]
    strategy_name: str
    separator: str
    hide_intermediate: bool
    hide_final: bool
    thinking_tags: list[str]
    skip_final: bool
    suppress_individual: bool
    aggregator: Backend | None
    aggregate_params: AggregateParams | None
    user_query: str

    @classmethod
    def from_config(
        cls,
        cfg: Config,
        registry: BackendRegistry,
        body: dict[str, Any],
    ) -> "StreamPlan":
        strategy = cfg.strategy_name
        user_query = oai.first_user_message(body)
        if strategy == "aggregate":
            p = cfg.aggregate
            suppress = p.suppress_individual_responses
            if "suppress_individual_responses" in body:  # per-request override
                suppress = bool(body["suppress_individual_responses"])
            return cls(
                backends=registry.select(p.source_backends),
                strategy_name=strategy,
                separator=p.intermediate_separator,
                hide_intermediate=p.strip_intermediate_thinking,
                hide_final=p.hide_aggregator_thinking,
                thinking_tags=p.thinking_tags,
                skip_final=False,
                suppress_individual=suppress,
                aggregator=registry.get(p.aggregator_backend) if p.aggregator_backend else None,
                aggregate_params=p,
                user_query=user_query,
            )
        p = cfg.concatenate
        return cls(
            backends=registry.backends,
            strategy_name=strategy,
            separator=p.separator,
            hide_intermediate=p.hide_intermediate_think,
            hide_final=p.hide_final_think,
            thinking_tags=p.thinking_tags,
            skip_final=p.skip_final_aggregation,
            suppress_individual=bool(body.get("suppress_individual_responses", False)),
            aggregator=None,
            aggregate_params=None,
            user_query=user_query,
        )


async def _pump(
    index: int,
    backend: Backend,
    body: dict[str, Any],
    headers: dict[str, str],
    timeout: float,
    queue: asyncio.Queue,
    trace=None,
) -> None:
    """Drive one backend stream, pushing (index, text | _DONE) into the queue.

    The request trace is re-bound inside this task (``use_trace``) so a
    ``tpu://`` backend's engine submission — which happens at the stream's
    first ``__anext__``, on THIS task, after the server handler already
    returned — still attaches its queue-wait/prefill/decode spans; the
    fan-out hop itself is recorded as a backend-tagged span."""
    try:
        with use_trace(trace), trace_span(trace, "fanout-stream",
                                          backend=backend.name, index=index):
            async for chunk in backend.stream(body, headers, timeout):
                text = oai.extract_delta_content(chunk)
                if text:
                    await queue.put((index, text))
    except Exception as e:
        logger.warning("Backend %s (%d) stream failed: %s", backend.name, index, e)
        aggregation_logger.error("Error processing backend %d: %s", index, e)
    finally:
        await queue.put((index, _DONE))


async def parallel_stream(
    plan: StreamPlan,
    body: dict[str, Any],
    headers: dict[str, str],
    timeout: float,
    aggregator_timeout: float | None = None,
    trace=None,
) -> AsyncIterator[bytes]:
    """Merge N backend streams into one OpenAI-compatible SSE byte stream."""
    aggregation_logger.info("Starting streaming aggregation process")
    yield sse.encode_event(oai.role_chunk(PROXY_MODEL_NAME))

    n = len(plan.backends)
    # Python filter by default; the native C++ twin is opt-in via
    # QUORUM_TPU_NATIVE=1 (measured slower for typical delta sizes — see
    # quorum_tpu/native/__init__.py).
    filters = {i: make_thinking_filter(plan.thinking_tags) for i in range(n)}
    collected = ["" for _ in range(n)]
    queue: asyncio.Queue = asyncio.Queue()
    tasks = [
        asyncio.create_task(_pump(i, b, body, headers, timeout, queue, trace))
        for i, b in enumerate(plan.backends)
    ]

    try:
        finished = 0
        while finished < n:
            index, item = await queue.get()
            if item is _DONE:
                finished += 1
                text = filters[index].flush() if plan.hide_intermediate else ""
            else:
                text = filters[index].feed(item) if plan.hide_intermediate else item
            if not text:
                continue
            collected[index] += text
            if not plan.suppress_individual:
                yield sse.encode_event(
                    oai.content_chunk(text, model=PROXY_MODEL_NAME, backend_index=index)
                )
    finally:
        for t in tasks:
            t.cancel()

    for i, content in enumerate(collected):
        aggregation_logger.info(
            "Backend %d content: %s", i, content or "No content received"
        )

    if not plan.skip_final:
        # Aggregate strategy: sources were already live-filtered per
        # strip_intermediate_thinking; hide_aggregator_thinking applies only to
        # the aggregator's own output below (matches combine.py's split).
        # Concatenate strategy: final join is stripped per hide_final_think
        # (reference quirk 6 semantics).
        if plan.strategy_name == "aggregate":
            labeled = [
                (plan.backends[i].name, text)
                for i, text in enumerate(collected)
                if text
            ]
        else:
            labeled = [
                (plan.backends[i].name, strip_thinking_tags(text, plan.thinking_tags, hide=plan.hide_final))
                for i, text in enumerate(collected)
                if text
            ]
        if labeled:
            if plan.strategy_name == "aggregate" and plan.aggregator is not None and plan.aggregate_params:
                if plan.aggregate_params.stream_aggregate:
                    # In-engine aggregation hop, live (docs/quorum.md): the
                    # aggregator's tokens ARE the client response — each
                    # delta rides out under the final-chunk id as it
                    # decodes, so aggregate TTFT is the aggregator's real
                    # TTFT instead of its full generation time. A closing
                    # zero-delta chunk carries finish_reason "stop" (the
                    # buffered path folds both into one chunk).
                    final_filter = make_thinking_filter(plan.thinking_tags)
                    with use_trace(trace), trace_span(
                            trace, "aggregate", strategy=plan.strategy_name,
                            aggregator=plan.aggregator.name, streamed=1):
                        agen = stream_aggregate_deltas(
                            labeled, plan.aggregator, plan.aggregate_params,
                            plan.user_query, headers,
                            aggregator_timeout or timeout)
                        async for item in agen:
                            if isinstance(item, AggregateOutcome):
                                break
                            text = (final_filter.feed(item)
                                    if plan.hide_final else item)
                            if text:
                                yield sse.encode_event(oai.content_chunk(
                                    text, model=PROXY_MODEL_NAME,
                                    id=oai.PARALLEL_FINAL_ID))
                        tail = final_filter.flush() if plan.hide_final else ""
                    if tail:
                        yield sse.encode_event(oai.content_chunk(
                            tail, model=PROXY_MODEL_NAME,
                            id=oai.PARALLEL_FINAL_ID))
                    yield sse.encode_event(oai.chunk(
                        id=oai.PARALLEL_FINAL_ID, model=PROXY_MODEL_NAME,
                        delta={}, finish_reason="stop"))
                    yield sse.encode_done()
                    return
                # use_trace: this generator body runs under the ASGI server
                # (the handler's context binding is gone), so the trace must
                # be re-bound for the aggregator hop's nested spans
                # (aggregator-call, a tpu:// aggregator's engine spans) to
                # attach — the same reason _pump re-binds.
                with use_trace(trace), trace_span(
                        trace, "aggregate", strategy=plan.strategy_name,
                        aggregator=plan.aggregator.name):
                    outcome = await aggregate_with_status(
                        labeled,
                        plan.aggregator,
                        plan.aggregate_params,
                        plan.user_query,
                        headers,
                        aggregator_timeout or timeout,
                    )
                combined = outcome.content
                if plan.hide_final:
                    combined = strip_thinking_tags(combined, plan.thinking_tags, hide=True)
            else:
                with trace_span(trace, "aggregate",
                                strategy=plan.strategy_name):
                    combined = plan.separator.join(text for _, text in labeled)
            aggregation_logger.info("Final aggregated streaming content: %s", combined)
            yield sse.encode_event(oai.final_chunk(combined, model=PROXY_MODEL_NAME))
        else:
            yield sse.encode_event(
                oai.error_chunk(
                    "Error: All backends failed to provide content",
                    model=PROXY_MODEL_NAME,
                )
            )

    yield sse.encode_done()
