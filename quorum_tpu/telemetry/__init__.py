"""quorum_tpu.telemetry — the engine flight-recorder subsystem (ISSUE 12).

Three load-bearing pieces plus the shared metrics plumbing:

  - :mod:`~quorum_tpu.telemetry.recorder` — the always-on bounded ring of
    structured engine events (dispatch/reap per program family, admission/
    injection/handoff/register, clamp transitions, deadline expiries,
    breaker/containment), exported as JSON and Chrome/Perfetto trace-event
    format from ``GET /debug/engine/timeline`` and auto-dumped to ``logs/``
    on failure containment.
  - :mod:`~quorum_tpu.telemetry.latency` — per-program-family device-time
    EWMAs/percentiles (the generalization of the PR 6 clamp EWMA) feeding
    ``quorum_tpu_dispatch_device_seconds{family=...}``.
  - :mod:`~quorum_tpu.telemetry.slo` — deadline-headroom SLO classes,
    per-class/stage good-vs-breached counters, and the sliding-window burn
    rate behind the ``/health`` → ``/ready`` degradation story.
  - :mod:`~quorum_tpu.telemetry.metrics` — the Prometheus primitive types
    and exposition validator (moved out of ``observability.py``, which
    keeps the registered families and re-exports these for back-compat).

See docs/observability.md.
"""

from quorum_tpu.telemetry.latency import LatencyModel
from quorum_tpu.telemetry.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    validate_exposition,
)
from quorum_tpu.telemetry.recorder import RECORDER, FlightRecorder
from quorum_tpu.telemetry.slo import SLO, SloTracker, classify

__all__ = [
    "DEFAULT_BUCKETS",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LatencyModel",
    "MetricsRegistry",
    "RECORDER",
    "SLO",
    "SloTracker",
    "classify",
    "validate_exposition",
]
