"""Per-family device-time latency model.

Generalizes the PR 6 effective-C clamp EWMA (one scalar per engine — the
per-chunk dispatch-to-reap estimate) into per-program-family statistics:
every reaped dispatch's dispatch→ready time is attributed to its
``compile_budget.json`` family ("plain", "loop", "verify", "dfa", ...;
admission-path programs attribute their dispatch wall time under their
admit-cache family names), and each family keeps an EWMA, running totals,
and a bounded sample reservoir for exact p50/p99.

This is the latency substrate ROADMAP open item 1's preemption cost model
reads from: "how long does one more megachunk dispatch cost?" and "how long
until a preempted row's register program lands?" are per-family questions a
single blended EWMA cannot answer. The process-global exposition rides
``quorum_tpu_dispatch_device_seconds{family=...}`` (observability.py); this
object is the per-engine view, exported on ``GET /debug/engine/timeline``
and printed per leg by ``scripts/hostpath_bench.py``.
"""

from __future__ import annotations

import math
import threading
from collections import deque

# EWMA weight — matches the engine's CHUNK_EWMA_ALPHA so the per-family
# estimate for the decode family tracks the clamp's scalar.
EWMA_ALPHA = 0.3
# Bounded per-family reservoir for exact percentiles: big enough for a
# bench leg's full dispatch count, small enough to never matter.
MAX_SAMPLES = 512


class _Family:
    __slots__ = ("count", "total_s", "ewma_s", "samples")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.ewma_s = 0.0
        self.samples: deque = deque(maxlen=MAX_SAMPLES)


class LatencyModel:
    """Thread-safe per-family dispatch-latency statistics (one per engine;
    observed from the decode loop's reap and the admission paths — under
    disagg those are two different threads)."""

    def __init__(self, alpha: float = EWMA_ALPHA):
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def observe(self, family: str, seconds: float) -> None:
        s = max(0.0, float(seconds))
        with self._lock:
            f = self._families.get(family)
            if f is None:
                f = _Family()
                self._families[family] = f
            f.count += 1
            f.total_s += s
            f.ewma_s = (s if f.count == 1
                        else (1 - self.alpha) * f.ewma_s + self.alpha * s)
            f.samples.append(s)

    def ewma(self, family: str) -> float:
        """The family's EWMA estimate in seconds (0.0 before any sample)."""
        with self._lock:
            f = self._families.get(family)
            return f.ewma_s if f is not None else 0.0

    def families(self) -> list[str]:
        with self._lock:
            return sorted(self._families)

    @staticmethod
    def _pct(samples: list[float], p: float) -> float:
        """Nearest-rank percentile over sorted ``samples`` (ceil(p% · n)'th
        value, 1-indexed) — int(p/100·n) would overshoot by one rank
        whenever p% · n lands on an integer."""
        if not samples:
            return 0.0
        idx = max(0, math.ceil(p / 100 * len(samples)) - 1)
        return samples[min(len(samples) - 1, idx)]

    def snapshot(self) -> dict[str, dict]:
        """{family: {count, total_s, ewma_ms, p50_ms, p99_ms}} — the
        JSON-able per-engine view (timeline endpoint, bench legs)."""
        with self._lock:
            items = [(name, f.count, f.total_s, f.ewma_s, sorted(f.samples))
                     for name, f in self._families.items()]
        out = {}
        for name, count, total_s, ewma_s, samples in items:
            out[name] = {
                "count": count,
                "total_s": round(total_s, 6),
                "ewma_ms": round(ewma_s * 1e3, 3),
                "p50_ms": round(self._pct(samples, 50) * 1e3, 3),
                "p99_ms": round(self._pct(samples, 99) * 1e3, 3),
            }
        return out
