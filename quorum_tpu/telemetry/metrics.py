"""Prometheus primitives + exposition validation (the metrics plumbing).

Moved here from ``observability.py`` when the telemetry package grew the
flight recorder / latency-model / SLO subsystems (ISSUE 12): the primitive
types are shared plumbing every telemetry piece builds on, while
``observability.py`` keeps the REGISTERED FAMILIES (the serving-latency
histograms, counters, gauges) and the request-tracing spine. Import either
module — ``observability`` re-exports everything here for back-compat.

  - :class:`Histogram` / :class:`Counter` / :class:`Gauge` — one Prometheus
    family each: thread-safe recording plus text exposition. Pure stdlib,
    O(buckets)/O(series) memory.
  - :class:`MetricsRegistry` — ordered collection of families, one-call
    exposition (the ``/metrics`` body).
  - :func:`validate_exposition` — a promtool-style pure-Python checker for
    a full Prometheus text exposition (``make metrics-check``).
"""

from __future__ import annotations

import bisect
import threading

# Serving-latency bucket ladder: sub-millisecond (intra-chunk host work)
# through minutes (a long generation behind a queue). Upper bounds in
# seconds, strictly increasing; +Inf is implicit.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _fmt_float(v: float) -> str:
    """Prometheus sample value: shortest exact-enough decimal repr."""
    out = repr(float(v))
    return out


def _esc_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: tuple[tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_esc_label(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Histogram:
    """One Prometheus histogram family: thread-safe ``observe`` plus text
    exposition with cumulative ``_bucket`` samples, ``_sum`` and ``_count``.

    Per-bucket counts are stored non-cumulative and summed at expose time, so
    ``observe`` is O(log buckets) (bisect) under a short lock. Labeled
    children share the family (one ``# TYPE`` line, samples grouped)."""

    def __init__(self, name: str, help_text: str,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(f"histogram buckets must strictly increase: {buckets}")
        self.name = name
        self.help = help_text
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        # label-tuple -> [per-bucket counts..., +Inf count, sum, count]
        self._series: dict[tuple[tuple[str, str], ...], list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        idx = bisect.bisect_left(self.buckets, float(value))
        with self._lock:
            row = self._series.get(key)
            if row is None:
                row = [0] * (len(self.buckets) + 1) + [0.0, 0]
                self._series[key] = row
            row[idx] += 1
            row[-2] += float(value)
            row[-1] += 1

    def snapshot(self) -> dict:
        """{labels: {"buckets": cumulative counts, "sum": s, "count": n}}."""
        with self._lock:
            series = {k: list(v) for k, v in self._series.items()}
        out = {}
        for key, row in series.items():
            cum, total = [], 0
            for c in row[: len(self.buckets) + 1]:
                total += c
                cum.append(total)
            out[key] = {"buckets": cum, "sum": row[-2], "count": row[-1]}
        return out

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        snap = self.snapshot() or {(): {"buckets": [0] * (len(self.buckets) + 1),
                                        "sum": 0.0, "count": 0}}
        for key in sorted(snap):
            s = snap[key]
            bounds = [_fmt_float(b) for b in self.buckets] + ["+Inf"]
            for ub, c in zip(bounds, s["buckets"]):
                le = 'le="%s"' % ub
                lines.append(f"{self.name}_bucket{_fmt_labels(key, le)} {c}")
            lines.append(f"{self.name}_sum{_fmt_labels(key)} {_fmt_float(s['sum'])}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {s['count']}")
        return lines


class Counter:
    """One Prometheus counter family: thread-safe monotonic ``inc`` plus
    exposition. ``inc`` accepts labels (``inc(stage="queue")``) — each
    distinct label set is its own series under the family's one ``# TYPE``
    line; label-less families expose a single bare sample.

    Process-wide like the registry's other families — engines sharing the
    process accumulate into one series (the per-engine breakdown lives in
    the ``quorum_tpu_engine_*`` block each engine's ``metrics()`` feeds)."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()
        self._series: dict[tuple[tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    @property
    def value(self) -> float:
        """Total across every labeled series (the label-less reading)."""
        with self._lock:
            return sum(self._series.values())

    def value_of(self, **labels: str) -> float:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            return self._series.get(key, 0.0)

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        with self._lock:
            snap = dict(self._series) or {(): 0.0}
        for key in sorted(snap):
            lines.append(f"{self.name}{_fmt_labels(key)} "
                         f"{_fmt_float(snap[key])}")
        return lines


class Gauge:
    """One Prometheus gauge: thread-safe ``set`` plus exposition.

    Process-wide last-writer-wins semantics (the scheduler threads of
    several engines share one family); fine for the depth-style gauges this
    registry carries — they describe "now", not an accumulation.

    ``set`` accepts labels (``set(3, stage="1")``) like :class:`Counter`'s
    ``inc`` — each distinct label set is its own last-writer-wins series
    under the family's one ``# TYPE`` line; label-less families keep their
    single bare sample."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()
        self._series: dict[tuple[tuple[str, str], ...], float] = {(): 0.0}

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            self._series[key] = float(value)

    @property
    def value(self) -> float:
        """The label-less series (the pre-label reading); labeled series
        are read via :meth:`value_of`."""
        with self._lock:
            return self._series.get((), 0.0)

    def value_of(self, **labels: str) -> float:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            return self._series.get(key, 0.0)

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            snap = dict(self._series)
        for key in sorted(snap):
            lines.append(f"{self.name}{_fmt_labels(key)} "
                         f"{_fmt_float(snap[key])}")
        return lines


class MetricsRegistry:
    """Ordered collection of histogram/gauge families, one-call exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: dict[str, Histogram] = {}
        self._gauges: dict[str, Gauge] = {}
        self._counters: dict[str, Counter] = {}

    def histogram(self, name: str, help_text: str,
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = Histogram(name, help_text, buckets)
                self._hists[name] = h
            return h

    def gauge(self, name: str, help_text: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = Gauge(name, help_text)
                self._gauges[name] = g
            return g

    def counter(self, name: str, help_text: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = Counter(name, help_text)
                self._counters[name] = c
            return c

    def expose(self) -> list[str]:
        with self._lock:
            families = (list(self._hists.values())
                        + list(self._counters.values())
                        + list(self._gauges.values()))
        lines: list[str] = []
        for fam in families:
            lines.extend(fam.expose())
        return lines

    def reset(self) -> None:
        """Drop all recorded samples (tests)."""
        with self._lock:
            for h in self._hists.values():
                with h._lock:
                    h._series.clear()
            for g in self._gauges.values():
                g.set(0.0)
            for c in self._counters.values():
                with c._lock:
                    c._series.clear()


# ---- exposition validation -------------------------------------------------

def validate_exposition(text: str) -> list[str]:
    """Promtool-style pure-Python check of a Prometheus text exposition.

    Returns a list of human-readable problems (empty = valid). Checks line
    grammar, one ``# TYPE`` line per family (samples grouped after it),
    numeric sample values, histogram bucket monotonicity, a ``+Inf`` bucket,
    and ``_count`` == the ``+Inf`` bucket per labeled series."""
    import re

    errors: list[str] = []
    typed: dict[str, str] = {}
    seen_sample_families: set[str] = set()
    # family -> labelkey -> {"buckets": [(le, v)...], "count": v, "sum": v}
    hist: dict[str, dict[str, dict]] = {}
    name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+(\S+)(\s+\S+)?$")
    label_re = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"$')

    def family_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed \
                    and typed[name[: -len(suffix)]] == "histogram":
                return name[: -len(suffix)]
        return name

    for n, raw in enumerate(text.splitlines(), 1):
        line = raw
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or not name_re.fullmatch(parts[2]) or \
                    parts[3] not in ("counter", "gauge", "histogram",
                                     "summary", "untyped"):
                errors.append(f"line {n}: malformed TYPE line: {raw!r}")
                continue
            fam = parts[2]
            if fam in typed:
                errors.append(f"line {n}: duplicate TYPE line for {fam}")
            if fam in seen_sample_families:
                errors.append(
                    f"line {n}: TYPE for {fam} appears after its samples")
            typed[fam] = parts[3]
            continue
        if line.startswith("#"):
            continue  # HELP / comments
        m = sample_re.match(line)
        if m is None:
            errors.append(f"line {n}: malformed sample line: {raw!r}")
            continue
        name, _, labelstr, value, _ = m.groups()
        labels: dict[str, str] = {}
        if labelstr:
            for part in _split_labels(labelstr):
                lm = label_re.match(part.strip())
                if lm is None:
                    errors.append(f"line {n}: malformed label {part!r}")
                    continue
                labels[lm.group(1)] = lm.group(2)
        try:
            val = float(value)
        except ValueError:
            errors.append(f"line {n}: non-numeric value {value!r}")
            continue
        fam = family_of(name)
        seen_sample_families.add(fam)
        if typed.get(fam) == "histogram":
            series = hist.setdefault(fam, {})
            key = ",".join(f"{k}={v}" for k, v in sorted(labels.items())
                           if k != "le")
            entry = series.setdefault(key, {"buckets": [], "count": None,
                                            "sum": None})
            if name.endswith("_bucket"):
                if "le" not in labels:
                    errors.append(f"line {n}: _bucket sample without le label")
                else:
                    le = (float("inf") if labels["le"] == "+Inf"
                          else float(labels["le"]))
                    entry["buckets"].append((le, val))
            elif name.endswith("_count"):
                entry["count"] = val
            elif name.endswith("_sum"):
                entry["sum"] = val
    for fam, series in hist.items():
        for key, entry in series.items():
            buckets = entry["buckets"]
            if not buckets:
                errors.append(f"{fam}{{{key}}}: histogram with no buckets")
                continue
            if buckets[-1][0] != float("inf"):
                errors.append(f"{fam}{{{key}}}: missing +Inf bucket")
            for (le1, v1), (le2, v2) in zip(buckets, buckets[1:]):
                if le2 <= le1:
                    errors.append(
                        f"{fam}{{{key}}}: bucket bounds not increasing "
                        f"({le1} -> {le2})")
                if v2 < v1:
                    errors.append(
                        f"{fam}{{{key}}}: bucket counts not monotonic "
                        f"(le={le1}:{v1} > le={le2}:{v2})")
            if entry["count"] is None:
                errors.append(f"{fam}{{{key}}}: missing _count sample")
            elif buckets and buckets[-1][0] == float("inf") \
                    and entry["count"] != buckets[-1][1]:
                errors.append(
                    f"{fam}{{{key}}}: _count {entry['count']} != +Inf "
                    f"bucket {buckets[-1][1]}")
            if entry["sum"] is None:
                errors.append(f"{fam}{{{key}}}: missing _sum sample")
    return errors


def _split_labels(labelstr: str) -> list[str]:
    """Split ``a="x",b="y,z"`` on commas outside quoted values."""
    parts, buf, in_q, esc = [], [], False, False
    for ch in labelstr:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            buf.append(ch)
            continue
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts
