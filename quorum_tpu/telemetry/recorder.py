"""Engine flight recorder: an always-on, bounded ring of structured events.

PRs 6-10 deliberately erased the host-visible execution boundaries (megachunk
scans, fused draft→verify turns, dual disagg loops, zero-drain injection) —
one opaque "decode" blob per dispatch is all a request trace sees. This ring
is the post-hoc answer: every engine records its scheduling decisions here as
small structured events — dispatch issued/reaped per ring entry (tagged with
its ``compile_budget.json`` program family), admission/injection/handoff/
register, effective-C clamp transitions, deadline expiries, breaker and
containment events — stamped with one monotonic clock (``time.perf_counter``)
and the request id, so events from the prefill and decode loops of a disagg
engine (or the staged injection path of a zero-drain one) correlate across
threads.

Design constraints, in order:

  - **bounded**: a ``deque(maxlen=capacity)`` (default 4096 events,
    ``QUORUM_TPU_FLIGHT_EVENTS``); past the cap the oldest event is
    overwritten and an ``on_drop`` hook ticks (wired to
    ``quorum_tpu_flight_recorder_dropped_total`` by ``observability``).
  - **lock-cheap**: ``record`` takes one short lock, builds one small tuple,
    appends. No I/O, no jax, no stringification beyond what the caller
    already made. The token-for-token pin and the bounded-overhead test in
    ``tests/test_telemetry.py`` keep this honest; ``QUORUM_TPU_FLIGHT_RECORDER=0``
    turns the whole thing off (record becomes two attribute reads).
  - **exportable**: JSON (``snapshot``) and Chrome/Perfetto trace-event
    format (``to_trace_events`` — open the downloaded file in
    ui.perfetto.dev), both served from ``GET /debug/engine/timeline``.
  - **post-mortem**: ``dump(reason)`` writes the ring to
    ``logs/flightrec-<reason>-<stamp>.json`` (``QUORUM_TPU_FLIGHT_DIR``),
    rate-limited per reason; the engine auto-dumps on ``_fail_all``,
    containment, breaker-open, and the DEADLINE_SLACK_S backstop so every
    chaos-harness containment leaves an artifact (``scripts/chaos_check.py``
    asserts it).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

# Dispatch/reap pairs become Perfetto "X" (complete) slices; everything else
# is an instant event on its loop's track.
_SPAN_KINDS = frozenset({"reap"})


class FlightRecorder:
    """Process-wide bounded ring of engine events (see module docstring)."""

    def __init__(self, capacity: int | None = None,
                 enabled: bool | None = None):
        if capacity is None:
            capacity = int(os.environ.get("QUORUM_TPU_FLIGHT_EVENTS", "4096"))
        self.capacity = max(16, int(capacity))
        if enabled is None:
            enabled = os.environ.get("QUORUM_TPU_FLIGHT_RECORDER", "1") != "0"
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # (t, kind, rid, engine, loop, data-dict-or-None)
        self._ring: deque = deque(maxlen=self.capacity)
        self._n_recorded = 0
        # Hook ticked when a full ring overwrites its oldest event —
        # observability wires the dropped-events counter through it (the
        # recorder itself imports nothing from observability: no cycle).
        self.on_drop = None
        # reason -> last dump stamp (rate limit, see dump()).
        self._last_dump: dict[str, float] = {}
        self._dump_seq = 0

    # -- recording -----------------------------------------------------------

    def record(self, kind: str, rid: str = "", engine: str = "",
               loop: str = "", t: float | None = None, **data) -> None:
        """Append one event. ``t`` defaults to ``time.perf_counter()`` now —
        pass an explicit stamp to backdate (e.g. a dispatch's issue time).
        ``data`` values must be JSON-serializable scalars/lists."""
        if not self.enabled:
            return
        if t is None:
            t = time.perf_counter()
        ev = (t, kind, rid, engine, loop, data or None)
        with self._lock:
            if len(self._ring) >= self.capacity and self.on_drop is not None:
                try:
                    self.on_drop()
                except Exception:
                    pass
            self._ring.append(ev)
            self._n_recorded += 1

    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    def total(self) -> int:
        """Events recorded over the recorder's lifetime (>= depth)."""
        with self._lock:
            return self._n_recorded

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._n_recorded = 0
            self._last_dump.clear()

    # -- export --------------------------------------------------------------

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """The ring as a list of event dicts, oldest first. ``t`` is
        seconds on the process-wide ``time.perf_counter`` clock — the same
        timebase every stamp in ``data`` (t_issue/t_ready) uses, so
        cross-loop ordering is exact."""
        with self._lock:
            events = list(self._ring)
        if limit is not None:
            events = events[-limit:]
        out = []
        for t, kind, rid, engine, loop, data in events:
            ev = {"t": round(t, 6), "kind": kind}
            if rid:
                ev["rid"] = rid
            if engine:
                ev["engine"] = engine
            if loop:
                ev["loop"] = loop
            if data:
                ev.update(data)
            out.append(ev)
        return out

    def to_trace_events(self) -> list[dict]:
        """Chrome trace-event export (open in ui.perfetto.dev or
        chrome://tracing). Layout: one Perfetto *process* per engine (plus
        one for engine-less events, e.g. server-side backstops); inside it,
        reaped dispatches render as complete ("X") slices on per-ring-depth
        threads — overlapped in-flight dispatches show as parallel bars,
        each tagged with its program family and request ids — and every
        other event is an instant ("i") on its loop's thread. Request-id
        correlation across the prefill/decode loops rides ``args.rid``."""
        with self._lock:
            events = list(self._ring)
        pids: dict[str, int] = {}
        tids: dict[tuple[int, str], int] = {}
        meta: list[dict] = []
        out: list[dict] = []

        def pid_of(engine: str) -> int:
            name = engine or "server"
            p = pids.get(name)
            if p is None:
                p = len(pids) + 1
                pids[name] = p
                meta.append({"ph": "M", "name": "process_name", "pid": p,
                             "tid": 0, "args": {"name": name}})
            return p

        def tid_of(pid: int, track: str) -> int:
            t = tids.get((pid, track))
            if t is None:
                t = sum(1 for (p, _) in tids if p == pid) + 1
                tids[(pid, track)] = t
                meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                             "tid": t, "args": {"name": track}})
            return t

        for t, kind, rid, engine, loop, data in events:
            data = data or {}
            pid = pid_of(engine)
            args = {k: v for k, v in data.items()}
            if rid:
                args["rid"] = rid
            if kind in _SPAN_KINDS and "t_issue" in data:
                t_issue = float(data["t_issue"])
                t_ready = float(data.get("t_ready") or t)
                tid = tid_of(pid, "ring[%d]" % int(data.get("depth", 0)))
                out.append({
                    "ph": "X", "name": str(data.get("family") or kind),
                    "cat": "dispatch", "pid": pid, "tid": tid,
                    "ts": round(t_issue * 1e6, 3),
                    "dur": round(max(0.0, t_ready - t_issue) * 1e6, 3),
                    "args": args,
                })
                continue
            tid = tid_of(pid, loop or "events")
            out.append({
                "ph": "i", "s": "t", "name": kind, "cat": kind,
                "pid": pid, "tid": tid, "ts": round(t * 1e6, 3),
                "args": args,
            })
        return meta + out

    # -- post-mortem dumps ---------------------------------------------------

    def dump(self, reason: str, log_dir: str | None = None) -> str | None:
        """Write the ring to ``<dir>/flightrec-<reason>-<stamp>.json``;
        returns the path, or None when disabled/rate-limited/failed. Never
        raises — a failing dump must not take the scheduler turn with it.
        Rate-limited per reason (``QUORUM_TPU_FLIGHT_DUMP_INTERVAL``
        seconds, default 0.25) so a containment storm cannot turn into a
        disk-write storm; the ring is cumulative, so the newest artifact
        still holds the suppressed occurrences' events."""
        if not self.enabled:
            return None
        try:
            interval = float(os.environ.get(
                "QUORUM_TPU_FLIGHT_DUMP_INTERVAL", "0.25"))
        except ValueError:
            interval = 0.25
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < interval:
                return None
            self._last_dump[reason] = now
            self._dump_seq += 1
            seq = self._dump_seq
        try:
            out_dir = log_dir or os.environ.get("QUORUM_TPU_FLIGHT_DIR",
                                                "logs")
            os.makedirs(out_dir, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            path = os.path.join(
                out_dir, f"flightrec-{reason}-{stamp}-{seq:04d}.json")
            body = {
                "reason": reason,
                "dumped_at": time.time(),
                "clock": "perf_counter",
                "events": self.snapshot(),
            }
            with open(path, "w") as f:
                json.dump(body, f)
            logger.warning("flight recorder dumped %d events to %s (%s)",
                           len(body["events"]), path, reason)
            return path
        except Exception:
            logger.exception("flight recorder dump failed (%s)", reason)
            return None


def merged_trace_events(
        groups: list[tuple[str, list[dict], float]]) -> list[dict]:
    """Fleet-timeline export: several processes' recorder SNAPSHOTS (the
    dict form ``FlightRecorder.snapshot`` emits / ``GET
    /debug/engine/timeline`` serves) merged into one Chrome trace-event
    stream. Each group is ``(process_name, events, offset_s)`` — one
    Perfetto *process* per group (the router, then one per replica), with
    ``offset_s`` added to every stamp so all groups land on ONE timebase
    (the router estimates each replica's offset from its telemetry
    polls; an unestimable offset is passed as 0.0, leaving that replica
    on its raw clock). Mirrors :meth:`FlightRecorder.to_trace_events`:
    reaped dispatches with ``t_issue`` become complete ("X") slices,
    everything else an instant ("i"); request-id correlation — the fleet
    plane's cross-tier trace-id — rides ``args.rid``."""
    tids: dict[tuple[int, str], int] = {}
    meta: list[dict] = []
    out: list[dict] = []

    def tid_of(pid: int, track: str) -> int:
        t = tids.get((pid, track))
        if t is None:
            t = sum(1 for (p, _) in tids if p == pid) + 1
            tids[(pid, track)] = t
            meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                         "tid": t, "args": {"name": track}})
        return t

    for pid0, (pname, events, offset) in enumerate(groups):
        pid = pid0 + 1
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": pname or f"proc-{pid}"}})
        for ev in events:
            if not isinstance(ev, dict):
                continue
            kind = str(ev.get("kind") or "event")
            try:
                t = float(ev.get("t", 0.0)) + offset
            except (TypeError, ValueError):
                continue
            args = {k: v for k, v in ev.items() if k not in ("t", "kind")}
            engine = str(ev.get("engine") or "")
            if kind in _SPAN_KINDS and "t_issue" in ev:
                try:
                    t_issue = float(ev["t_issue"]) + offset
                    t_ready = float(ev.get("t_ready") or ev["t"]) + offset
                except (TypeError, ValueError):
                    continue
                track = "ring[%d]" % int(ev.get("depth", 0) or 0)
                if engine:
                    track = f"{engine} {track}"
                out.append({
                    "ph": "X", "name": str(ev.get("family") or kind),
                    "cat": "dispatch", "pid": pid,
                    "tid": tid_of(pid, track),
                    "ts": round(t_issue * 1e6, 3),
                    "dur": round(max(0.0, t_ready - t_issue) * 1e6, 3),
                    "args": args,
                })
                continue
            track = str(ev.get("loop") or "events")
            if engine:
                track = f"{engine}/{track}"
            out.append({
                "ph": "i", "s": "t", "name": kind, "cat": kind,
                "pid": pid, "tid": tid_of(pid, track),
                "ts": round(t * 1e6, 3), "args": args,
            })
    return meta + out


RECORDER = FlightRecorder()
