"""SLO accounting: per-class latency objectives, counters, burn rate.

Requests classify by **deadline headroom** at admission into two classes:
a request whose declared budget (the ``timeout`` body knob, else the
config default) is at most ``QUORUM_TPU_SLO_INTERACTIVE_S`` (default 30 s)
is ``interactive`` — someone is waiting on it; anything looser is
``batch``. The class is attached to the request trace (``meta.slo``) and
scored once at request teardown (``observability.finish_request_trace``)
against per-class objectives, one good/breached observation per *stage*:

  ``ttft``        first content byte within the class's TTFT target
                  (streaming requests that produced any token)
  ``inter_token`` worst wire flush gap within the inter-token target
                  (streaming requests with >= 2 content flushes)
  ``deadline``    the request finished without running over its deadline
                  (breached on 504; shed-with-503-queue counts breached
                  too — the client did not get served inside its budget)

Counters ride ``quorum_tpu_slo_{good,breached}_total{class=,stage=}``
(observability.py, ``make metrics-check``), and a sliding-window **burn
rate** per class (breached / observed over the last
``QUORUM_TPU_SLO_WINDOW_S``, default 300 s) is exposed on ``/health`` and
``GET /debug/engine/timeline``. Setting ``QUORUM_TPU_SLO_READY_BURN`` to a
fraction (e.g. ``0.5``) wires the burn rate into the degradation story:
``/health`` reports ``degraded`` and ``/ready`` sheds (503 + Retry-After)
while any class burns past it — a load balancer rotates the replica before
clients eat the breaches. Off by default: the objectives are measurements
first, and a CPU test box must not flap readiness on them.

Objective targets (seconds, env-tunable):

  QUORUM_TPU_SLO_TTFT_INTERACTIVE_S   (default 2.0)
  QUORUM_TPU_SLO_TTFT_BATCH_S         (default 30.0)
  QUORUM_TPU_SLO_GAP_INTERACTIVE_S    (default 0.5)
  QUORUM_TPU_SLO_GAP_BATCH_S          (default 5.0)

This is the accounting half of ROADMAP open item 1 (preemptive SLO-aware
scheduling): the classes defined here are the priority classes admission
will act on, and the burn rate is the signal that says *when*.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

SLO_CLASSES = ("interactive", "batch")
SLO_STAGES = ("ttft", "inter_token", "deadline")


def _env_s(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def interactive_headroom_s() -> float:
    """The classification boundary: deadline headroom at or below this is
    interactive."""
    return _env_s("QUORUM_TPU_SLO_INTERACTIVE_S", 30.0)


def classify(timeout_s: float | None) -> str:
    """SLO class for a request with ``timeout_s`` of deadline headroom at
    submission (None = no deadline = batch)."""
    if timeout_s is None:
        return "batch"
    return ("interactive" if timeout_s <= interactive_headroom_s()
            else "batch")


def targets(cls: str) -> dict[str, float]:
    """{stage: target seconds} for one class (deadline has no scalar
    target — the request's own deadline is the target)."""
    if cls == "interactive":
        return {"ttft": _env_s("QUORUM_TPU_SLO_TTFT_INTERACTIVE_S", 2.0),
                "inter_token": _env_s("QUORUM_TPU_SLO_GAP_INTERACTIVE_S",
                                      0.5)}
    return {"ttft": _env_s("QUORUM_TPU_SLO_TTFT_BATCH_S", 30.0),
            "inter_token": _env_s("QUORUM_TPU_SLO_GAP_BATCH_S", 5.0)}


class SloTracker:
    """Thread-safe per-class good/breached accounting + sliding-window
    burn rate. Observations also tick the process-global
    ``quorum_tpu_slo_{good,breached}_total`` counter families."""

    WINDOW_EVENTS = 4096  # bound on the sliding window's memory

    def __init__(self):
        self._lock = threading.Lock()
        # (monotonic stamp, class, ok)
        self._window: deque = deque(maxlen=self.WINDOW_EVENTS)
        self._good: dict[tuple[str, str], int] = {}
        self._breached: dict[tuple[str, str], int] = {}

    def record(self, cls: str, stage: str, ok: bool) -> None:
        from quorum_tpu import observability as obs

        key = (cls, stage)
        with self._lock:
            book = self._good if ok else self._breached
            book[key] = book.get(key, 0) + 1
            self._window.append((time.monotonic(), cls, bool(ok)))
        fam = obs.SLO_GOOD if ok else obs.SLO_BREACHED
        fam.inc(**{"class": cls, "stage": stage})

    def score_trace(self, trace) -> None:
        """Score one finished request trace (class from ``meta.slo``;
        untagged traces — engine-direct tests, non-chat endpoints — are
        not scored). Called from finish_request_trace, i.e. exactly once
        per request."""
        cls = (trace.meta or {}).get("slo")
        if cls not in SLO_CLASSES:
            return
        tgt = targets(cls)
        if trace.ttft is not None:
            self.record(cls, "ttft", trace.ttft <= tgt["ttft"])
            # Worst flush gap, tracked UNCAPPED on the trace (the
            # token_times list stops at its cap — a >2048-token stream's
            # late stall must still score as a breach).
            worst = getattr(trace, "max_token_gap", None)
            if worst is not None:
                self.record(cls, "inter_token", worst <= tgt["inter_token"])
        status = trace.status
        if status is not None and status != 499:
            # 504 = deadline ran out mid-serve; the queue-stage shed is a
            # 503 whose trace carries a deadline-exceeded marker (other
            # 503s — breaker, queue-full — are capacity, not a deadline
            # breach). 5xx without a deadline marker scores nothing: a
            # contained engine failure is a failure, not an SLO sample.
            shed = status == 503 and any(
                s.name == "deadline-exceeded" for s in trace.spans)
            if status == 504 or shed:
                self.record(cls, "deadline", False)
            elif status < 500:
                self.record(cls, "deadline", True)

    def burn_rate(self, cls: str, window_s: float | None = None) -> float:
        """breached / observed for ``cls`` over the last ``window_s``
        seconds (0.0 with no observations)."""
        if window_s is None:
            window_s = _env_s("QUORUM_TPU_SLO_WINDOW_S", 300.0)
        cutoff = time.monotonic() - window_s
        with self._lock:
            events = [(c, ok) for t, c, ok in self._window
                      if t >= cutoff and c == cls]
        if not events:
            return 0.0
        breached = sum(1 for _, ok in events if not ok)
        return breached / len(events)

    def snapshot(self) -> dict:
        """Per-class totals by stage plus the current burn rate — the
        /health ``slo`` block and the timeline export's ``slo`` section."""
        with self._lock:
            good = dict(self._good)
            breached = dict(self._breached)
        out = {}
        for cls in SLO_CLASSES:
            stages = {}
            for stage in SLO_STAGES:
                g = good.get((cls, stage), 0)
                b = breached.get((cls, stage), 0)
                if g or b:
                    stages[stage] = {"good": g, "breached": b}
            out[cls] = {
                "stages": stages,
                "burn_rate": round(self.burn_rate(cls), 4),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._window.clear()
            self._good.clear()
            self._breached.clear()


SLO = SloTracker()


def ready_burn_threshold() -> float | None:
    """The opt-in /ready shedding threshold (None = disabled)."""
    raw = os.environ.get("QUORUM_TPU_SLO_READY_BURN", "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if 0.0 < v <= 1.0 else None


def burning_class(window_s: float | None = None) -> str | None:
    """The first class whose burn rate exceeds the opt-in threshold, or
    None (also None when the knob is off)."""
    thr = ready_burn_threshold()
    if thr is None:
        return None
    for cls in SLO_CLASSES:
        if SLO.burn_rate(cls, window_s) > thr:
            return cls
    return None
