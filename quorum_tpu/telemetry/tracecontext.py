"""W3C trace-context helpers: one trace-id names a request across tiers.

The fleet observability plane's correlation key (docs/observability.md,
"Fleet plane"): the router stamps every request with a ``traceparent``
header (https://www.w3.org/TR/trace-context/ — version ``00``, a 32-hex
trace-id, a 16-hex span-id for the sending hop, and a 2-hex flags byte),
the replica's server threads the trace-id through its
:class:`~quorum_tpu.observability.RequestTrace` and uses it as the
flight-recorder ``rid``, and the engine's dispatch/reap events inherit it
via the trace — so the router's route events, the replica's request spans,
and the engine's device timeline all join on one id, surviving failover
(same trace-id, a fresh span-id per hop).

Pure stdlib, jax-free, imported by ``oai.py`` / the router / the engine —
keep it dependency-light.
"""

from __future__ import annotations

import re
import uuid

# traceparent: version "00" only (the one defined version); trace-id and
# span-id must be non-zero per spec.
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id() -> str:
    """A fresh 32-hex trace-id (uuid4 randomness; never all-zero)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """A fresh 16-hex span-id for one hop."""
    return uuid.uuid4().hex[:16]


def format_traceparent(trace_id: str, span_id: str,
                       flags: str = "01") -> str:
    """The wire form: ``00-<trace-id>-<span-id>-<flags>``."""
    return f"00-{trace_id}-{span_id}-{flags}"


def parse_traceparent(value: str | None) -> tuple[str, str] | None:
    """``(trace_id, span_id)`` from a traceparent header, or None when the
    value is absent or malformed (unknown versions and zero ids are
    rejected — a caller falls back to minting, never to trusting junk)."""
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def child_traceparent(trace_id: str) -> tuple[str, str]:
    """``(span_id, header)`` for a new hop inside ``trace_id`` — same
    trace, fresh span (what the router stamps per replica attempt)."""
    span_id = new_span_id()
    return span_id, format_traceparent(trace_id, span_id)
