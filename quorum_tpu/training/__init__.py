from quorum_tpu.training.trainer import TrainState, loss_fn, make_train_step, train_init

__all__ = ["TrainState", "loss_fn", "make_train_step", "train_init"]
