"""Training checkpoint save/resume (orbax) + serve-from-checkpoint.

The reference proxy is stateless and has no checkpointing of any kind
(SURVEY.md §5.4 — its only persistence is config.yaml); a complete TPU
framework needs elastic training: save the full sharded TrainState
(params + AdamW moments + step), restore it *directly into the mesh
layout* (each device reads its own shard — no host-side gather of a
multi-GB pytree), and keep training from the exact step.

Design:
  - orbax ``CompositeCheckpointHandler`` with three items — ``params``,
    ``opt_state``, ``step`` — so serving can restore the params item ALONE:
    ``restore_params`` never materializes the 2× AdamW moments (at 7B the
    bf16 params are ~14.5 GB of a 16 GB chip; params + moments would OOM
    exactly where serve-from-checkpoint is needed).
  - Restore is sharding-aware: the abstract target carries the SAME
    NamedShardings the live state uses, so restored arrays materialize
    sharded — resuming on a different mesh shape re-lays the weights
    automatically.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import optax
from jax.sharding import Mesh

from quorum_tpu.models.model_config import ModelSpec
from quorum_tpu.training.trainer import TrainState, make_optimizer, train_init


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(
        ocp.CompositeCheckpointHandler("params", "opt_state", "step")
    )


def save_checkpoint(path: str, state: TrainState) -> None:
    """Write the full TrainState to ``path`` (a directory, created fresh)."""
    import orbax.checkpoint as ocp

    ckptr = _checkpointer()
    ckptr.save(
        os.path.abspath(path),
        args=ocp.args.Composite(
            params=ocp.args.StandardSave(state.params),
            opt_state=ocp.args.StandardSave(state.opt_state),
            step=ocp.args.StandardSave({"step": state.step}),
        ),
        force=True,
    )


def _abstract_like(tree: Any) -> Any:
    """ShapeDtypeStruct pytree carrying the live tree's shardings."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        tree,
    )


def _abstract_params(spec: ModelSpec, mesh: Mesh) -> Any:
    """Sharded abstract params pytree — no device allocation."""
    from quorum_tpu.models.init import init_params
    from quorum_tpu.parallel.sharding import param_shardings

    shapes = jax.eval_shape(lambda: init_params(spec, 0))
    shardings = param_shardings(mesh, shapes, n_kv_heads=spec.n_kv_heads)
    return jax.tree.map(
        lambda s, sh: (None if s is None
                       else jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)),
        shapes, shardings,
        is_leaf=lambda x: x is None or hasattr(x, "shape"),
    )


def _abstract_state(
    spec: ModelSpec, mesh: Mesh, opt: optax.GradientTransformation
) -> TrainState:
    """Abstract TrainState with the exact shardings train_init produces —
    derived via AOT compilation (``lower().compile().output_shardings``),
    so building the restore target allocates NOTHING on device (restore
    time is exactly when HBM headroom matters)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    params = _abstract_params(spec, mesh)
    compiled = jax.jit(opt.init).lower(params).compile()
    opt_shapes = jax.eval_shape(opt.init, params)
    rep = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            # same normalization as train_init: fully-replicated outputs
            # collapse to SingleDeviceSharding in the AOT answer too
            sharding=sh if isinstance(sh, NamedSharding) else rep,
        ),
        opt_shapes, compiled.output_shardings,
    )
    import jax.numpy as jnp

    step = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)
    return TrainState(params=params, opt_state=opt_state, step=step)


def restore_checkpoint(
    path: str,
    spec: ModelSpec,
    mesh: Mesh,
    *,
    optimizer: optax.GradientTransformation | None = None,
) -> TrainState:
    """Restore a full TrainState onto ``mesh``, sharded in place."""
    import orbax.checkpoint as ocp

    opt = optimizer or make_optimizer()
    abstract = _abstract_state(spec, mesh, opt)
    restored = _checkpointer().restore(
        os.path.abspath(path),
        args=ocp.args.Composite(
            params=ocp.args.StandardRestore(abstract.params),
            opt_state=ocp.args.StandardRestore(abstract.opt_state),
            step=ocp.args.StandardRestore({"step": abstract.step}),
        ),
    )
    state = TrainState(
        params=restored.params,
        opt_state=restored.opt_state,
        step=restored.step["step"],
    )
    # Orbax can hand scalar/0-d leaves back single-device; pin every leaf to
    # the template's mesh sharding (no-op for leaves already laid out).
    return jax.tree.map(
        lambda x, a: jax.device_put(x, a.sharding), state, abstract
    )


def restore_params(path: str, spec: ModelSpec, mesh: Mesh) -> Any:
    """Load ONLY the params item of a training checkpoint (for serving:
    ``InferenceEngine(spec, mesh, params=restore_params(...))``) — the
    optimizer moments are never read or materialized."""
    import orbax.checkpoint as ocp

    abstract = _abstract_params(spec, mesh)
    ckptr = _checkpointer()
    restored = ckptr.restore(
        os.path.abspath(path),
        args=ocp.args.Composite(params=ocp.args.StandardRestore(abstract)),
    )
    return restored.params
