"""Training step: next-token cross-entropy + AdamW over the sharded pytree.

The reference proxy has no training of any kind (SURVEY.md "What quorum is
NOT", /root/reference/src/quorum/oai_proxy.py has no torch/jax imports), but a
TPU-native framework's model runtime must be trainable to be complete — the
same ``forward_logits`` that serves requests is differentiated here, so the
serving and training paths can never drift apart.

TPU-first design:

  - grads/optimizer run under the SAME GSPMD shardings as serving: params are
    placed by quorum_tpu.parallel.sharding and optimizer state inherits the
    layout via jit sharding propagation — Megatron-style TP falls out with no
    extra code, XLA inserts the psums.
  - tokens are sharded ``[dp, sp]``: batch over the data-parallel axis and
    sequence over the sequence-parallel axis, so long-context training
    shards activation memory the way the scaling-book recipe prescribes.
  - ``remat=True`` wraps each scanned layer in ``jax.checkpoint`` — the
    standard FLOPs-for-HBM trade for long sequences.
  - the train step donates params + opt state: XLA updates them in place.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quorum_tpu.compile_cache import enable_persistent_compile_cache
from quorum_tpu.models.init import init_params
from quorum_tpu.models.model_config import ModelSpec
from quorum_tpu.models.transformer import forward_logits
from quorum_tpu.parallel.mesh import AXIS_DP, AXIS_SP
from quorum_tpu.parallel.sharding import shard_pytree

enable_persistent_compile_cache()  # restart compiles become disk reads


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def loss_fn(params, spec: ModelSpec, tokens: jnp.ndarray, remat: bool = True):
    """Mean next-token cross-entropy over ``tokens`` [B, T] (0 = pad).

    Computed in f32 off bf16 activations; the pad mask keeps padded positions
    out of the mean so bucketed batches train correctly.
    """
    logits = forward_logits(params, spec, tokens[:, :-1], remat=remat)  # [B,T-1,V]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = (targets != 0).astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.1,
    *,
    warmup_steps: int = 0,
    total_steps: int | None = None,
    grad_clip: float | None = None,
    accum_steps: int = 1,
) -> optax.GradientTransformation:
    """The standard LLM training stack, composed from optax:

      - AdamW (b1 0.9, b2 0.95) at ``lr`` — constant by default; with
        ``warmup_steps``/``total_steps`` a linear-warmup + cosine-decay
        schedule (the near-universal LLM recipe);
      - optional global-norm gradient clipping (``grad_clip``);
      - optional gradient accumulation (``accum_steps`` micro-batches per
        optimizer update, via ``optax.MultiSteps``) — the TPU-relevant
        lever: global batch beyond what fits HBM costs steps, not memory.
        Micro-gradients are cast to f32 before the running mean (bf16
        accumulation would round away late micro-batches as the window
        grows). For micro-batches with EQUAL real-token counts the
        accumulated update equals one big-batch step (pinned by
        tests/test_train_checkpoint); unequal counts weight each
        micro-batch's tokens by 1/its own count — loss_fn normalizes per
        micro-batch — so keep bucketed batches out of one window.

    Any bespoke ``optax.GradientTransformation`` can still be passed to
    ``train_init``/``make_train_step`` directly; this is the shipped recipe.
    """
    if total_steps is not None:
        if warmup_steps >= total_steps:
            raise ValueError(
                f"warmup_steps={warmup_steps} must be < total_steps="
                f"{total_steps} (no decay budget left)")
        sched = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr,
            warmup_steps=max(0, warmup_steps),
            decay_steps=max(1, total_steps))
    elif warmup_steps > 0:
        sched = optax.linear_schedule(0.0, lr, warmup_steps)
    else:
        sched = lr
    tx = optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay)
    if grad_clip is not None:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
    if accum_steps > 1:
        # f32 accumulator: MultiSteps keeps its running mean in the
        # incoming gradient dtype, and a bf16 mean over a long window
        # rounds away the late micro-batches' 1/k-scaled contributions.
        cast_f32 = optax.GradientTransformation(
            lambda params: optax.EmptyState(),
            lambda updates, state, params=None: (
                jax.tree.map(lambda g: g.astype(jnp.float32), updates),
                state))
        tx = optax.chain(cast_f32, optax.MultiSteps(tx, every_k_schedule=accum_steps))
    return tx


def train_init(
    spec: ModelSpec,
    mesh: Mesh,
    *,
    seed: int = 0,
    optimizer: optax.GradientTransformation | None = None,
) -> TrainState:
    """Initialize a sharded TrainState on ``mesh``.

    Params get the explicit TP/EP layout from the sharding table; optimizer
    moments inherit it through jit output-sharding propagation (they are
    elementwise over params, so GSPMD keeps them aligned).
    """
    opt = optimizer or make_optimizer()
    params = shard_pytree(mesh, init_params(spec, seed),
                          n_kv_heads=spec.n_kv_heads)
    opt_state = jax.jit(opt.init)(params)
    # jit collapses fully-replicated outputs (adam count, moments of
    # replicated params) to SingleDeviceSharding; pin those back to a
    # replicated NamedSharding so the whole state shares one device set —
    # required for the train step's donation and for sharded checkpoint
    # restore to round-trip exactly. tp-sharded moments keep the
    # NamedSharding propagation already gave them.
    rep = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda x: x if isinstance(x.sharding, NamedSharding)
        else jax.device_put(x, rep),
        opt_state,
    )
    step = jax.device_put(jnp.zeros((), jnp.int32), rep)
    return TrainState(params=params, opt_state=opt_state, step=step)


def make_train_step(
    spec: ModelSpec,
    mesh: Mesh,
    *,
    optimizer: optax.GradientTransformation | None = None,
    remat: bool = True,
):
    """Compile one SGD step over the mesh: returns ``step(state, tokens)``.

    ``tokens`` must be [B, T] with B divisible by the dp axis and T by the sp
    axis; the returned callable is jitted with donated state.
    """
    opt = optimizer or make_optimizer()
    token_sharding = NamedSharding(mesh, P(AXIS_DP, AXIS_SP))

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens: jnp.ndarray):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, spec, tokens, remat)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    def run(state: TrainState, tokens) -> tuple[TrainState, jnp.ndarray]:
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), token_sharding)
        return step(state, tokens)

    return run
