"""Chaos harness: inject faults at every named site and assert containment.

``make chaos-check`` runs the full sweep on a tiny CPU engine behind the real
ASGI app (no network, httpx ASGITransport). For each injection site
(quorum_tpu/faults.py) it drives concurrent load, arms the fault, and
asserts the containment contract of docs/robustness.md:

  - only the affected request(s) error; a co-batched or queued bystander
    either completes or is requeued and completes;
  - the immediately following request succeeds (the engine rebuilt);
  - deadline-exceeded requests get their timeout response within
    deadline + slack and release their slots;
  - a failure storm opens the engine breaker (503 + Retry-After) and
    /health reports it; a cooldown probe closes it again;
  - with faults disarmed, greedy AND sampled outputs are pinned
    token-for-token against the pre-chaos baseline (fault machinery is
    inert when disarmed);
  - the HTTP backend retry ladder recovers from transient connect
    errors / 5xx within its budget;
  - the router replica-kill drill (phase 6, docs/scaling.md): SIGKILL one
    replica under load — the survivor's in-flight stream completes
    untouched, the dead replica's requests fail over and complete
    elsewhere within their deadlines, the /ready poller rotates the
    corpse out of the ring, and with every replica dead the router sheds
    503 + Retry-After instead of hanging;
  - the quorum member-kill drill (phase 10, docs/quorum.md): SIGKILL one
    member of a quorum=3 fan-out mid-generation — with a spare cell the
    member finishes token-exact elsewhere and the quorum stays full, with
    no spare the request is served degraded from the survivors, never
    failed.

Exit codes: 0 = all checks passed, 1 = at least one failed, 2 = the harness
itself hung (watchdog). ``tests/test_robustness.py`` runs the quick subset
as a suite smoke; the full sweep is wired into ``make chaos-check``.
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("QUORUM_TPU_COMPILE_CACHE", "0")
# The disagg handoff phase needs one virtual device per group.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

SCRIPT_TIMEOUT_S = 600.0   # watchdog over the whole sweep
DEADLINE_SLACK_S = 2.0     # acceptance: timeout response within deadline + 2s

_CHECKS: list[tuple[str, bool, str]] = []


def check(name: str, ok: bool, detail: str = "") -> None:
    _CHECKS.append((name, bool(ok), detail))
    print(f"  [{'ok' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail and not ok else ""), flush=True)


def _flight_dump_check(label: str, needle: str) -> None:
    """Containments are no longer post-mortem-blind (ISSUE 12): after a
    containment phase, a flight-recorder dump artifact must exist in the
    sweep's dump dir, parse as JSON, and hold an event mentioning the
    faulted site (the containment/fail-all event's error carries the
    FaultInjected message, which names the site). Dumps are cumulative
    ring snapshots, so any artifact written at-or-after the phase holds
    its events — newest first."""
    files = sorted(glob.glob(os.path.join(
        os.environ.get("QUORUM_TPU_FLIGHT_DIR", "logs"),
        "flightrec-*.json")), reverse=True)
    ok, detail = False, "no flightrec-*.json dump artifacts found"
    for path in files:
        try:
            with open(path) as f:
                body = json.load(f)
        except Exception as e:
            detail = f"unparseable dump {path}: {e}"
            continue
        events = body.get("events")
        if not isinstance(events, list):
            detail = f"dump {path} has no events list"
            continue
        if any(needle in json.dumps(ev) for ev in events):
            ok = True
            detail = os.path.basename(path)
            break
        detail = f"site {needle!r} in none of {len(files)} dumps"
    check(f"{label}: flight-recorder dump holds the faulted site", ok,
          detail)


def _spawn_fake_replica(name: str, *, chunk_delay: float = 0.0,
                        tokens: int = 8):
    """Spawn a killable jax-free fake replica process; returns
    ``(proc, base_url)`` once it prints its bound port."""
    import subprocess

    env = dict(os.environ)
    proc = subprocess.Popen(
        [sys.executable, "-m", "quorum_tpu.router.fake_replica",
         "--name", name, "--port", "0",
         "--chunk-delay", str(chunk_delay), "--tokens", str(tokens)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    deadline = time.time() + 30.0
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            return proc, f"http://127.0.0.1:{port}"
    proc.kill()
    raise RuntimeError(f"fake replica {name} never bound a port")


async def _router_kill_drill(check) -> None:
    """Phase 6 body: two fake replica processes behind the real router
    app; SIGKILL one mid-stream and assert the containment contract."""
    import httpx

    from quorum_tpu.router import affinity as aff
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.telemetry.recorder import RECORDER

    proc_a = proc_b = None
    try:
        proc_a, url_a = _spawn_fake_replica("kill-a", chunk_delay=0.05,
                                            tokens=60)
        proc_b, url_b = _spawn_fake_replica("kill-b", chunk_delay=0.05,
                                            tokens=60)
        rcfg = RouterConfig(
            replicas=[("kill-a", url_a), ("kill-b", url_b)],
            ready_interval=0.25, retries=1, timeout=20.0,
            breaker_threshold=2, breaker_cooldown=0.5,
            migrate_on_rotation=False,
            # This phase pins the RESUME-OFF degrade contract (exactly one
            # error chunk on the killed stream, never a re-send); phase 9
            # runs the same kill with resume ON and asserts zero loss.
            stream_resume=False)
        router_app = create_router_app(rcfg)
        mgr = router_app.state["replica_set"]

        def body_keyed_to(target: str, *, stream: bool,
                          max_tokens: int = 60, salt: str = "") -> dict:
            """A conversation whose affinity primary is ``target``."""
            for i in range(200):
                msgs = [{"role": "user",
                         "content": f"drill{salt} conversation {i}: "
                                    "please answer at length"}]
                key = aff.conversation_key({"messages": msgs},
                                           rcfg.affinity_chunk)
                if mgr.ring.primary(key) == target:
                    return {"model": "m", "messages": msgs,
                            "stream": stream, "max_tokens": max_tokens}
            raise RuntimeError(f"no key found for {target}")

        transport = httpx.ASGITransport(app=router_app)
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://router",
                                     timeout=30.0) as rc:

            async def consume_stream(body: dict) -> dict:
                out = {"tokens": 0, "done": False, "error_chunks": 0,
                       "routed": None}
                async with rc.stream("POST", "/chat/completions",
                                     json=body) as resp:
                    out["status"] = resp.status_code
                    out["routed"] = resp.headers.get("x-routed-to")
                    async for line in resp.aiter_lines():
                        if not line.startswith("data: "):
                            continue
                        data = line[len("data: "):]
                        if data.strip() == "[DONE]":
                            out["done"] = True
                            continue
                        ev = json.loads(data)
                        choice = (ev.get("choices") or [{}])[0]
                        delta = choice.get("delta") or {}
                        if choice.get("finish_reason") == "error":
                            out["error_chunks"] += 1
                        elif delta.get("content"):
                            out["tokens"] += 1
                return out

            # In-flight streams on BOTH replicas (~3s each at 60 tokens
            # x 50ms), then SIGKILL replica A mid-stream.
            # Keys computed BEFORE the kill: the poller may rotate the
            # corpse out at any tick, after which no key maps to it.
            queued_bodies = [
                body_keyed_to("kill-a", stream=False, max_tokens=4,
                              salt=f"q{i}")
                for i in range(3)]
            stream_a = asyncio.create_task(consume_stream(
                body_keyed_to("kill-a", stream=True)))
            stream_b = asyncio.create_task(consume_stream(
                body_keyed_to("kill-b", stream=True)))
            await asyncio.sleep(0.6)  # both streams well under way
            proc_a.kill()
            proc_a.wait()
            # "Queued for A" requests arriving after the kill: they must
            # fail over to B and complete within their deadline.
            t0 = time.time()
            queued = await asyncio.wait_for(asyncio.gather(
                *(rc.post("/chat/completions", json=body)
                  for body in queued_bodies)), timeout=15.0)
            failover_wall = time.time() - t0
            got_a = await asyncio.wait_for(stream_a, timeout=30.0)
            got_b = await asyncio.wait_for(stream_b, timeout=30.0)
            check("router kill: survivor stream unharmed",
                  got_b["routed"] == "kill-b" and got_b["tokens"] == 60
                  and got_b["done"] and got_b["error_chunks"] == 0,
                  f"{got_b}")
            check("router kill: killed stream errors, never hangs or "
                  "double-delivers",
                  got_a["routed"] == "kill-a" and got_a["tokens"] < 60
                  and got_a["error_chunks"] == 1 and got_a["done"],
                  f"{got_a}")
            check("router kill: queued requests complete elsewhere in "
                  "deadline",
                  all(r.status_code == 200
                      and r.headers.get("x-routed-to") == "kill-b"
                      for r in queued) and failover_wall < 10.0,
                  f"statuses={[r.status_code for r in queued]} "
                  f"wall={failover_wall:.1f}s")
            # The /ready poller rotates the corpse out of the ring.
            poll_deadline = time.time() + 5.0
            while time.time() < poll_deadline and "kill-a" in mgr.ring:
                await asyncio.sleep(0.1)
            check("router kill: dead replica rotated out of the ring",
                  "kill-a" not in mgr.ring and "kill-b" in mgr.ring,
                  f"ring={sorted(mgr.ring.members)}")
            after = await rc.post(
                "/chat/completions",
                json=body_keyed_to("kill-b", stream=False, max_tokens=4))
            check("router kill: post-rotation requests serve from the "
                  "survivor", after.status_code == 200
                  and after.headers.get("x-routed-to") == "kill-b")
            events = json.dumps(RECORDER.snapshot())
            check("router kill: failover visible on metrics + flight "
                  "recorder",
                  "router-failover" in events
                  and "router-replica-out" in events)
            # Kill the survivor too: the router must shed, never hang.
            proc_b.kill()
            proc_b.wait()
            while time.time() < poll_deadline + 5.0 and len(mgr.ring):
                await asyncio.sleep(0.1)
            shed = await asyncio.wait_for(
                rc.post("/chat/completions",
                        json={"model": "m", "max_tokens": 4,
                              "messages": [{"role": "user",
                                            "content": "anyone alive?"}]}),
                timeout=15.0)
            check("router kill: all replicas dead -> 503 + Retry-After, "
                  "no hang",
                  shed.status_code == 503
                  and "retry-after" in {k.lower() for k in shed.headers},
                  f"status={shed.status_code}")
            await mgr.aclose()
    finally:
        for proc in (proc_a, proc_b):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()


async def _fleet_trace_drill(check) -> None:
    """Phase 7 body: trace continuity through failover, fleet-wide.

    Two fake replica processes behind the real router; SIGKILL one while
    a stream is in flight, then send a request keyed to the corpse. The
    failed-over request's W3C trace-id must name it in the router's own
    timeline (failover + serving hop), in the SURVIVOR's flight
    recorder, and in the merged /debug/fleet/timeline — one id, three
    processes (docs/observability.md "Fleet plane")."""
    import httpx

    from quorum_tpu.router import affinity as aff
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.telemetry.recorder import RECORDER

    proc_a = proc_b = None
    try:
        proc_a, url_a = _spawn_fake_replica("trace-a", chunk_delay=0.05,
                                            tokens=60)
        proc_b, url_b = _spawn_fake_replica("trace-b", chunk_delay=0.05,
                                            tokens=60)
        rcfg = RouterConfig(
            replicas=[("trace-a", url_a), ("trace-b", url_b)],
            ready_interval=0.25, retries=1, timeout=20.0,
            breaker_threshold=2, breaker_cooldown=0.5,
            migrate_on_rotation=False)
        router_app = create_router_app(rcfg)
        mgr = router_app.state["replica_set"]

        def body_keyed_to(target: str, *, stream: bool,
                          max_tokens: int = 60) -> dict:
            for i in range(200):
                msgs = [{"role": "user",
                         "content": f"trace conversation {i}: "
                                    "please answer at length"}]
                key = aff.conversation_key({"messages": msgs},
                                           rcfg.affinity_chunk)
                if mgr.ring.primary(key) == target:
                    return {"model": "m", "messages": msgs,
                            "stream": stream, "max_tokens": max_tokens}
            raise RuntimeError(f"no key found for {target}")

        transport = httpx.ASGITransport(app=router_app)
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://router",
                                     timeout=30.0) as rc:
            # one poll sweep up front: telemetry (and clock offsets) for
            # both replicas while both are alive
            await mgr.poll_once()
            failover_body = body_keyed_to("trace-a", stream=False,
                                          max_tokens=4)

            async def consume(body: dict) -> None:
                async with rc.stream("POST", "/chat/completions",
                                     json=body) as resp:
                    async for _line in resp.aiter_lines():
                        pass

            stream_a = asyncio.create_task(consume(
                body_keyed_to("trace-a", stream=True)))
            await asyncio.sleep(0.6)  # stream well under way
            proc_a.kill()
            proc_a.wait()
            failed_over = await asyncio.wait_for(
                rc.post("/chat/completions", json=failover_body),
                timeout=15.0)
            await asyncio.wait_for(stream_a, timeout=30.0)
            trace_id = failed_over.headers.get("x-request-id", "")
            check("fleet trace: failed-over request serves from the "
                  "survivor with a 32-hex trace-id",
                  failed_over.status_code == 200
                  and failed_over.headers.get("x-routed-to") == "trace-b"
                  and len(trace_id) == 32,
                  f"status={failed_over.status_code} rid={trace_id!r}")
            tp = failed_over.headers.get("traceparent", "")
            check("fleet trace: response traceparent carries the same "
                  "trace-id", tp.startswith(f"00-{trace_id}-"), tp)

            # 1/3 — router timeline: failed attempt on the corpse, serving
            # hop on the survivor marked failover=1, distinct spans
            mine = [ev for ev in RECORDER.snapshot()
                    if ev.get("rid") == trace_id]
            failed = [ev for ev in mine
                      if ev["kind"] == "router-failover"]
            routed = [ev for ev in mine if ev["kind"] == "router-route"]
            check("fleet trace: router timeline joins failover + serving "
                  "hop on the trace-id",
                  bool(failed) and bool(routed)
                  and failed[0].get("replica") == "trace-a"
                  and routed[0].get("replica") == "trace-b"
                  and routed[0].get("failover") == 1
                  and routed[0].get("span") != failed[0].get("span"),
                  f"failover={failed} route={routed}")

            # 2/3 — the survivor's own recorder saw the same trace-id
            async with httpx.AsyncClient(timeout=10.0) as direct:
                tl = (await direct.get(
                    f"{url_b}/debug/engine/timeline")).json()
            surv = [ev for ev in tl.get("events", [])
                    if ev.get("rid") == trace_id]
            check("fleet trace: survivor's recorder carries the "
                  "trace-id",
                  {"dispatch", "reap"} <= {ev["kind"] for ev in surv},
                  f"kinds={sorted({ev['kind'] for ev in surv})}")

            # 3/3 — the merged fleet timeline joins both processes on it
            fleet = (await rc.get("/debug/fleet/timeline")).json()
            merged = [ev for ev in fleet["events"]
                      if ev.get("rid") == trace_id]
            procs = {ev.get("process") for ev in merged}
            aligned = {row["name"]: row.get("clock_aligned")
                       for row in fleet.get("replicas", [])}
            check("fleet trace: merged fleet timeline joins router + "
                  "survivor on the trace-id, clock-aligned",
                  procs == {"router", "trace-b"}
                  and aligned.get("trace-b") is True,
                  f"procs={sorted(p or '?' for p in procs)} "
                  f"aligned={aligned}")
            stamps = [ev["t"] for ev in merged]
            check("fleet trace: aligned events sit within one request's "
                  "duration",
                  bool(stamps) and max(stamps) - min(stamps) < 5.0,
                  f"spread={max(stamps) - min(stamps):.3f}s"
                  if stamps else "no events")
            await mgr.aclose()
    finally:
        for proc in (proc_a, proc_b):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()


async def _qos_preemption_drill(check) -> None:
    """Phase 8 (docs/scheduling.md): the preemption contract under fault.

    Own app on a dedicated qos=1 engine — slots=1 so an interactive
    arrival NEVER finds a free slot (the preemption path is the only way
    in), kv_pages=1 so the drill also audits page accounting across
    park/resume. Three checks:

      1. an interactive arrival mid-decode preempts the batch resident
         and admits (the beneficiary finishes first);
      2. the parked victim's stream is token-for-token identical to its
         solo (uncontended) run — the preemption contract;
      3. with ``engine.preempt`` armed, the park fault dooms ONLY the
         victim: the beneficiary still admits and completes, the next
         request is clean, and the page pool drains to zero (no leaked
         pages from the half-parked row).
    """
    import queue as _queue

    from quorum_tpu import faults
    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app

    cfg = {
        "settings": {"timeout": 60},
        "primary_backends": [{
            "name": "Q",
            # d_model=96 ≠ the main engine's 128: a distinct cache key,
            # so this drill never flips qos on the shared phase-0 engine.
            "url": ("tpu://llama-tiny?d_model=96&max_seq=128"
                    "&slots=1&queue=8&decode_chunk=4&max_tokens=64"
                    "&qos=1&kv_pages=1&kv_page_size=16"),
            "model": "chaos-qos",
        }],
    }
    app = create_app(Config(raw=cfg), watch_config=False)
    backend = app.state["registry"].get("Q")
    eng = backend.engine
    check("qos: engine flag set via URL opt", bool(eng.qos))
    tok = backend.tokenizer
    victim_ids = tok.encode("the quick brown fox jumps over")
    bene_ids = tok.encode("hello there")

    def run_solo(ids, n, *, priority=None):
        req = eng.submit(list(ids), max_new_tokens=n, seed=5,
                         eos_id=None, priority=priority)
        return list(eng.stream_results(req))

    def drain_async(req, sink):
        try:
            for t in eng.stream_results(req):
                sink.append(t)
        except Exception:
            # Armed arm: the doomed victim's stream raises FaultInjected
            # here — the drill inspects the err frame / short stream
            # directly, so the thread just exits quietly.
            pass

    solo = run_solo(victim_ids, 48)
    check("qos: solo baseline nonempty", len(solo) > 0)

    async def drill(label, armed):
        if armed:
            faults.reset_counts()
            faults.arm("engine.preempt", times=1)
        # The tiny model decodes its whole 48-token budget in tens of
        # milliseconds: on a loaded core the victim can finish before the
        # interactive arrival's admission attempt ever flags it. Retry
        # the attempt until a preemption (or the armed fault) is actually
        # observed — every attempt still checks the full contract.
        for attempt in range(5):
            before = eng.n_preemptions
            victim = eng.submit(list(victim_ids), max_new_tokens=48,
                                seed=5, eos_id=None, priority="batch")
            got: list[int] = []
            th = threading.Thread(target=drain_async, args=(victim, got),
                                  daemon=True)
            th.start()
            # The victim must be mid-decode when the interactive request
            # lands, or there is nothing to preempt.
            deadline_t = time.time() + 30
            while victim.emitted < 6 and time.time() < deadline_t:
                await asyncio.sleep(0.01)
            bene = eng.submit(list(bene_ids), max_new_tokens=8, seed=9,
                              eos_id=None, priority="interactive")
            bene_got = list(await asyncio.to_thread(
                lambda: list(eng.stream_results(bene))))
            await asyncio.to_thread(th.join, 60)
            hit = (faults.fired("engine.preempt") >= 1 if armed
                   else eng.n_preemptions > before)
            if hit:
                break
        if armed:
            faults.disarm()
            check("qos: preempt fault fired",
                  faults.fired("engine.preempt") >= 1)
            # The fault lands between flag and park: the victim alone is
            # doomed (an err frame ended its stream mid-generation).
            err = None
            try:
                while True:
                    kind, val = victim.out.get_nowait()
                    if kind == "err":
                        err = val
            except _queue.Empty:
                pass
            check("qos: faulted park dooms only the victim",
                  err is not None or len(got) < len(solo),
                  f"err={err!r} got={len(got)}/{len(solo)}")
        else:
            check("qos: preemption occurred",
                  eng.n_preemptions == before + 1,
                  f"preemptions {before}->{eng.n_preemptions}")
            check("qos: victim stream token-exact across park/resume",
                  got == solo, f"lens {len(got)} vs {len(solo)}")
        check(f"qos: beneficiary admitted and completed ({label})",
              len(bene_got) == 8, f"got {len(bene_got)}")

    await drill("clean", armed=False)
    await drill("faulted", armed=True)

    # Post-drill hygiene: a fresh request is clean, and page accounting
    # is exact — allocated pages are retained prefix donors only (live
    # claims all zero, pool conserved); a conservation miss means the
    # faulted park lost a row's pages (the exact-accounting half of the
    # phase).
    again = run_solo(victim_ids, 48)
    check("qos: next request after fault matches solo", again == solo)
    m = eng.metrics()
    with eng._cond:
        live_claims = sum(eng._page_claims)
    check("qos: page accounting exact (no leaked pages or claims)",
          m.get("kv_pages_allocated", 0) + m.get("kv_pages_free", 0)
          == eng.kv_pool_pages and live_claims == 0,
          f"allocated={m.get('kv_pages_allocated')} "
          f"free={m.get('kv_pages_free')} pool={eng.kv_pool_pages} "
          f"claims={live_claims}")
    check("qos: preemption metrics exported",
          m.get("qos") == 1 and m.get("preemptions_total", 0) >= 1
          and m.get("preempted_tokens_total", 0) >= 1)


async def _stream_resume_drill(check) -> None:
    """Phase 9 body (ISSUE 19, docs/robustness.md "Zero-loss streams"):
    with resume ON, a SIGKILLed replica's live stream continues on the
    survivor with the client-visible token sequence IDENTICAL to an
    uninterrupted run; a survivor whose replay guard refuses the journal
    degrades to the PR 12 error-chunk contract with no duplicate frames
    (likewise a fault injected at ``router.resume``); and a scripted
    drain of 1-of-2 replicas under live traffic finishes every request —
    zero failures — with the parked stream proactively resumed."""
    import httpx

    from quorum_tpu import faults
    from quorum_tpu.observability import ROUTER_STREAM_RESUMES
    from quorum_tpu.router import affinity as aff
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.telemetry.recorder import RECORDER

    async def consume(rc, body: dict) -> dict:
        out = {"text": "", "frames": [], "done": False, "error_chunks": 0,
               "error_text": "", "roles": 0, "routed": None, "ids": set()}
        async with rc.stream("POST", "/chat/completions",
                             json=body) as resp:
            out["status"] = resp.status_code
            out["routed"] = resp.headers.get("x-routed-to")
            async for line in resp.aiter_lines():
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data.strip() == "[DONE]":
                    out["done"] = True
                    continue
                ev = json.loads(data)
                if ev.get("id"):
                    out["ids"].add(ev["id"])
                choice = (ev.get("choices") or [{}])[0]
                delta = choice.get("delta") or {}
                if choice.get("finish_reason") == "error":
                    out["error_chunks"] += 1
                    out["error_text"] += delta.get("content") or ""
                elif delta.get("role"):
                    out["roles"] += 1
                elif delta.get("content"):
                    out["text"] += delta["content"]
                    out["frames"].append(delta["content"])
        return out

    async def cluster(tag: str):
        """Spawn a killable pair + a resume-ON router over them."""
        proc_a, url_a = _spawn_fake_replica(f"{tag}-a", chunk_delay=0.05,
                                            tokens=60)
        proc_b, url_b = _spawn_fake_replica(f"{tag}-b", chunk_delay=0.05,
                                            tokens=60)
        rcfg = RouterConfig(
            replicas=[(f"{tag}-a", url_a), (f"{tag}-b", url_b)],
            ready_interval=0.25, retries=1, timeout=20.0,
            breaker_threshold=3, breaker_cooldown=0.5,
            migrate_on_rotation=False)
        router_app = create_router_app(rcfg)
        return (proc_a, url_a), (proc_b, url_b), rcfg, router_app

    def keyed_to(target: str, mgr, rcfg, *, salt: str = "") -> dict:
        for i in range(200):
            msgs = [{"role": "user",
                     "content": f"resume{salt} conversation {i}: "
                                "please answer at length"}]
            key = aff.conversation_key({"messages": msgs},
                                       rcfg.affinity_chunk)
            if mgr.ring.primary(key) == target:
                return {"model": "m", "messages": msgs,
                        "stream": True, "max_tokens": 60}
        raise RuntimeError(f"no key found for {target}")

    # ---- arm 1: SIGKILL mid-stream -> token-exact resume on survivor ----
    procs = []
    try:
        (proc_a, _), (proc_b, _), rcfg, router_app = await cluster("res")
        procs += [proc_a, proc_b]
        mgr = router_app.state["replica_set"]
        transport = httpx.ASGITransport(app=router_app)
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://router",
                                     timeout=60.0) as rc:
            body = keyed_to("res-a", mgr, rcfg)
            base = await asyncio.wait_for(consume(rc, body), timeout=30.0)
            check("resume: uninterrupted baseline streams clean",
                  base["done"] and base["error_chunks"] == 0
                  and len(base["text"]) > 0, f"{base['status']}")
            resumed_before = ROUTER_STREAM_RESUMES.value_of(
                outcome="resumed")
            task = asyncio.create_task(consume(rc, body))
            await asyncio.sleep(0.6)  # well mid-stream (60 x 50ms)
            proc_a.kill()
            proc_a.wait()
            got = await asyncio.wait_for(task, timeout=30.0)
            check("resume: killed stream finishes token-exact on survivor",
                  got["text"] == base["text"] and got["done"]
                  and got["error_chunks"] == 0,
                  f"len={len(got['text'])}/{len(base['text'])} "
                  f"errors={got['error_chunks']}")
            check("resume: one role chunk, one chunk identity, no "
                  "duplicate frames",
                  got["roles"] == 1 and len(got["ids"]) == 1
                  and "".join(got["frames"]) == got["text"])
            check("resume: outcome counted and recorder-evented",
                  ROUTER_STREAM_RESUMES.value_of(outcome="resumed")
                  == resumed_before + 1
                  and "router-stream-resume"
                  in json.dumps(RECORDER.snapshot()))
            await mgr.aclose()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    # ---- arm 2: divergence + router.resume fault -> error-chunk degrade -
    procs = []
    try:
        (proc_a, url_a), (proc_b, url_b), rcfg, router_app = \
            await cluster("div")
        procs += [proc_a, proc_b]
        mgr = router_app.state["replica_set"]
        transport = httpx.ASGITransport(app=router_app)
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://router",
                                     timeout=60.0) as rc, \
                httpx.AsyncClient(timeout=10.0) as direct:
            body = keyed_to("div-a", mgr, rcfg)
            base = await asyncio.wait_for(consume(rc, body), timeout=30.0)
            # every replica's replay guard refuses the journal
            for url in (url_a, url_b):
                await direct.post(f"{url}/admin/diverge")
            await direct.post(f"{url_a}/admin/abort?after=2")
            divergence_before = ROUTER_STREAM_RESUMES.value_of(
                outcome="divergence")
            got = await asyncio.wait_for(consume(rc, body), timeout=30.0)
            check("resume divergence: degrades to the error-chunk "
                  "contract, no duplicate frames",
                  got["error_chunks"] == 1 and got["done"]
                  and "diverged" in got["error_text"]
                  and base["text"].startswith(got["text"])
                  and got["text"] != base["text"],
                  f"errors={got['error_chunks']} "
                  f"text={got['text'][:40]!r}")
            check("resume divergence: outcome counted",
                  ROUTER_STREAM_RESUMES.value_of(outcome="divergence")
                  == divergence_before + 1)
            # fault injection AT the resume site: the single sibling's
            # attempt burns, candidates exhaust, same degrade contract
            await direct.post(f"{url_b}/admin/diverge?off=1")
            await direct.post(f"{url_a}/admin/diverge?off=1")
            await direct.post(f"{url_a}/admin/abort?after=2")
            fired_before = faults.fired("router.resume")
            faults.arm("router.resume", times=1)
            try:
                got = await asyncio.wait_for(consume(rc, body),
                                             timeout=30.0)
            finally:
                faults.disarm()
            check("resume fault site: router.resume fired and degraded "
                  "cleanly",
                  faults.fired("router.resume") == fired_before + 1
                  and got["error_chunks"] == 1 and got["done"]
                  and base["text"].startswith(got["text"]),
                  f"fired={faults.fired('router.resume')} "
                  f"errors={got['error_chunks']}")
            await mgr.aclose()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    # ---- arm 3: graceful drain of 1-of-2 under live traffic ------------
    procs = []
    try:
        (proc_a, url_a), (proc_b, url_b), rcfg, router_app = \
            await cluster("drn")
        procs += [proc_a, proc_b]
        mgr = router_app.state["replica_set"]
        transport = httpx.ASGITransport(app=router_app)
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://router",
                                     timeout=60.0) as rc, \
                httpx.AsyncClient(timeout=10.0) as direct:
            body_a = keyed_to("drn-a", mgr, rcfg)
            body_b = keyed_to("drn-b", mgr, rcfg, salt="x")
            base_a = await asyncio.wait_for(consume(rc, body_a),
                                            timeout=30.0)
            base_b = await asyncio.wait_for(consume(rc, body_b),
                                            timeout=30.0)
            stream_a = asyncio.create_task(consume(rc, body_a))
            stream_b = asyncio.create_task(consume(rc, body_b))
            await asyncio.sleep(0.6)  # both streams live
            r = await rc.post("/router/drain?replica=drn-a")
            report = r.json()
            # live traffic THROUGH the drain window: all must complete
            extra = await asyncio.wait_for(asyncio.gather(
                *(rc.post("/chat/completions",
                          json={"model": "m", "max_tokens": 4,
                                "messages": [{"role": "user",
                                              "content": f"drain load "
                                                         f"{i}"}]})
                  for i in range(4))), timeout=20.0)
            got_a = await asyncio.wait_for(stream_a, timeout=30.0)
            got_b = await asyncio.wait_for(stream_b, timeout=30.0)
            check("drain: reported drained with zero residents",
                  r.status_code == 200 and report.get("drained") is True
                  and report.get("resident") == 0, f"{report}")
            check("drain: parked stream resumed token-exact — zero loss",
                  got_a["text"] == base_a["text"] and got_a["done"]
                  and got_a["error_chunks"] == 0,
                  f"len={len(got_a['text'])}/{len(base_a['text'])}")
            check("drain: survivor stream untouched",
                  got_b["text"] == base_b["text"] and got_b["done"]
                  and got_b["error_chunks"] == 0)
            check("drain: zero failed requests under live traffic",
                  all(x.status_code == 200 for x in extra)
                  and all(x.headers.get("x-routed-to") == "drn-b"
                          for x in extra),
                  f"statuses={[x.status_code for x in extra]}")
            check("drain: replica out of the ring, drain on the recorder",
                  "drn-a" not in mgr.ring
                  and "router-drain" in json.dumps(RECORDER.snapshot()))
            # undrain + recovery: the replica rejoins on a /ready tick
            await direct.post(f"{url_a}/admin/undrain")
            deadline = time.time() + 5.0
            while time.time() < deadline and "drn-a" not in mgr.ring:
                await asyncio.sleep(0.1)
            check("drain: undrained replica rejoins the ring",
                  "drn-a" in mgr.ring, f"ring={sorted(mgr.ring.members)}")
            await mgr.aclose()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


async def _quorum_member_kill_drill(check) -> None:
    """Phase 10 body (docs/quorum.md): a ``quorum=3`` fan-out loses one
    member to SIGKILL mid-generation. With a spare cell in the ring the
    member finishes token-exact elsewhere and the quorum stays FULL;
    with no spare the member is dropped and the request is SERVED from
    the survivors plus the dead member's partial answer — degraded,
    never failed, no error chunk."""
    import httpx

    from quorum_tpu.observability import QUORUM_DEGRADED, QUORUM_REQUESTS
    from quorum_tpu.router.app import RouterConfig, create_router_app

    sep = "\n\n---\n\n"  # RouterConfig.quorum_separator default
    body = {"model": "m", "stream": True, "quorum": 3, "max_tokens": 60,
            "messages": [{"role": "user", "content":
                          "quorum chaos drill: answer at length"}]}

    async def consume(rc) -> dict:
        out = {"streams": {}, "final": None, "errors": 0, "done": False,
               "assigned": [], "status": 0}
        async with rc.stream("POST", "/chat/completions",
                             json=body) as resp:
            out["status"] = resp.status_code
            out["assigned"] = (resp.headers.get("x-quorum-replicas")
                               or "").split(",")
            async for line in resp.aiter_lines():
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data.strip() == "[DONE]":
                    out["done"] = True
                    continue
                ev = json.loads(data)
                choice = (ev.get("choices") or [{}])[0]
                delta = choice.get("delta") or {}
                if (ev.get("id") == "error"
                        or choice.get("finish_reason") == "error"):
                    out["errors"] += 1
                elif ev.get("id") == "chatcmpl-parallel-final":
                    out["final"] = delta.get("content") or ""
                elif delta.get("content"):
                    out["streams"].setdefault(ev.get("id"), "")
                    out["streams"][ev.get("id")] += delta["content"]
        return out

    async def cluster(tag: str, n: int):
        pairs = [_spawn_fake_replica(f"{tag}{i}", chunk_delay=0.05,
                                     tokens=60) for i in range(n)]
        rcfg = RouterConfig(
            replicas=[(f"{tag}{i}", url)
                      for i, (_, url) in enumerate(pairs)],
            ready_interval=0.25, retries=1, timeout=30.0,
            breaker_threshold=3, breaker_cooldown=0.5,
            migrate_on_rotation=False)
        return [p for p, _ in pairs], create_router_app(rcfg)

    async def arm(tag: str, n: int, drill) -> None:
        procs, router_app = await cluster(tag, n)
        mgr = router_app.state["replica_set"]
        try:
            transport = httpx.ASGITransport(app=router_app)
            async with httpx.AsyncClient(transport=transport,
                                         base_url="http://router",
                                         timeout=60.0) as rc:
                await drill(rc, procs)
            await mgr.aclose()
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()

    # ---- arm 1: kill with a spare -> token-exact resume, quorum FULL ----
    async def with_spare(rc, procs):
        base = await asyncio.wait_for(consume(rc), timeout=30.0)
        texts = set(base["streams"].values())
        check("quorum: uninterrupted 3-member fan-out combines clean",
              base["done"] and base["errors"] == 0
              and len(base["streams"]) == 3 and len(texts) == 1
              and base["final"] == sep.join([texts.pop()] * 3),
              f"status={base['status']} members={len(base['streams'])}")
        degraded_before = QUORUM_DEGRADED.value
        full_before = QUORUM_REQUESTS.value_of(outcome="full")
        task = asyncio.create_task(consume(rc))
        await asyncio.sleep(0.6)  # well mid-stream (60 x 50ms chunks)
        victim = procs[int(base["assigned"][0].removeprefix("qs"))]
        victim.kill()
        victim.wait()
        got = await asyncio.wait_for(task, timeout=30.0)
        check("quorum: killed member finishes token-exact on the spare "
              "(quorum stays full)",
              got["done"] and got["errors"] == 0
              and got["final"] == base["final"],
              f"errors={got['errors']} "
              f"len={len(got['final'] or '')}/{len(base['final'] or '')}")
        check("quorum: spare-covered kill counts full, not degraded",
              QUORUM_REQUESTS.value_of(outcome="full") == full_before + 1
              and QUORUM_DEGRADED.value == degraded_before)

    await arm("qs", 4, with_spare)

    # ---- arm 2: kill with NO spare -> served degraded, never failed -----
    async def no_spare(rc, procs):
        base = await asyncio.wait_for(consume(rc), timeout=30.0)
        t = next(iter(base["streams"].values()))
        broken_before = QUORUM_DEGRADED.value_of(reason="stream_broken")
        degr_before = QUORUM_REQUESTS.value_of(outcome="degraded")
        failed_before = QUORUM_REQUESTS.value_of(outcome="failed")
        task = asyncio.create_task(consume(rc))
        await asyncio.sleep(0.6)
        victim = procs[int(base["assigned"][0].removeprefix("qn"))]
        victim.kill()
        victim.wait()
        got = await asyncio.wait_for(task, timeout=30.0)
        pieces = (got["final"] or "").split(sep)
        partials = [p for p in pieces if p != t]
        check("quorum: member death with no spare serves the survivors "
              "(no error chunk, partial answer joins the combine)",
              got["done"] and got["errors"] == 0 and len(pieces) == 3
              and pieces.count(t) == 2 and len(partials) == 1
              and partials[0] and t.startswith(partials[0]),
              f"errors={got['errors']} pieces={len(pieces)}")
        check("quorum: the loss is counted degraded, never failed",
              QUORUM_DEGRADED.value_of(reason="stream_broken")
              == broken_before + 1
              and QUORUM_REQUESTS.value_of(outcome="degraded")
              == degr_before + 1
              and QUORUM_REQUESTS.value_of(outcome="failed")
              == failed_before)

    await arm("qn", 3, no_spare)


def _config() -> dict:
    return {
        "settings": {"timeout": 30},
        "primary_backends": [{
            "name": "T",
            # prefill_chunk=32: the templated short prompt (~19 tokens)
            # single-shot admits (the engine.admit site), the 30-word one
            # (~170 tokens) rides chunked prefill (engine.prefill_segment).
            # d_model=128 keeps warm decode measurably slow (~tens of ms
            # per token on CPU) so the deadline scenarios actually catch
            # requests mid-flight instead of racing a finished generation.
            "url": ("tpu://llama-tiny?d_model=128&max_seq=256"
                    "&slots=2&queue=8&decode_chunk=4"
                    "&prefill_chunk=32&prefix_store=host"
                    "&prefix_store_chunk=32&max_tokens=8"),
            "model": "chaos",
        }],
    }


async def _run(quick: bool) -> None:
    import httpx

    from quorum_tpu import faults
    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app

    app = create_app(Config(raw=_config()), watch_config=False)
    backend = app.state["registry"].get("T")
    engine = backend.engine
    transport = httpx.ASGITransport(app=app)
    auth = {"Authorization": "Bearer chaos"}

    async with httpx.AsyncClient(transport=transport,
                                 base_url="http://chaos") as client:

        # The long-running deadline scenarios must actually run long: a
        # random-init model's greedy stream can sample EOS at any step, so
        # they bias it out (an ordinary OpenAI logit_bias knob).
        no_eos = {str(backend.tokenizer.eos_id): -100}

        async def chat(content: str = "hello", *, max_tokens: int = 8,
                       temperature: float = 0.0, seed: int = 0,
                       timeout: float | None = None,
                       ban_eos: bool = False) -> httpx.Response:
            body: dict = {
                "model": "chaos", "max_tokens": max_tokens,
                "temperature": temperature, "seed": seed,
                "messages": [{"role": "user", "content": content}],
            }
            if timeout is not None:
                body["timeout"] = timeout
            if ban_eos:
                body["logit_bias"] = no_eos
            return await client.post("/v1/chat/completions", json=body,
                                     headers=auth)

        def text(r: httpx.Response) -> str:
            return r.json()["choices"][0]["message"]["content"]

        # ---- phase 0: baseline (compiles programs, pins outputs) ---------
        print("phase 0: baseline", flush=True)
        greedy0 = text(await chat(seed=1))
        sampled0 = text(await chat(temperature=0.9, seed=7))
        check("baseline greedy nonempty", isinstance(greedy0, str))
        # Warm every decode history bucket (one full-budget generation):
        # first-use XLA compiles block the scheduler for seconds, and the
        # deadline phases below assert ~sub-second sweep latencies.
        await chat("warmup", max_tokens=235, ban_eos=True)

        # ---- phase 1: one fault per engine site under concurrent load ----
        long_prompt = "word " * 30  # > prefill_chunk tokens: chunked path
        sites = [("engine.admit", "hi"),
                 ("engine.prefill_segment", long_prompt),
                 ("engine.decode", "hi")]
        if quick:
            sites = sites[:1]
        for site, prompt in sites:
            print(f"phase 1: inject {site}", flush=True)
            faults.reset_counts()
            faults.arm(site, times=1)
            burst = await asyncio.gather(
                *(chat(prompt if i == 0 else "bystander", seed=i)
                  for i in range(4)))
            faults.disarm()
            statuses = [r.status_code for r in burst]
            check(f"{site}: fault fired", faults.fired(site) >= 1)
            check(f"{site}: at least one request failed",
                  any(s >= 500 for s in statuses), f"statuses={statuses}")
            check(f"{site}: not every request failed (bounded blast radius)",
                  any(s == 200 for s in statuses), f"statuses={statuses}")
            follow = await chat(seed=1)
            check(f"{site}: next request succeeds",
                  follow.status_code == 200 and text(follow) == greedy0,
                  f"status={follow.status_code}")
            _flight_dump_check(site, site)

        # snapshot worker: a fault there may cost one snapshot, never a
        # request or the worker thread.
        print("phase 1: inject engine.snapshot", flush=True)
        faults.arm("engine.snapshot", times=1)
        r = await chat("snapshot me " * 8, seed=3)
        engine.drain_prefix_store()
        faults.disarm()
        check("engine.snapshot: request unaffected", r.status_code == 200)
        check("engine.snapshot: worker survives",
              engine.health()["snapshot_worker_alive"])

        # ---- phase 2: deadlines ------------------------------------------
        # Latency injection (faults delay mode) makes each decode dispatch
        # stall 50ms: generation speed becomes a harness constant instead
        # of a property of the box, so the deadline windows are exact.
        print("phase 2: deadlines", flush=True)
        faults.arm("engine.decode", times=100000, delay=0.05)
        try:
            # Queue-stage shed: both slots blocked by slow generations
            # (~48 tokens x 12.5ms/token), the late request's 0.3s deadline
            # expires while it is still pending.
            blockers = [asyncio.create_task(
                chat("blocker", max_tokens=48, seed=10 + i, ban_eos=True))
                for i in range(2)]
            await asyncio.sleep(0.1)
            t0 = time.monotonic()
            shed = await chat("late", timeout=0.3, max_tokens=4)
            waited = time.monotonic() - t0
            await asyncio.gather(*blockers)
            check("deadline(queue): shed with 503",
                  shed.status_code == 503, f"status={shed.status_code}")
            check("deadline(queue): Retry-After present",
                  "retry-after" in {k.lower() for k in shed.headers})
            check("deadline(queue): answered within deadline + slack",
                  waited <= 0.3 + DEADLINE_SLACK_S, f"waited={waited:.2f}s")
            if not quick:
                # Decode-stage: admitted, then cancelled mid-generation ->
                # 504, and the slot is free for the follow-up.
                t0 = time.monotonic()
                late = await chat("slow", timeout=0.3, max_tokens=100,
                                  ban_eos=True)
                waited = time.monotonic() - t0
                check("deadline(decode): 504", late.status_code == 504,
                      f"status={late.status_code}")
                check("deadline(decode): within deadline + slack",
                      waited <= 0.3 + DEADLINE_SLACK_S,
                      f"waited={waited:.2f}s")
        finally:
            faults.disarm("engine.decode")
        if not quick:
            follow = await chat(seed=1)
            check("deadline(decode): slot released, next request ok",
                  follow.status_code == 200 and text(follow) == greedy0)

        # ---- phase 3: breaker under a failure storm ----------------------
        print("phase 3: breaker", flush=True)
        engine.breaker.threshold = 2
        engine.breaker.window = 60.0
        engine.breaker.cooldown = 0.5
        for i in range(2):
            faults.arm("engine.decode", times=1)
            await chat("poison", seed=20 + i)
            faults.disarm()
        check("breaker: open after failure storm",
              engine.breaker.state == "open", engine.breaker.state)
        rejected = await chat("during-open")
        check("breaker: rejects with 503", rejected.status_code == 503,
              f"status={rejected.status_code}")
        check("breaker: 503 carries Retry-After",
              "retry-after" in {k.lower() for k in rejected.headers})
        health = (await client.get("/health")).json()
        check("health: degraded while breaker open",
              health["status"] == "degraded", health["status"])
        ready = await client.get("/ready")
        check("ready: 503 while breaker open", ready.status_code == 503)
        await asyncio.sleep(0.6)
        probe = await chat(seed=1)
        check("breaker: cooldown probe succeeds and closes it",
              probe.status_code == 200 and engine.breaker.state == "closed",
              f"status={probe.status_code} state={engine.breaker.state}")
        health = (await client.get("/health")).json()
        check("health: healthy after recovery",
              health["status"] == "healthy", health["status"])

        # ---- phase 4: fault-free path is untouched -----------------------
        print("phase 4: disarmed pinning", flush=True)
        faults.disarm()
        check("no site left armed", not faults.armed())
        greedy1 = text(await chat(seed=1))
        sampled1 = text(await chat(temperature=0.9, seed=7))
        check("greedy output pinned across chaos", greedy1 == greedy0)
        check("sampled output pinned across chaos", sampled1 == sampled0)

        # ---- phase 4b: disagg KV-handoff fault site under load -----------
        # A small disaggregated (1+1 device group) engine beside the main
        # colocated one: the prefill→decode handoff fails for ONE
        # admission while a streaming request decodes and a bystander
        # admission queues — only the faulted request dies, the stream and
        # bystander complete unchanged, no requeue storm, no rebuild, and
        # both group loops stay alive (docs/tpu_backends.md).
        if not quick:
            print("phase 4b: disagg kv handoff", flush=True)
            from quorum_tpu.engine.engine import InferenceEngine
            from quorum_tpu.models.model_config import resolve_spec
            from quorum_tpu.ops.sampling import SamplerConfig
            from quorum_tpu.parallel.mesh import disagg_meshes

            pm, dm = disagg_meshes(1, 1)
            tiny = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
            deng = InferenceEngine(
                tiny, dm, prefill_mesh=pm, decode_chunk=4, n_slots=2,
                prefill_chunk=16, seed=77)
            samp = SamplerConfig(temperature=0.0)
            base = deng.generate([3, 4, 5], max_new_tokens=6,
                                 sampler=samp).token_ids
            streamer = deng.submit([9, 8, 7], max_new_tokens=24,
                                   sampler=samp)
            stream_it = deng.stream_results(streamer)
            # The streamer must be past its OWN admission handoff before
            # the fault arms (times=1 must hit the victim's handoff, not
            # the stream's): its first token proves it is decoding.
            stream_toks = [next(stream_it)]
            faults.reset_counts()
            faults.arm("engine.kv_handoff", times=1)
            bad = deng.submit([5, 6, 7], max_new_tokens=6, sampler=samp)
            bystander = deng.submit([3, 4, 5], max_new_tokens=6,
                                    sampler=samp)
            err = None
            try:
                list(deng.stream_results(bad))
            except Exception as e:
                err = e
            by_toks = list(deng.stream_results(bystander))
            stream_toks += list(stream_it)
            faults.disarm()
            check("kv_handoff: fault fired",
                  faults.fired("engine.kv_handoff") >= 1)
            check("kv_handoff: failed handoff dooms its own request",
                  isinstance(err, faults.FaultInjected), repr(err))
            check("kv_handoff: queued bystander completes unchanged",
                  by_toks == base, f"{by_toks} != {base}")
            check("kv_handoff: concurrent stream unaffected",
                  len(stream_toks) == 24, f"len={len(stream_toks)}")
            follow = deng.generate([3, 4, 5], max_new_tokens=6,
                                   sampler=samp).token_ids
            check("kv_handoff: follow-up matches baseline", follow == base)
            check("kv_handoff: no device-state rebuild",
                  deng.n_rebuilds == 0, f"rebuilds={deng.n_rebuilds}")
            dh = deng.health()
            check("kv_handoff: both group loops alive",
                  dh["scheduler_alive"] and dh["prefill_scheduler_alive"])
            check("kv_handoff: KV crossed the group boundary",
                  deng.kv_handoff_bytes > 0)
            _flight_dump_check("kv_handoff", "engine.kv_handoff")
            deng.shutdown()

            # ---- phase 4b2: same drill under the PAGED decode cache ------
            # kv_pages=1 reshapes the handoff's decode side: the staged
            # admission pre-reserves the row's page span on the prefill
            # thread and the decode loop uploads the table before the hput
            # scatter. A failed handoff must unwind the page CLAIM too —
            # a leaked claim would strand pool pages until restart (the
            # bystander/follow-up checks would then shed or hang).
            print("phase 4b2: disagg kv handoff (paged)", flush=True)
            peng = InferenceEngine(
                tiny, dm, prefill_mesh=pm, decode_chunk=4, n_slots=2,
                prefill_chunk=16, seed=77, kv_pages=True)
            pbase = peng.generate([3, 4, 5], max_new_tokens=6,
                                  sampler=samp).token_ids
            check("paged handoff: disagg output matches dense twin",
                  pbase == base, f"{pbase} != {base}")
            faults.reset_counts()
            faults.arm("engine.kv_handoff", times=1)
            bad = peng.submit([5, 6, 7], max_new_tokens=6, sampler=samp)
            err = None
            try:
                list(peng.stream_results(bad))
            except Exception as e:
                err = e
            faults.disarm()
            check("paged handoff: fault fired",
                  faults.fired("engine.kv_handoff") >= 1)
            check("paged handoff: failed handoff dooms its own request",
                  isinstance(err, faults.FaultInjected), repr(err))
            follow = peng.generate([3, 4, 5], max_new_tokens=6,
                                   sampler=samp).token_ids
            check("paged handoff: follow-up matches baseline",
                  follow == base, f"{follow} != {base}")
            with peng._cond:
                leaked = [i for i, c in enumerate(peng._page_claims) if c]
            check("paged handoff: no leaked page claims", not leaked,
                  f"slot groups with live claims: {leaked}")
            pm_ = peng.metrics()
            check("paged handoff: pool accounting consistent",
                  pm_["kv_pages_allocated"] + pm_["kv_pages_free"]
                  == peng.kv_pool_pages)
            peng.shutdown()

        # ---- phase 4c: speculative verify fault site ---------------------
        # A spec_decode engine beside the main one (the main engine's
        # deadline/breaker phases count on engine.decode dispatches, so it
        # stays spec-free): a failed verify dispatch dooms only its own
        # turn's rows — the queued bystander keeps its place and admits,
        # NO device-state rebuild happens (the ring's chained state was
        # never consumed), and the engine keeps serving identically. The
        # logit_bias-forced periodic stream makes prompt-lookup drafting
        # (and therefore the engine.verify site) fire deterministically.
        if not quick:
            print("phase 4c: speculative verify", flush=True)
            import numpy as np

            from quorum_tpu.engine.engine import InferenceEngine
            from quorum_tpu.models.model_config import resolve_spec
            from quorum_tpu.ops.sampling import SamplerConfig

            tiny = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
            seng = InferenceEngine(tiny, decode_chunk=4, n_slots=1,
                                   decode_pipeline=2, spec_decode=4,
                                   seed=78)
            samp = SamplerConfig(temperature=0.0)
            sbias = np.zeros((tiny.vocab_size,), np.float32)
            sbias[7] = 1e9

            def srun(n=12):
                req = seng.submit([7, 7, 7, 7], max_new_tokens=n,
                                  sampler=samp, logit_bias=sbias)
                return list(seng.stream_results(req))

            sbase = srun()
            check("verify: workload speculates", seng.n_spec_turns > 0,
                  f"turns={seng.n_spec_turns}")
            faults.reset_counts()
            faults.arm("engine.verify", times=1)
            bad = seng.submit([7, 7, 7, 7], max_new_tokens=12,
                              sampler=samp, logit_bias=sbias)
            bystander = seng.submit([7, 7, 7, 7], max_new_tokens=12,
                                    sampler=samp, logit_bias=sbias)
            err = None
            try:
                list(seng.stream_results(bad))
            except Exception as e:
                err = e
            by_toks = list(seng.stream_results(bystander))
            faults.disarm()
            check("verify: fault fired",
                  faults.fired("engine.verify") >= 1)
            check("verify: failed dispatch dooms its own turn's rows",
                  isinstance(err, faults.FaultInjected), repr(err))
            check("verify: queued bystander completes unchanged",
                  by_toks == sbase, f"{by_toks} != {sbase}")
            check("verify: no device-state rebuild (ring never doomed)",
                  seng.n_rebuilds == 0, f"rebuilds={seng.n_rebuilds}")
            check("verify: follow-up matches baseline", srun() == sbase)
            _flight_dump_check("verify", "engine.verify")
            seng.shutdown()

        # ---- phase 4d: zero-drain injection-path faults ------------------
        # A colocated zero_drain=1 engine (ISSUE 11): an engine.admit or
        # engine.prefill_segment failure while the decode ring is full
        # dooms ONLY the injecting request — never an in-flight megachunk
        # or the queued bystander, with no device-state rebuild (staging
        # is the blast-radius boundary, exactly like a disagg prefill
        # fault) and zero admission stall throughout (the ring never
        # clamps for an admission under zero_drain).
        if not quick:
            print("phase 4d: zero-drain injection", flush=True)
            from quorum_tpu.engine.engine import InferenceEngine
            from quorum_tpu.models.model_config import resolve_spec
            from quorum_tpu.ops.sampling import SamplerConfig

            tiny = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
            zeng = InferenceEngine(
                tiny, decode_chunk=4, n_slots=2, decode_pipeline=4,
                decode_loop=2, prefill_chunk=16, zero_drain=True, seed=81)
            samp = SamplerConfig(temperature=0.0)
            zbase = zeng.generate([3, 4, 5], max_new_tokens=6,
                                  sampler=samp).token_ids
            long_ids = [(7 + 3 * i) % tiny.vocab_size for i in range(40)]
            zeng.generate(long_ids, max_new_tokens=2, sampler=samp)
            for site in ("engine.admit", "engine.prefill_segment"):
                # Budget past the ring's K*C*chunk capacity: a stream
                # that fits one ring fill would finish before the
                # injection faults even land.
                streamer = zeng.submit([9, 8, 7], max_new_tokens=48,
                                       sampler=samp)
                stream_it = zeng.stream_results(streamer)
                # The streamer must be decoding (its own injection done)
                # before the fault arms — times=1 must hit the victim.
                stream_toks = [next(stream_it)]
                faults.reset_counts()
                faults.arm(site, times=1)
                bad = zeng.submit(long_ids, max_new_tokens=6, sampler=samp)
                bystander = zeng.submit([3, 4, 5], max_new_tokens=6,
                                        sampler=samp)
                err = None
                try:
                    list(zeng.stream_results(bad))
                except Exception as e:
                    err = e
                by_toks = list(zeng.stream_results(bystander))
                stream_toks += list(stream_it)
                faults.disarm()
                check(f"zero-drain {site}: fault fired",
                      faults.fired(site) >= 1)
                check(f"zero-drain {site}: dooms only the injecting "
                      "request", isinstance(err, faults.FaultInjected),
                      repr(err))
                check(f"zero-drain {site}: queued bystander completes "
                      "unchanged", by_toks == zbase,
                      f"{by_toks} != {zbase}")
                check(f"zero-drain {site}: concurrent stream unaffected",
                      len(stream_toks) == 48, f"len={len(stream_toks)}")
                check(f"zero-drain {site}: no device-state rebuild",
                      zeng.n_rebuilds == 0, f"rebuilds={zeng.n_rebuilds}")
                _flight_dump_check(f"zero-drain {site}", site)
            follow = zeng.generate([3, 4, 5], max_new_tokens=6,
                                   sampler=samp).token_ids
            check("zero-drain: follow-up matches baseline",
                  follow == zbase)
            check("zero-drain: ring never clamped for admission",
                  zeng.admission_stall_s == 0.0,
                  f"stall={zeng.admission_stall_s}")
            check("zero-drain: injections overlapped live work",
                  zeng.n_admission_overlap >= 1,
                  f"overlap={zeng.n_admission_overlap}")
            check("zero-drain: scheduler alive",
                  zeng.health()["scheduler_alive"])
            zeng.shutdown()

        # ---- phase 5: HTTP backend retry ladder --------------------------
        print("phase 5: http retry", flush=True)
        from quorum_tpu.backends.http_backend import HttpBackend
        from quorum_tpu.observability import BACKEND_RETRIES

        calls = {"n": 0}

        def flaky(req: httpx.Request) -> httpx.Response:
            calls["n"] += 1
            if calls["n"] <= 2:
                return httpx.Response(500, json={"error": {
                    "message": "transient", "type": "server_error"}})
            return httpx.Response(200, json={
                "choices": [{"message": {"role": "assistant",
                                         "content": "ok"}}]})

        hb = HttpBackend(
            "flaky", "http://upstream.test/v1", "m", retries=3,
            client=httpx.AsyncClient(transport=httpx.MockTransport(flaky)))
        before = BACKEND_RETRIES.value_of(backend="flaky")
        result = await hb.complete({"messages": []}, auth, 10.0)
        check("http retry: transient 5xx recovered",
              result.status_code == 200 and calls["n"] == 3,
              f"status={result.status_code} calls={calls['n']}")
        check("http retry: backend_retries_total advanced",
              BACKEND_RETRIES.value_of(backend="flaky") == before + 2)
        # Injected connect-level fault at the http.request site retries too.
        faults.arm("http.request", times=1)
        result = await hb.complete({"messages": []}, auth, 10.0)
        faults.disarm()
        check("http retry: injected transport fault recovered",
              result.status_code == 200)
        await hb.aclose()

        # ---- phase 6: router replica-kill drill --------------------------
        # The multi-replica tier's containment contract (docs/scaling.md):
        # SIGKILL one replica under load — the survivor's in-flight stream
        # is untouched, requests keyed to the dead replica fail over and
        # complete elsewhere within their deadlines, the /ready poller
        # rotates the corpse out of the ring, and with EVERY replica dead
        # the router sheds 503 + Retry-After instead of hanging. Fake
        # (jax-free, killable) replica processes keep the drill about the
        # ROUTER's behavior, not engine boot time.
        if not quick:
            print("phase 6: router replica-kill", flush=True)
            await _router_kill_drill(check)

        # ---- phase 7: fleet trace continuity through failover ------------
        # One W3C trace-id across three processes (docs/observability.md
        # "Fleet plane"): kill a replica mid-stream, fail a request over,
        # and find its trace-id in the router's timeline, the survivor's
        # flight recorder, and the merged /debug/fleet/timeline.
        if not quick:
            print("phase 7: fleet trace continuity", flush=True)
            await _fleet_trace_drill(check)

        # ---- phase 8: QoS preemption under fault -------------------------
        # The qos=1 scheduler's contract (docs/scheduling.md): a
        # mid-decode park is token-exact for the victim, admits the
        # beneficiary, and a fault AT the park point (engine.preempt)
        # dooms only the victim with page accounting exact afterwards.
        if not quick:
            print("phase 8: qos preemption", flush=True)
            await _qos_preemption_drill(check)

        # ---- phase 9: zero-loss streams (resume + drain) -----------------
        # ISSUE 19's acceptance drill: SIGKILL mid-stream with resume ON
        # -> the client-visible sequence is identical to an uninterrupted
        # run; a refusing replay guard (and a fault at router.resume)
        # degrades to the phase-6 error-chunk contract with no duplicate
        # frames; draining 1-of-2 replicas under live traffic fails zero
        # requests and proactively resumes the parked stream.
        if not quick:
            print("phase 9: zero-loss stream resume + drain", flush=True)
            await _stream_resume_drill(check)

        # ---- phase 10: quorum member-kill degradation --------------------
        # Native quorum serving's containment contract (docs/quorum.md):
        # SIGKILL one member of a quorum=3 fan-out mid-generation. With a
        # spare cell the member resumes token-exact and the quorum stays
        # full; with no spare the request is served from the survivors
        # (plus the dead member's partial answer) — degraded on the
        # counters, never failed, never an error chunk.
        if not quick:
            print("phase 10: quorum member-kill", flush=True)
            await _quorum_member_kill_drill(check)

    from quorum_tpu.engine.engine import shutdown_all_engines

    shutdown_all_engines()


def run(quick: bool = False) -> dict:
    """Entry point shared with the tests/test_robustness.py smoke: run the
    sweep, return {"passed": n, "failed": n, "failures": [names]}."""
    _CHECKS.clear()
    # Flight-recorder dumps land in a fresh sweep-local dir (not the
    # serving logs/), un-rate-limited so every containment phase leaves
    # its own artifact for _flight_dump_check. The env override is
    # restored afterwards: the tests/test_robustness.py smoke calls run()
    # inside the pytest process, and later tests' dumps must keep their
    # own dir/rate-limit.
    saved = {k: os.environ.get(k) for k in
             ("QUORUM_TPU_FLIGHT_DIR", "QUORUM_TPU_FLIGHT_DUMP_INTERVAL")}
    os.environ["QUORUM_TPU_FLIGHT_DIR"] = tempfile.mkdtemp(
        prefix="chaos-flightrec-")
    os.environ["QUORUM_TPU_FLIGHT_DUMP_INTERVAL"] = "0"
    try:
        asyncio.run(asyncio.wait_for(_run(quick), SCRIPT_TIMEOUT_S))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    failures = [name for name, ok, _ in _CHECKS if not ok]
    return {"passed": sum(1 for _, ok, _ in _CHECKS if ok),
            "failed": len(failures), "failures": failures}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--quick", action="store_true",
                   help="reduced sweep (one site, queue deadline only)")
    args = p.parse_args()
    t0 = time.time()
    try:
        out = run(quick=args.quick)
    except asyncio.TimeoutError:
        print(json.dumps({"error": "chaos sweep hung past watchdog"}),
              flush=True)
        return 2
    out["seconds"] = round(time.time() - t0, 1)
    print(json.dumps(out), flush=True)
    return 0 if out["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
