"""Host-path microbench: what the decode-dispatch pipeline and the
megachunk decode loop buy on CPU.

Runs a tiny random-init engine (no checkpoint, no TPU) through the same
compiled serving programs the real chip runs — once per pipeline depth and
once with ``decode_loop=C`` megachunk fusion — and reports the dispatch
accounting the PR-1 counters expose:

  - ``dispatches_per_request``  decode dispatches the generation cost
                                (under decode_loop=C one dispatch covers up
                                to C chunks, so this drops ~C×)
  - ``syncs_per_request``       dispatches the host BLOCKED on (chunk
                                dispatched with an empty ring); the pipelined
                                remainder overlapped the host turnaround
  - ``overrun_tokens``          tokens produced but discarded (0 when rows
                                finish on device — EOS/budget at any depth)
  - ``drain_gap_ms_per_dispatch`` host time between a dispatch's payload
                                landing on host and its last token handed to
                                the consumer queues — the per-dispatch host
                                tax megachunking amortizes over C chunks
  - ``host_turnaround_share``   fraction of the K=1 wall time the deeper
                                pipeline hid (≈ turnaround/(turnaround +
                                chunk time) when fully hidden — PERF.md §2)

It additionally measures **prefill interference** (the disagg=P+D
acceptance number, docs/tpu_backends.md): the inter-token p50/p95/p99 gap
of one streaming request while admission churn runs concurrently, colocated
vs disaggregated — on the colocated engine every admission clamps the
decode ring and interleaves its prefill segments between decode chunks,
while the disagg engine prefills on its own device group and hands the KV
off device→device, so the streaming gaps stay flat.

The **qos leg** (`--only-qos`, docs/scheduling.md) is the scheduler's
A/B: interactive TTFT p50/p99 under a batch-churn backlog, FIFO vs
``qos=1`` (WFQ admission + mid-decode preemption), against an
uncontended solo floor, plus the batch-throughput cost and the
preemption/replay counters.

Usage:  python scripts/hostpath_bench.py [--tokens N] [--chunk C]
        [--depth K] [--loop C] [--skip-interference] [--skip-qos]
Prints one human-readable block and one machine-parsable JSON line.
``make hostpath-bench`` runs it; tests/test_hostpath_bench.py is the suite's
smoke over the same entry points.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time

# Runnable as `python scripts/hostpath_bench.py` from a checkout without
# `pip install -e`: the repo root (not scripts/) must be importable.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The interference legs need >= 2 virtual CPU devices (one per disagg
# group) and the sharded legs >= 4 (disagg=2+2&tp=2 vs colocated tp=4 at
# matched device count). Effective only before the first `import jax` —
# standalone runs; under pytest the suite conftest already forces an
# 8-device mesh.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()


def run(tokens: int = 64, chunk: int = 4, depth: int = 4,
        repeats: int = 3, loop: int = 4) -> dict:
    """Generate ``tokens`` greedily at decode_pipeline=1 and =``depth``
    (both unfused) plus decode_loop=``loop`` megachunks on fresh tiny
    engines; return the dispatch/sync/overrun/drain-gap accounting plus
    wall times (median of ``repeats`` after a compile warm-up)."""
    if depth < 2:
        # depth 1 IS the K=1 baseline leg — comparing it against itself
        # would report run-to-run noise as a pipeline win.
        raise ValueError("depth must be >= 2 (1 is the baseline leg)")
    if loop < 2:
        raise ValueError("loop must be >= 2 (1 is the unfused baseline)")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = MODEL_PRESETS["llama-tiny"]
    greedy = SamplerConfig(temperature=0.0)
    prompt = [5, 6, 7]
    out: dict = {"tokens": tokens, "decode_chunk": chunk, "depth": depth,
                 "loop": loop}
    streams: dict[str, list[int]] = {}

    # Legs: (tag, pipeline depth, decode_loop). The loop leg keeps the deep
    # ring — megachunks compose with pipelining (C chunks per in-flight
    # entry), and the acceptance number is dispatches/request at loop=C.
    legs = [("k1", 1, 1), (f"k{depth}", depth, 1),
            (f"loop{loop}", depth, loop)]
    for tag, k, c in legs:
        eng = InferenceEngine(spec, decode_chunk=chunk, decode_pipeline=k,
                              decode_loop=c)
        eng.generate(prompt, max_new_tokens=tokens, sampler=greedy)  # warm-up
        c0, o0, v0 = eng.n_decode_chunks, eng.n_overlapped, eng.n_overrun
        g0 = eng.drain_gap_s
        walls = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = eng.generate(prompt, max_new_tokens=tokens, sampler=greedy)
            walls.append(time.perf_counter() - t0)
        streams[tag] = res.token_ids
        dispatches = (eng.n_decode_chunks - c0) / repeats
        overlapped = (eng.n_overlapped - o0) / repeats
        out[f"{tag}_dispatches_per_request"] = dispatches
        out[f"{tag}_syncs_per_request"] = dispatches - overlapped
        out[f"{tag}_overrun_tokens"] = eng.n_overrun - v0
        out[f"{tag}_drain_gap_ms_per_dispatch"] = round(
            (eng.drain_gap_s - g0) / max(1.0, dispatches * repeats) * 1e3, 3)
        out[f"{tag}_wall_s"] = round(statistics.median(walls), 4)
        out[f"{tag}_tok_s"] = round(tokens / statistics.median(walls), 1)
        # Per-family device-seconds (ISSUE 12): the leg's dispatch time
        # attributed by compile-budget program family (p50/p99 from the
        # engine's LatencyModel reservoir) — an A/B arm's win is
        # attributable to the family that moved (the loop leg's time
        # lives under "loop", the unfused legs' under "plain").
        out[f"{tag}_device_seconds"] = eng.latency.snapshot()
        eng.shutdown()

    t1, tk = out["k1_wall_s"], out[f"k{depth}_wall_s"]
    # The wall time the deeper ring hid is host turnaround that K=1 spent
    # synchronized: its share of the K=1 request is the measured stand-in
    # for turnaround/(turnaround + chunk time).
    out["host_turnaround_share"] = round(max(0.0, t1 - tk) / t1, 3) if t1 else 0.0
    out["loop_dispatch_reduction"] = round(
        out["k1_dispatches_per_request"]
        / max(1e-9, out[f"loop{loop}_dispatches_per_request"]), 2)
    out["tokens_match"] = (streams["k1"] == streams[f"k{depth}"]
                           == streams[f"loop{loop}"])
    return out


def spec(tokens: int = 64, chunk: int = 4, depth: int = 4,
         g: int = 4) -> dict:
    """Speculative-decoding A/B (ISSUE 10): acceptance rate, tok/s and
    dispatches/request with spec_decode on vs off, on a repetitive leg and
    a CONSTRAINED repetitive leg, tokens asserted identical.

    The workload forces a periodic stream with ``logit_bias`` (greedy +
    one dominating token), so prompt-lookup drafting engages by
    construction and acceptance measures the verify machinery, not the
    random tiny model's self-repetition. The constrained leg runs the same
    stream under a wildcard regex grammar — the dfa-verify program variant
    with its table gathers and per-position draft-prefix masking — which
    before this ISSUE fell back to the plain chunked path. Verify turns
    are ring-resident: the ``*_spec_overlapped`` counters show dispatches
    issued onto a non-empty decode_pipeline ring."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    import numpy as np

    from quorum_tpu.constrain import compile_response_format
    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.engine.tokenizer import ByteTokenizer
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig

    mspec = MODEL_PRESETS["llama-tiny"]
    tok = ByteTokenizer(mspec.vocab_size)
    greedy = SamplerConfig(temperature=0.0)
    bias = np.zeros((mspec.vocab_size,), np.float32)
    bias[7] = 1e9  # period-1 stream: every prompt-lookup draft can accept
    wildcard = compile_response_format(
        {"type": "regex", "pattern": "[\\x00-\\xff]*"},
        tok, mspec.vocab_size)
    out: dict = {"spec_tokens": tokens, "spec_g": g}
    for leg, grammar in (("rep", None), ("crep", wildcard)):
        streams = {}
        for arm, sd in (("off", 0), ("on", g)):
            eng = InferenceEngine(mspec, decode_chunk=chunk,
                                  decode_pipeline=depth, spec_decode=sd)

            def one():
                req = eng.submit(
                    [7, 7, 7, 7], max_new_tokens=tokens, sampler=greedy,
                    seed=0, logit_bias=bias,
                    eos_id=tok.eos_id if grammar is not None else None,
                    grammar=grammar)
                return [t for t in eng.stream_results(req)]

            one()  # warm every program/bucket the measured pass dispatches
            c0, t0 = eng.n_decode_chunks, eng.n_spec_turns
            a0, d0, o0 = (eng.n_spec_accepted, eng.n_spec_drafted,
                          eng.n_spec_overlapped)
            w0 = time.perf_counter()
            streams[arm] = one()
            wall = time.perf_counter() - w0
            pre = f"spec_{leg}_{arm}"
            out[f"{pre}_tok_s"] = round(tokens / wall, 1)
            out[f"{pre}_dispatches_per_request"] = eng.n_decode_chunks - c0
            if sd:
                out[f"{pre}_acceptance"] = round(
                    (eng.n_spec_accepted - a0)
                    / max(1, eng.n_spec_drafted - d0), 3)
                out[f"{pre}_spec_turns"] = eng.n_spec_turns - t0
                out[f"{pre}_spec_overlapped"] = eng.n_spec_overlapped - o0
            # Per-family attribution: the spec-on arm's device time lives
            # under the verify/dfa_verify families, the off arm's under
            # plain/dfa — the A/B win is attributable by family.
            out[f"{pre}_device_seconds"] = eng.latency.snapshot()
            eng.shutdown()
        out[f"spec_{leg}_tokens_match"] = streams["off"] == streams["on"]
        out[f"spec_{leg}_speedup"] = round(
            out[f"spec_{leg}_on_tok_s"]
            / max(1e-9, out[f"spec_{leg}_off_tok_s"]), 2)
    return out


def interference(tokens: int = 64, chunk: int = 4, depth: int = 4,
                 loop: int = 4, churn: int = 4,
                 churn_prompt_tokens: int = 48) -> dict:
    """Streaming inter-token gaps under concurrent admission churn, three
    arms: colocated (drain-based), colocated + ``zero_drain=1`` (staged
    in-flight row injection, ISSUE 11), and ``disagg=1+1``. One long
    greedy stream's token-arrival gaps (ms percentiles over the per-chunk
    reap gaps) while ``churn`` chunked admissions (prompts of
    ``churn_prompt_tokens`` ≫ prefill_chunk) are submitted back to back.
    The acceptance number is the p99 gap: drain-based colocated
    admissions clamp the ring to depth 1 and interleave prefill segments
    between decode chunks; the zero-drain arm keeps the ring at full
    K×C depth and injects at reap boundaries (admission stall
    structurally 0); the disagg arm's prefill runs on its own device
    group entirely. The gate (ISSUE 11): zero-drain p99 within ~2× of
    disagg's, all three streams token-for-token identical."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    import jax

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig
    from quorum_tpu.parallel.mesh import disagg_meshes

    if len(jax.devices()) < 2:
        raise RuntimeError(
            "the interference bench needs >= 2 virtual devices "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=2)")
    spec = MODEL_PRESETS["llama-tiny"]
    greedy = SamplerConfig(temperature=0.0)
    stream_prompt = [5, 6, 7]

    def churn_ids(i: int) -> list[int]:
        # DISTINCT prompt per churn admission: a repeated prompt is
        # slot-resident after its first admission, so the colocated arm
        # would tier-0-reuse all but one segment of every later churn
        # admission (the staged arms cannot reuse) — the arms would stop
        # measuring the same admission work.
        return [(11 + 3 * j + 5 * i) % spec.vocab_size
                for j in range(churn_prompt_tokens)]

    # The measured stream must OUTLIVE the dispatch ring: a budget within
    # K×C×chunk tokens fits entirely in one ring fill, finishing before
    # any churn admission can interfere — the phase would measure nothing.
    tokens = max(tokens, 2 * depth * loop * chunk)
    out: dict = {"tokens": tokens, "churn_admissions": churn,
                 "churn_prompt_tokens": churn_prompt_tokens}
    streams: dict[str, list[int]] = {}

    for tag in ("colocated", "zero_drain", "disagg"):
        kw = dict(decode_chunk=chunk, decode_pipeline=depth,
                  decode_loop=loop, n_slots=2, prefill_chunk=16)
        if tag == "disagg":
            pm, dm = disagg_meshes(1, 1)
            eng = InferenceEngine(spec, dm, prefill_mesh=pm, **kw)
        elif tag == "zero_drain":
            eng = InferenceEngine(spec, zero_drain=True, **kw)
        else:
            eng = InferenceEngine(spec, **kw)
        # Warm every program the measured pass dispatches (stream decode
        # buckets, churn segment/handoff buckets): first-use XLA compiles
        # would otherwise dominate the gap percentiles. The churn runs
        # CONCURRENTLY with the warmup stream so the drain-based arm also
        # compiles its clamped (C=1, deep-history) decode variants — the
        # admission-pressure window is exactly what the measured pass
        # spends its time in there.
        warm = eng.submit(stream_prompt, max_new_tokens=tokens,
                          sampler=greedy, seed=0)
        eng.generate(churn_ids(0), max_new_tokens=2, sampler=greedy)
        list(eng.stream_results(warm))

        req = eng.submit(stream_prompt, max_new_tokens=tokens,
                         sampler=greedy, seed=0)
        # One churn admission enqueued BEFORE the stream is consumed (same
        # in every arm): a fused K×C stream can finish in a handful of
        # dispatches, and a churner thread that loses the startup race
        # would leave the admission-interference window unexercised. This
        # one is guaranteed to admit while the stream decodes.
        pre = eng.submit(churn_ids(1), max_new_tokens=2, sampler=greedy)
        stamps: list[float] = []
        toks: list[int] = []
        done = threading.Event()
        n_churned = 1

        def churn_loop():
            nonlocal n_churned
            while not done.is_set() and n_churned < churn * 4:
                eng.generate(churn_ids(1 + n_churned), max_new_tokens=2,
                             sampler=greedy)
                n_churned += 1

        churner = threading.Thread(target=churn_loop, daemon=True)
        churner.start()
        for t in eng.stream_results(req):
            toks.append(t)
            stamps.append(time.perf_counter())
        list(eng.stream_results(pre))
        done.set()
        churner.join()
        streams[tag] = toks
        # A decode chunk's k tokens reach the consumer microseconds apart;
        # the per-chunk reap gap is the signal. Keep only gaps above 0.1ms
        # so the intra-chunk deliveries don't dilute the percentiles.
        gaps = sorted(b - a for a, b in zip(stamps, stamps[1:])
                      if b - a > 1e-4)
        if not gaps:
            gaps = [0.0]

        def pct(p):
            return round(gaps[min(len(gaps) - 1,
                                  int(p / 100 * len(gaps)))] * 1e3, 3)

        out[f"{tag}_intertoken_p50_ms"] = pct(50)
        out[f"{tag}_intertoken_p95_ms"] = pct(95)
        out[f"{tag}_intertoken_p99_ms"] = pct(99)
        out[f"{tag}_churn_completed"] = n_churned
        if tag == "disagg":
            out["disagg_kv_handoffs"] = eng.n_kv_handoffs
            out["disagg_kv_handoff_bytes"] = eng.kv_handoff_bytes
        elif tag == "zero_drain":
            # The zero-drain acceptance counters: injections that landed
            # on a live ring, and the structural-0 admission stall.
            out["zero_drain_admission_overlap"] = eng.n_admission_overlap
            out["zero_drain_admission_stall_s"] = round(
                eng.admission_stall_s, 6)
        else:
            # Wall time the drain-based ring spent clamped for admissions
            # — what zero_drain removes (structurally 0 there).
            out["colocated_admission_stall_s"] = round(
                eng.admission_stall_s, 6)
        # Per-family attribution per arm: the colocated arm's admission
        # cost shows under seg/single_shot against its clamped decode
        # families; the staged arms split theirs across seg/hslice/hput/
        # register while loop keeps full-depth time.
        out[f"{tag}_device_seconds"] = eng.latency.snapshot()
        eng.shutdown()

    out["interference_tokens_match"] = (
        streams["colocated"] == streams["disagg"]
        and streams["colocated"] == streams["zero_drain"])
    c99, z99, d99 = (out["colocated_intertoken_p99_ms"],
                     out["zero_drain_intertoken_p99_ms"],
                     out["disagg_intertoken_p99_ms"])
    # Floor the denominator at the gap filter (0.1 ms): a tiny-budget leg
    # whose reap gaps all fell under the filter reports d99 = 0.0, and an
    # unfloored ratio would record a billions-x artifact as the headline.
    out["interference_p99_ratio"] = round(c99 / max(0.1, d99), 2)
    # The ISSUE 11 gate: zero-drain p99 within ~2x of the disagg number.
    out["zero_drain_p99_vs_disagg"] = round(z99 / max(0.1, d99), 2)
    out["zero_drain_p99_vs_colocated"] = round(c99 / max(0.1, z99), 2)
    return out


def sharded(tokens: int = 48, chunk: int = 4, depth: int = 2,
            loop: int = 2, repeats: int = 2) -> dict:
    """Per-group sharding under disagg (ISSUE 14): three arms at the SAME
    device count (4) — colocated ``tp=4``, ``disagg=2+2&tp=2`` (both
    groups tp-sharded, the handoff resharding between the two layouts on
    the fly), and ``disagg=2+2&pp=2`` (the decode group pipeline-staged:
    stage s holds L/pp layers + their KV shard, rows flow stage→stage
    inside the fused megachunk scan). Reports per arm: decode tok/s,
    handoff bytes/s across the group boundary, dispatch counts, and the
    per-family device-seconds attribution (the staged arm's decode time
    lives under the ``pp_*`` families) — tokens asserted identical across
    all arms (sharding moves bytes, never samples)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    import jax

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig
    from quorum_tpu.parallel.mesh import MeshConfig, disagg_meshes, make_mesh

    if len(jax.devices()) < 4:
        raise RuntimeError(
            "the sharded legs need >= 4 virtual devices "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=4)")
    spec = MODEL_PRESETS["llama-tiny"]
    greedy = SamplerConfig(temperature=0.0)
    prompt = [(3 + 5 * i) % spec.vocab_size for i in range(40)]
    kw = dict(decode_chunk=chunk, decode_pipeline=depth, decode_loop=loop,
              n_slots=2, prefill_chunk=16)
    out: dict = {"sharded_tokens": tokens, "sharded_devices": 4}
    streams: dict[str, list[int]] = {}
    for tag in ("colocated_tp4", "disagg_tp2", "disagg_pp2"):
        if tag == "colocated_tp4":
            eng = InferenceEngine(
                spec, make_mesh(MeshConfig(tp=4), jax.devices()[:4]), **kw)
        elif tag == "disagg_tp2":
            pm, dm = disagg_meshes(2, 2, tp=2)
            eng = InferenceEngine(spec, dm, prefill_mesh=pm, **kw)
        else:
            pm, dm = disagg_meshes(2, 2, pp=2)
            eng = InferenceEngine(spec, dm, prefill_mesh=pm, **kw)
        eng.generate(prompt, max_new_tokens=tokens, sampler=greedy)  # warm
        c0, b0 = eng.n_decode_chunks, eng.kv_handoff_bytes
        walls = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = eng.generate(prompt, max_new_tokens=tokens, sampler=greedy)
            walls.append(time.perf_counter() - t0)
        streams[tag] = res.token_ids
        wall = statistics.median(walls)
        pre = f"sharded_{tag}"
        out[f"{pre}_tok_s"] = round(tokens / wall, 1)
        out[f"{pre}_dispatches_per_request"] = (
            (eng.n_decode_chunks - c0) / repeats)
        handoff_b = eng.kv_handoff_bytes - b0
        out[f"{pre}_handoff_bytes_per_s"] = round(
            handoff_b / max(1e-9, wall * repeats), 1)
        out[f"{pre}_handoff_bytes"] = handoff_b
        # Per-family device-seconds: the staged arm's decode time lives
        # under pp_loop/pp_plain; the handoff halves under hslice/hput.
        out[f"{pre}_device_seconds"] = eng.latency.snapshot()
        if tag == "disagg_pp2":
            out[f"{pre}_decode_pp"] = eng.decode_pp
        eng.shutdown()
    out["sharded_tokens_match"] = (
        streams["colocated_tp4"] == streams["disagg_tp2"]
        == streams["disagg_pp2"])
    return out


def paged(tokens: int = 8, streams: int = 24, page_size: int = 16,
          pool_pages: int = 32) -> dict:
    """Rows-per-chip at FIXED KV HBM (ISSUE 17, the paged-layout headline):
    dense vs ``kv_pages=1`` on a short-stream mix, same position budget.

    The budget is ``pool_pages × page_size`` cache positions. The dense
    rectangle spends it on ``budget // max_seq`` slots — every row pays
    ``max_seq`` whether it uses it or not — while the paged engine spends
    it on a page pool and admits as many rows as their ACTUAL spans fit
    (each short stream here spans ≲ 2 pages). Reports per arm: peak
    concurrently-resident rows, completed streams, wall time, and for the
    paged arm the peak page occupancy — with every stream's tokens
    asserted identical to its dense twin (capacity, never semantics).
    The acceptance gate: peak paged rows ≥ 4× the dense slot count."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = MODEL_PRESETS["llama-tiny"]
    greedy = SamplerConfig(temperature=0.0)
    positions = pool_pages * page_size
    dense_slots = max(1, positions // spec.max_seq)
    # Short streams: ~10-token prompts + the decode budget span ≲ 2 pages,
    # so the pool admits pool_pages // 2 of them at once.
    paged_slots = max(dense_slots, pool_pages // 2)
    prompts = [[(3 + 7 * i + j) % (spec.vocab_size - 1) + 1
                for j in range(8 + (i % 3))] for i in range(streams)]
    out: dict = {"paged_streams": streams, "paged_pool_pages": pool_pages,
                 "paged_page_size": page_size,
                 "paged_dense_rows": dense_slots}
    results: dict[str, dict[int, list[int]]] = {}
    for tag, kw in (("dense", dict(n_slots=dense_slots)),
                    ("paged", dict(n_slots=paged_slots, kv_pages=True,
                                   kv_page_size=page_size,
                                   kv_pool_pages=pool_pages))):
        eng = InferenceEngine(spec, decode_chunk=4, prefill_chunk=16, **kw)
        eng.generate(prompts[0], max_new_tokens=tokens,
                     sampler=greedy)  # warm-up
        peak = {"rows": 0, "pages": 0}
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                with eng._cond:
                    rows = sum(1 for r in eng._slots if r is not None)
                    pages = (eng._page_alloc.allocated_pages
                             if eng.kv_pages else 0)
                peak["rows"] = max(peak["rows"], rows)
                peak["pages"] = max(peak["pages"], pages)
                time.sleep(0.0005)

        outs: dict[int, list[int]] = {}

        def one(i: int) -> None:
            outs[i] = [t for t in eng.generate_stream(
                prompts[i], max_new_tokens=tokens, sampler=greedy, seed=i)]

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        t0 = time.perf_counter()
        ths = [threading.Thread(target=one, args=(i,))
               for i in range(streams)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        wall = time.perf_counter() - t0
        stop.set()
        poller.join()
        results[tag] = outs
        out[f"paged_{tag}_peak_rows"] = peak["rows"]
        out[f"paged_{tag}_completed"] = len(outs)
        out[f"paged_{tag}_wall_s"] = round(wall, 3)
        if eng.kv_pages:
            out["paged_peak_page_occupancy"] = round(
                peak["pages"] / pool_pages, 3)
        eng.shutdown()
    out["paged_rows_per_chip_ratio"] = round(
        out["paged_paged_peak_rows"] / max(1, out["paged_dense_peak_rows"]),
        2)
    out["paged_tokens_match"] = results["dense"] == results["paged"]
    return out


def dedup(prompt_len: int = 48, tokens: int = 8, members: int = 3,
          rounds: int = 8) -> dict:
    """Shared-prefix member dedup (docs/quorum.md): a ``members=M``
    shared-weights engine fans one prompt into M sampling streams; with
    ``quorum_dedup=1`` a coalesced member-complete admission prefills the
    prompt ONCE and broadcasts the K/V into all M cache rows. A
    prefill-heavy fan-out mix (long prompt, short decode) measures the
    headline: prefill tokens computed per fan-out down ~M×, outputs
    token-for-token identical to the M-prefill baseline. A round only
    dedups when all M submits coalesce into one admission group, so the
    reported ratio is the honest mixed-traffic number; ``dedup_rounds``
    says how many of ``rounds`` took the fast path."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = MODEL_PRESETS["llama-tiny"]
    greedy = SamplerConfig(temperature=0.0)
    prompt = [(5 + 11 * j) % (spec.vocab_size - 1) + 1
              for j in range(prompt_len)]
    kw = dict(seed=0, members=members, decode_chunk=4, n_slots=2,
              member_seeds="shared", prefix_cache=False)

    def fan(eng) -> list[list[int]]:
        reqs = [eng.submit(list(prompt), max_new_tokens=tokens,
                           sampler=greedy, seed=7 + m, member=m)
                for m in range(members)]
        return [list(eng.stream_results(r)) for r in reqs]

    out: dict = {"dedup_members": members, "dedup_prompt_len": prompt_len,
                 "dedup_rounds_driven": rounds}
    results: dict[str, list] = {}
    nominal = rounds * members * prompt_len
    for tag, extra in (("off", {}), ("on", {"quorum_dedup": True})):
        eng = InferenceEngine(spec, **kw, **extra)
        try:
            fan(eng)  # warm-up (compiles both prefill variants)
            tokens_before = eng.quorum_dedup_tokens
            prefills_before = eng.quorum_dedup_prefills
            t0 = time.perf_counter()
            results[tag] = [fan(eng) for _ in range(rounds)]
            wall = time.perf_counter() - t0
            # savings over the measured rounds only (warm-up excluded)
            saved = eng.quorum_dedup_tokens - tokens_before
            out[f"dedup_{tag}_wall_s"] = round(wall, 3)
            out[f"dedup_{tag}_prefill_tokens"] = nominal - saved
            if tag == "on":
                out["dedup_rounds"] = (eng.quorum_dedup_prefills
                                       - prefills_before)
        finally:
            eng.shutdown()
    out["dedup_prefill_token_ratio"] = round(
        out["dedup_off_prefill_tokens"]
        / max(1, out["dedup_on_prefill_tokens"]), 2)
    out["dedup_tokens_match"] = results["off"] == results["on"]
    return out


def qos(tokens: int = 24, churn: int = 3, arrivals: int = 8) -> dict:
    """QoS scheduler A/B (ISSUE 18, docs/scheduling.md): interactive TTFT
    under a batch backlog, FIFO vs ``qos=1``, on one llama-tiny engine.

    Both arms run the SAME mixed load — ``churn`` threads submitting
    ``priority="batch"`` streams of ``tokens`` tokens back-to-back, with
    ``arrivals`` sequential ``priority="interactive"`` requests measured
    for TTFT (submit → first token). The FIFO arm queues each interactive
    arrival behind whole batch generations; the qos arm admits it past
    the backlog (WFQ order) and, with every slot busy, parks a batch
    resident (mid-decode preemption — victims resume token-exactly, the
    contract tests/test_sched.py pins). Reports per arm: interactive
    TTFT p50/p99, batch churn throughput (the degradation cost), and for
    the qos arm the preemption/replay counters. A solo (uncontended)
    TTFT floor anchors the comparison."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import MODEL_PRESETS
    from quorum_tpu.ops.sampling import SamplerConfig

    spec = MODEL_PRESETS["llama-tiny"]
    greedy = SamplerConfig(temperature=0.0)
    iprompt = [17, 23, 31, 47, 53]

    def churn_ids(i: int) -> list[int]:
        return [(5 + 3 * i + j) % (spec.vocab_size - 1) + 1
                for j in range(10)]

    def pct(xs: list[float], p: float) -> float:
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(p * len(xs)))], 1)

    def ttft_one(eng, prio: "str | None") -> float:
        t0 = time.perf_counter()
        req = eng.submit(list(iprompt), max_new_tokens=4, sampler=greedy,
                         seed=1, priority=prio)
        it = eng.stream_results(req)
        next(it, None)
        ttft_ms = (time.perf_counter() - t0) * 1000.0
        for _ in it:
            pass
        return ttft_ms

    def wait_backlog(eng, budget_s: float = 2.0) -> None:
        """Admit the next interactive arrival against a FORMED backlog
        (every slot batch-resident): both arms measure the same contended
        moment instead of racing the churn threads' re-submit gap."""
        t_end = time.perf_counter() + budget_s
        while time.perf_counter() < t_end:
            with eng._cond:
                if all(r is not None for r in eng._slots):
                    return
            time.sleep(0.001)

    out: dict = {"qos_arrivals": arrivals, "qos_churn_threads": churn,
                 "qos_churn_tokens": tokens}
    for tag, qos_on in (("fifo", False), ("qos", True)):
        eng = InferenceEngine(spec, n_slots=2, decode_chunk=4,
                              prefill_chunk=16, qos=qos_on)
        eng.generate(iprompt, max_new_tokens=4, sampler=greedy)  # warm
        eng.generate(churn_ids(0), max_new_tokens=tokens, sampler=greedy)
        if not qos_on:
            solo = [ttft_one(eng, None) for _ in range(arrivals)]
            out["qos_solo_ttft_p50_ms"] = pct(solo, 0.5)
            out["qos_solo_ttft_p99_ms"] = pct(solo, 0.99)
        stop = threading.Event()
        done = {"streams": 0, "tokens": 0}

        def churn_loop(k: int) -> None:
            i = k
            while not stop.is_set():
                req = eng.submit(churn_ids(i), max_new_tokens=tokens,
                                 sampler=greedy, seed=i, priority="batch")
                n = sum(1 for _ in eng.stream_results(req))
                done["streams"] += 1
                done["tokens"] += n
                i += churn
        ths = [threading.Thread(target=churn_loop, args=(k,), daemon=True)
               for k in range(churn)]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        time.sleep(0.3)  # let the backlog form: both slots batch-resident
        ttfts = []
        for _ in range(arrivals):
            wait_backlog(eng)
            ttfts.append(ttft_one(eng, "interactive"))
            time.sleep(0.05)
        stop.set()
        for t in ths:
            t.join(30)
        wall = time.perf_counter() - t0
        out[f"qos_{tag}_interactive_ttft_p50_ms"] = pct(ttfts, 0.5)
        out[f"qos_{tag}_interactive_ttft_p99_ms"] = pct(ttfts, 0.99)
        out[f"qos_{tag}_churn_streams"] = done["streams"]
        out[f"qos_{tag}_churn_tok_s"] = round(done["tokens"] / wall, 1)
        if qos_on:
            m = eng.metrics()
            out["qos_preemptions"] = m["preemptions_total"]
            out["qos_preempted_tokens"] = m["preempted_tokens_total"]
            out["qos_replayed_tokens"] = m["replayed_tokens_total"]
        eng.shutdown()
    out["qos_ttft_p99_ratio"] = round(
        out["qos_fifo_interactive_ttft_p99_ms"]
        / max(1e-9, out["qos_qos_interactive_ttft_p99_ms"]), 2)
    out["qos_batch_degradation"] = round(
        out["qos_qos_churn_tok_s"]
        / max(1e-9, out["qos_fifo_churn_tok_s"]), 2)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tokens", type=int, default=64)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--loop", type=int, default=4,
                    help="decode_loop=C for the megachunk leg (>= 2)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--spec-g", type=int, default=4,
                    help="draft length for the speculative A/B legs")
    ap.add_argument("--skip-spec", action="store_true",
                    help="skip the speculative-decoding A/B legs")
    ap.add_argument("--skip-interference", action="store_true",
                    help="skip the colocated-vs-disagg interference legs")
    ap.add_argument("--skip-sharded", action="store_true",
                    help="skip the per-group-sharding legs (disagg+tp / "
                         "staged-pp vs colocated tp at matched devices)")
    ap.add_argument("--only-interference", action="store_true",
                    help="run ONLY the interference legs (bench.py's "
                         "subprocess phase — the depth/megachunk sweep "
                         "would be compiled and thrown away)")
    ap.add_argument("--only-spec", action="store_true",
                    help="run ONLY the speculative A/B legs (bench.py's "
                         "subprocess phase)")
    ap.add_argument("--only-sharded", action="store_true",
                    help="run ONLY the per-group-sharding legs (bench.py's "
                         "subprocess phase)")
    ap.add_argument("--skip-paged", action="store_true",
                    help="skip the paged-KV rows-per-chip legs")
    ap.add_argument("--only-paged", action="store_true",
                    help="run ONLY the paged-KV rows-per-chip legs "
                         "(bench.py's subprocess phase)")
    ap.add_argument("--skip-qos", action="store_true",
                    help="skip the QoS scheduler A/B legs")
    ap.add_argument("--only-qos", action="store_true",
                    help="run ONLY the QoS scheduler A/B legs (bench.py's "
                         "subprocess phase)")
    ap.add_argument("--skip-dedup", action="store_true",
                    help="skip the shared-prefix member-dedup legs")
    ap.add_argument("--only-dedup", action="store_true",
                    help="run ONLY the shared-prefix member-dedup legs "
                         "(bench.py's subprocess phase)")
    args = ap.parse_args()
    if args.only_dedup:
        md = dedup()
        _print_dedup(md)
        print(json.dumps(md), flush=True)
        return 0
    if args.only_qos:
        mq = qos()
        _print_qos(mq)
        print(json.dumps(mq), flush=True)
        return 0
    if args.only_paged:
        mp = paged()
        _print_paged(mp)
        print(json.dumps(mp), flush=True)
        return 0
    if args.only_sharded:
        try:
            msh = sharded(args.tokens, args.chunk, args.depth, args.loop,
                          args.repeats)
        except RuntimeError as e:
            msh = {"sharded_skipped": str(e)}
            print(f"sharded legs skipped: {e}")
        else:
            _print_sharded(msh)
        print(json.dumps(msh), flush=True)
        return 0
    if args.only_spec:
        ms = spec(args.tokens, args.chunk, args.depth, args.spec_g)
        for leg in ("rep", "crep"):
            print(f"  spec {leg}: {ms[f'spec_{leg}_off_tok_s']} -> "
                  f"{ms[f'spec_{leg}_on_tok_s']} tok/s, acceptance "
                  f"{ms[f'spec_{leg}_on_acceptance']:.0%}, tokens "
                  f"identical: {ms[f'spec_{leg}_tokens_match']}")
        print(json.dumps(ms), flush=True)
        return 0
    if args.only_interference:
        mi = interference(args.tokens, args.chunk, args.depth, args.loop)
        print("prefill interference (streaming inter-token gap under "
              "admission churn):")
        for tag in ("colocated", "zero_drain", "disagg"):
            print(f"  {tag:10}: p50 {mi[f'{tag}_intertoken_p50_ms']} ms, "
                  f"p95 {mi[f'{tag}_intertoken_p95_ms']} ms, "
                  f"p99 {mi[f'{tag}_intertoken_p99_ms']} ms "
                  f"({mi[f'{tag}_churn_completed']} churn admissions)")
        print(f"  p99 colocated/disagg: {mi['interference_p99_ratio']:.2f}x"
              f", zero_drain/disagg: {mi['zero_drain_p99_vs_disagg']:.2f}x"
              f" (gate: ~2x), colocated/zero_drain: "
              f"{mi['zero_drain_p99_vs_colocated']:.2f}x")
        print(json.dumps(mi), flush=True)
        return 0
    if args.depth < 2:
        ap.error("--depth must be >= 2 (1 is the K=1 baseline both legs run)")
    if args.loop < 2:
        ap.error("--loop must be >= 2 (1 is the unfused baseline)")
    m = run(args.tokens, args.chunk, args.depth, args.repeats, args.loop)
    k, c = args.depth, args.loop
    print(f"host-path microbench (llama-tiny, {m['tokens']} tokens, "
          f"decode_chunk={m['decode_chunk']}):")
    for tag, label in (("k1", "K=1      "), (f"k{k}", f"K={k}      "),
                       (f"loop{c}", f"K={k} C={c}")):
        print(f"  {label}: {m[f'{tag}_dispatches_per_request']:.1f} "
              f"dispatches/req, {m[f'{tag}_syncs_per_request']:.1f} blocking "
              f"syncs/req, {m[f'{tag}_tok_s']} tok/s, "
              f"{m[f'{tag}_drain_gap_ms_per_dispatch']:.2f} ms drain "
              "gap/dispatch")
        fams = m.get(f"{tag}_device_seconds", {})
        decode_fams = {f: s for f, s in fams.items()
                       if f in ("plain", "loop", "dfa", "loop_dfa",
                                "verify", "dfa_verify", "spec_loop",
                                "spec_loop_dfa", "unknown")}
        if decode_fams:
            parts = ", ".join(
                f"{f} p50 {s['p50_ms']}ms / p99 {s['p99_ms']}ms "
                f"(n={s['count']})" for f, s in sorted(decode_fams.items()))
            print(f"             device-seconds by family: {parts}")
    print(f"  overrun tokens: K=1 {m['k1_overrun_tokens']}, "
          f"K={k} {m[f'k{k}_overrun_tokens']}, "
          f"C={c} {m[f'loop{c}_overrun_tokens']} (on-device finish)")
    print(f"  host-turnaround share hidden by K={k}: "
          f"{m['host_turnaround_share']:.1%}")
    print(f"  dispatch reduction at decode_loop={c}: "
          f"{m['loop_dispatch_reduction']:.1f}x")
    print(f"  token-for-token identical: {m['tokens_match']}")
    if not args.skip_spec:
        ms = spec(args.tokens, args.chunk, args.depth, args.spec_g)
        m.update(ms)
        print(f"speculative decoding A/B (g={args.spec_g}, forced-periodic "
              "stream, spec on vs off):")
        for leg, label in (("rep", "repetitive "), ("crep", "constrained")):
            print(f"  {label}: "
                  f"{ms[f'spec_{leg}_off_tok_s']} -> "
                  f"{ms[f'spec_{leg}_on_tok_s']} tok/s "
                  f"({ms[f'spec_{leg}_speedup']:.2f}x), "
                  f"{ms[f'spec_{leg}_off_dispatches_per_request']} -> "
                  f"{ms[f'spec_{leg}_on_dispatches_per_request']} "
                  f"dispatches/req, acceptance "
                  f"{ms[f'spec_{leg}_on_acceptance']:.0%}, "
                  f"{ms[f'spec_{leg}_on_spec_overlapped']} of "
                  f"{ms[f'spec_{leg}_on_spec_turns']} verify turns "
                  "overlapped the ring, tokens identical: "
                  f"{ms[f'spec_{leg}_tokens_match']}")
    if not args.skip_interference:
        mi = interference(args.tokens, args.chunk, args.depth, args.loop)
        m.update(mi)
        print("prefill interference (streaming inter-token gap under "
              "admission churn):")
        for tag in ("colocated", "zero_drain", "disagg"):
            print(f"  {tag:10}: p50 {mi[f'{tag}_intertoken_p50_ms']} ms, "
                  f"p95 {mi[f'{tag}_intertoken_p95_ms']} ms, "
                  f"p99 {mi[f'{tag}_intertoken_p99_ms']} ms "
                  f"({mi[f'{tag}_churn_completed']} churn admissions)")
        print(f"  p99 colocated/disagg: {mi['interference_p99_ratio']:.2f}x"
              f" (higher = disagg insulates better); KV handed off: "
              f"{mi['disagg_kv_handoff_bytes']} bytes in "
              f"{mi['disagg_kv_handoffs']} transfers")
        print(f"  p99 zero_drain/disagg: "
              f"{mi['zero_drain_p99_vs_disagg']:.2f}x (gate: ~2x, in "
              "software on one device group); injections onto a live "
              f"ring: {mi['zero_drain_admission_overlap']}, admission "
              f"stall {mi['zero_drain_admission_stall_s']}s "
              f"(drain-based arm: {mi['colocated_admission_stall_s']}s)")
        print(f"  token-for-token identical: "
              f"{mi['interference_tokens_match']}")
    if not args.skip_sharded:
        # A box with XLA_FLAGS preset to fewer than 4 virtual devices
        # (the pre-sharded-leg setting was 2) banks the skip instead of
        # losing every other leg's numbers to a crash before the final
        # JSON line — the onchip_session discipline.
        try:
            msh = sharded(args.tokens, args.chunk, args.depth, args.loop,
                          args.repeats)
        except RuntimeError as e:
            msh = {"sharded_skipped": str(e)}
            print(f"sharded legs skipped: {e}")
        else:
            _print_sharded(msh)
        m.update(msh)
    if not args.skip_paged:
        mp = paged()
        _print_paged(mp)
        m.update(mp)
    if not args.skip_qos:
        mq = qos()
        _print_qos(mq)
        m.update(mq)
    if not args.skip_dedup:
        md = dedup()
        _print_dedup(md)
        m.update(md)
    print(json.dumps(m), flush=True)
    return 0


def _print_dedup(md: dict) -> None:
    print(f"shared-prefix member dedup (members={md['dedup_members']}, "
          f"{md['dedup_prompt_len']}-token prompt, "
          f"{md['dedup_rounds_driven']} fan-outs):")
    print(f"  prefill tokens computed: {md['dedup_off_prefill_tokens']} -> "
          f"{md['dedup_on_prefill_tokens']} "
          f"({md['dedup_prefill_token_ratio']:.2f}x fewer; "
          f"{md['dedup_rounds']}/{md['dedup_rounds_driven']} fan-outs "
          "coalesced)")
    print(f"  wall: {md['dedup_off_wall_s']}s -> {md['dedup_on_wall_s']}s, "
          f"token-for-token identical: {md['dedup_tokens_match']}")


def _print_paged(mp: dict) -> None:
    print(f"paged KV rows-per-chip (fixed {mp['paged_pool_pages']}-page "
          f"HBM budget, {mp['paged_streams']} short streams):")
    print(f"  dense rectangle: {mp['paged_dense_rows']} rows, peak "
          f"resident {mp['paged_dense_peak_rows']}, "
          f"wall {mp['paged_dense_wall_s']}s")
    print(f"  kv_pages=1     : peak resident {mp['paged_paged_peak_rows']}"
          f", page occupancy {mp['paged_peak_page_occupancy']:.0%}, "
          f"wall {mp['paged_paged_wall_s']}s")
    print(f"  rows/chip: {mp['paged_rows_per_chip_ratio']:.1f}x "
          f"(gate: >= 4x), token-for-token identical: "
          f"{mp['paged_tokens_match']}")


def _print_qos(mq: dict) -> None:
    print(f"qos scheduler A/B ({mq['qos_churn_threads']}-thread batch "
          f"churn, {mq['qos_arrivals']} interactive arrivals):")
    print(f"  solo floor : interactive TTFT p50 "
          f"{mq['qos_solo_ttft_p50_ms']} ms, p99 "
          f"{mq['qos_solo_ttft_p99_ms']} ms (uncontended)")
    for tag, label in (("fifo", "fifo (qos=0)"), ("qos", "qos=1      ")):
        print(f"  {label}: interactive TTFT p50 "
              f"{mq[f'qos_{tag}_interactive_ttft_p50_ms']} ms, p99 "
              f"{mq[f'qos_{tag}_interactive_ttft_p99_ms']} ms; batch "
              f"{mq[f'qos_{tag}_churn_tok_s']} tok/s "
              f"({mq[f'qos_{tag}_churn_streams']} streams)")
    print(f"  p99 fifo/qos: {mq['qos_ttft_p99_ratio']:.2f}x (higher = qos "
          f"insulates better); batch cost: "
          f"{mq['qos_batch_degradation']:.2f}x of fifo throughput; "
          f"preemptions {mq['qos_preemptions']} "
          f"({mq['qos_preempted_tokens']} tokens parked, "
          f"{mq['qos_replayed_tokens']} replayed token-exactly)")


def _print_sharded(msh: dict) -> None:
    print("per-group sharding under disagg (4 devices, matched count):")
    for tag in ("colocated_tp4", "disagg_tp2", "disagg_pp2"):
        pre = f"sharded_{tag}"
        fams = msh.get(f"{pre}_device_seconds", {})
        decode = ", ".join(
            f"{f} p50 {s['p50_ms']}ms (n={s['count']})"
            for f, s in sorted(fams.items())
            if f in ("plain", "loop", "pp_plain", "pp_loop"))
        print(f"  {tag:13}: {msh[f'{pre}_tok_s']} tok/s, "
              f"{msh[f'{pre}_dispatches_per_request']:.1f} dispatches/req, "
              f"{msh[f'{pre}_handoff_bytes_per_s']} handoff B/s "
              f"({msh[f'{pre}_handoff_bytes']} B); {decode}")
    print(f"  token-for-token identical: {msh['sharded_tokens_match']}")


if __name__ == "__main__":
    raise SystemExit(main())
