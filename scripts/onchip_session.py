"""On-chip measurement runbook: PERF.md §5's "first moves", one command.

The round-3 TPU tunnel has repeatedly wedged minutes into a session, so
every measurement this script takes is banked to ``ONCHIP.json`` the moment
it lands — run it as soon as the chip answers and let it execute the whole
list; whatever the tunnel survives is kept:

  1. ``python bench.py`` end to end (itself probe-gated per phase since
     round 3) — the BENCH headline + 7B + int8 north-star numbers.
  2. Stacked A/B: phases 1/2 rerun with ``QUORUM_TPU_BENCH_STACKED=0`` —
     the stacked-vs-three-engines TTFT/tokens-per-second delta for PERF §4.
  3. ``kv_quant=int8`` on real silicon: one request against
     llama-3-8b ``quant=int8&kv_quant=int8&max_seq=8192`` (the native int8
     q·K / p·V decode einsums have only ever run on CPU).
  4. Pallas decode-kernel A/B (``QUORUM_TPU_FLASH_DECODE=1``) on a skewed
     co-batch at 7B — separate processes per arm (the flag is read at
     trace time).
  5. Megachunk decode A/B (``decode_loop=4`` vs unfused, ISSUE 6): the
     fused on-device chunk loop vs one-dispatch-per-chunk at 7B, separate
     processes per arm (decode_loop is structural on the engine). CPU
     already pins token equality and the ~C× dispatch reduction
     (make hostpath-bench); this arm measures what the killed dispatch
     boundary is worth in decode tok/s on real silicon.
  6. Spec-compose A/B (``spec_decode=4`` vs off, ISSUE 10): ring-resident
     row-wise speculation at 7B, separate processes per arm. CPU already
     pins token equality and >90% forced-periodic acceptance (incl. the
     constrained dfa-verify leg); this arm measures natural-text
     acceptance and the tok/s win per accepted token.
  7. One ``QUORUM_TPU_PROFILE_DIR`` trace of steady-state 7B decode, to
     attribute the ~38% HBM-roofline gap (PERF §4).
  8. int8 QUALITY at 7B scale: teacher-forced scoring (engine/score.py)
     of one fixed prompt under bf16 and under quant=int8 of the SAME
     seed-0 mistral-7b weights — mean |Δlogprob| and the ppl ratio. The
     CPU suite pins quantization error only on tiny models; this is the
     number that says int8 serving is quality-safe at the scale we ship.

Usage: ``python scripts/onchip_session.py
[--skip bench,ab,kvq,flash,megachunk,spec,disagg,sharded,zero_drain,kv_pages,profile,qq]``
Each step is a subprocess with its own budget; a wedged step is recorded
and skipped, never fatal. Results: ``ONCHIP.json`` (merged dict, one key
prefix per step) + profile trace under ``profiles/``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "ONCHIP.json")

KVQ_URL = ("tpu://llama-3-8b?max_seq=8192&slots=2&decode_chunk=16"
           "&max_tokens=32&quant=int8&kv_quant=int8&prefill_chunk=512")
B7_URL = ("tpu://mistral-7b?max_seq=4096&slots=2&decode_chunk=16"
          "&max_tokens=48&prefill_chunk=256")


def bank(update: dict) -> None:
    got = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                got = json.load(f)
        except (json.JSONDecodeError, OSError):
            # A mid-write kill (the scenario this script exists for) must
            # not poison every later session; start fresh.
            got = {}
    got.update(update)
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(got, f, indent=1)
    os.replace(tmp, OUT)  # atomic: never a truncated ONCHIP.json
    print(f"[onchip] banked: {sorted(update)}", flush=True)


def probe(budget: int = 120) -> bool:
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "x = jnp.ones((256,256), jnp.bfloat16);"
             "(x @ x).block_until_ready();"
             "print('PROBE_OK', jax.default_backend())"],
            capture_output=True, text=True, timeout=budget)
    except subprocess.TimeoutExpired:
        return False
    # Scan every line: teardown noise after the marker must not read as a
    # dead tunnel; a CPU-fallback jax must (ADVICE r3 / bench._probe_device).
    if p.returncode != 0:
        return False
    return any(
        ln.startswith("PROBE_OK") and not ln.rstrip().endswith(" cpu")
        for ln in (p.stdout or "").splitlines())


def probe_with_retry(window_s: int = 900) -> bool:
    """Probe with backoff for up to ``window_s`` — the tunnel's remote end
    is supervised and can recover minutes after a wedge."""
    deadline = time.time() + window_s
    wait = 30.0
    while True:
        if probe():
            return True
        left = deadline - time.time()
        if left <= 0:
            return False
        step = min(wait, left)
        print(f"[onchip] probe failed; retrying in {step:.0f}s "
              f"({left:.0f}s left)", flush=True)
        time.sleep(step)
        wait = min(wait * 2, 300.0)


def kill_process_tree(pid: int) -> None:
    """SIGKILL ``pid``, every /proc-walkable descendant, and each of their
    process groups. One kill discipline for the whole toolchain: a step
    child started in its own session is NOT reachable by killpg on its
    parent's group, and an orphaned step is exactly the process holding
    the single-holder TPU client."""
    import signal

    children: dict[int, list[int]] = {}
    for p in os.listdir("/proc"):
        if not p.isdigit():
            continue
        try:
            with open(f"/proc/{p}/stat") as f:
                ppid = int(f.read().rsplit(")", 1)[-1].split()[1])
        except (OSError, ValueError, IndexError):
            continue
        children.setdefault(ppid, []).append(int(p))
    doomed, stack = [], [pid]
    while stack:
        q = stack.pop()
        doomed.append(q)
        stack.extend(children.get(q, []))
    for q in doomed:
        for kill in (os.killpg, os.kill):
            try:
                kill(q, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass


# Worst-case host time probe_with_retry(300) can spend before a step's
# child even starts — budget planners must reserve it per step.
PROBE_OVERHEAD_S = 420


def run_step(name: str, argv: list[str], budget: int,
             env_extra: dict | None = None) -> dict:
    """Run one measurement subprocess; parse its last JSON line.

    The child runs in its OWN process group and a timeout kills its whole
    process TREE — bench.py spawns per-phase grandchildren, and killing
    only the direct child would orphan the process actually holding the
    single-holder TPU client."""
    if not probe_with_retry(300):
        return {f"{name}_error": "skipped: device probe failed"}
    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.time()
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        kill_process_tree(proc.pid)
        stdout, _ = proc.communicate()
        got = _last_json(stdout)
        got[f"{name}_error"] = f"timeout after {budget}s"
        return got
    got = _last_json(stdout)
    if not got:
        got = {f"{name}_error": f"rc={proc.returncode}: {(stderr or '')[-300:]}"}
    got[f"{name}_wall_s"] = round(time.time() - t0, 1)
    return got


def _last_json(stdout: str) -> dict:
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {}


# Serving measurement used by the kvq/flash/profile steps: drive requests
# through the real engine+backend (no HTTP — the socket stack is bench.py's
# job). Modes: "seq" (N sequential requests, report the warm one; wrapped
# in maybe_profile when QUORUM_TPU_PROFILE_DIR is set) and "skew" (after a
# sequential warmup, co-batch one LONG and one SHORT stream concurrently —
# the decode-kernel A/B case: the short row is the per-row-exact-read
# beneficiary).
_SERVE_ONE = r"""
import asyncio, json, os, sys, time
url, n_requests, prefix, n_words = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], int(sys.argv[4]))
mode = sys.argv[5] if len(sys.argv) > 5 else "seq"
from quorum_tpu.config import BackendSpec
from quorum_tpu.backends.tpu_backend import TpuBackend
from quorum_tpu.observability import maybe_profile

be = TpuBackend.from_spec(BackendSpec(name="M", url=url, model="m"))

async def one(seed, words):
    body = {"model": "m", "stream": True, "max_tokens": 32,
            "temperature": 0.0, "seed": seed,
            "messages": [{"role": "user", "content": "x " * words}]}
    t0 = time.time()
    first = None
    toks = 0
    async for chunk in be.stream(body, {}, 3600.0):
        if chunk.get("choices", [{}])[0].get("delta", {}).get("content"):
            first = first or time.time()
            toks += 1
    if first is None:   # error chunk / zero-token stream: record, not crash
        return {"ttft_s": -1.0, "toks": 0, "decode_s": 0.0}
    return {"ttft_s": first - t0, "toks": toks,
            "decode_s": time.time() - first}

def tok_s(r):
    return round((r["toks"] - 1) / max(r["decode_s"], 1e-9), 1)

if mode == "skew":
    asyncio.run(one(0, n_words))   # compile both admission buckets
    asyncio.run(one(1, 20))
    async def pair():
        return await asyncio.gather(one(2, n_words), one(3, 20))
    long_r, short_r = asyncio.run(pair())
    print(json.dumps({
        f"{prefix}_short_decode_tok_s": tok_s(short_r),
        f"{prefix}_long_decode_tok_s": tok_s(long_r),
        f"{prefix}_agg_decode_tok_s": round(
            tok_s(short_r) + tok_s(long_r), 1),
    }))
else:
    outs = [asyncio.run(one(i, n_words)) for i in range(n_requests - 1)]
    with maybe_profile("onchip"):   # no-op unless QUORUM_TPU_PROFILE_DIR
        outs.append(asyncio.run(one(n_requests - 1, n_words)))
    warm = outs[-1]
    print(json.dumps({
        f"{prefix}_ttft_ms": round(warm["ttft_s"] * 1e3, 1),
        f"{prefix}_decode_tok_s": tok_s(warm),
        f"{prefix}_n_tokens": warm["toks"],
    }))
"""


# Quality child: score one deterministic prompt with the engine's
# teacher-forced path; prints {"lp": [...]} (prompt-token logprobs, first
# dropped). One precision per process — bf16 weights alone are ~14.5 GB.
_SCORE_ONE = r"""
import json, sys
model, quant = sys.argv[1], sys.argv[2]
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.engine.engine import get_engine
from quorum_tpu.engine.score import score_token_batch
spec = resolve_spec(model, {"max_seq": "1024"})
eng = get_engine(spec, n_slots=1,
                 quant=(None if quant == "none" else quant))
ids = [(i * 37 + 11) % (spec.vocab_size - 8) + 5 for i in range(512)]
lp = score_token_batch(eng, [ids], top_k=0)[0]["token_logprobs"][1:]
print(json.dumps({"lp": lp}))
"""


def quant_quality_step(arm_budget: int = 1500) -> dict:
    import math

    # Env override exists for the CPU test harness (a 7B forward on CPU
    # takes minutes); the chip runs the real 7B default.
    model = os.environ.get("QUORUM_TPU_QQ_MODEL", "mistral-7b")
    arms = {}
    diag = {}  # _error/_wall_s markers ride along even when lp salvaged
    for arm in ("none", "int8"):
        got = run_step(
            f"qq_{arm}",
            [sys.executable, "-c", _SCORE_ONE, model, arm],
            budget=arm_budget)
        diag.update({k: v for k, v in got.items() if k != "lp"})
        if "lp" not in got:
            return diag
        arms[arm] = got["lp"]
    bf16, q8 = arms["none"], arms["int8"]
    mean_abs = sum(abs(a - b) for a, b in zip(bf16, q8)) / len(bf16)
    ppl = {k: math.exp(-sum(v) / len(v)) for k, v in
           (("bf16", bf16), ("int8", q8))}
    return {
        **diag,
        "qq_model": model,
        "qq_n_scored_tokens": len(bf16),
        "qq_mean_abs_dlogprob": round(mean_abs, 5),
        "qq_ppl_bf16": round(ppl["bf16"], 4),
        "qq_ppl_int8": round(ppl["int8"], 4),
        "qq_ppl_ratio": round(ppl["int8"] / ppl["bf16"], 5),
    }


def main() -> None:
    skip = set()
    args = sys.argv[1:]
    for i, a in enumerate(args):
        if a.startswith("--skip="):
            skip |= set(a.split("=", 1)[1].split(","))
        elif a == "--skip" and i + 1 < len(args):
            skip |= set(args[i + 1].split(","))

    if not probe_with_retry():
        print("[onchip] device probe failed — tunnel dead; retry later")
        bank({"onchip_error": "tunnel dead at session start",
              "ts": time.time()})
        sys.exit(3)
    print("[onchip] device alive — starting the list", flush=True)
    bank({"onchip_started_ts": time.time(), "onchip_error": None})

    # A supervisor (scripts/tunnel_watch.py) can hand this session a total
    # budget; steps that no longer fit are SKIPPED (banked as such) so the
    # session exits cleanly instead of being killed mid-computation —
    # a SIGKILL mid-dispatch can wedge the single-holder TPU tunnel.
    budget_env = os.environ.get("QUORUM_TPU_ONCHIP_BUDGET", "")
    session_deadline = (time.time() + float(budget_env)) if budget_env else None

    def fits(name: str, step_budget: int, n_children: int = 1) -> int:
        """Step budget trimmed to the session's remaining time; 0 = skip
        (a trimmed run that could not finish anything useful is worse than
        banking the skip and leaving the chip free). Each run_step can
        spend PROBE_OVERHEAD_S on its probe window before the child even
        starts, so that is reserved per child — otherwise a flaky tunnel
        pushes a cleanly-planned session past its supervisor's backstop."""
        if session_deadline is None:
            return step_budget
        reserve = PROBE_OVERHEAD_S * n_children
        left = int(session_deadline - time.time()) - reserve
        if left < min(step_budget, 900):
            bank({f"{name}_error": "skipped: session budget exhausted"})
            return 0
        return min(step_budget, left)

    bench_got: dict = {}
    if "bench" not in skip:
        # Full budget (10800 s) exceeds bench.py's own derived watchdog
        # (~9 900 s with the A/B and ckpt phases). A TRIMMED budget is
        # handed to bench as QUORUM_TPU_BENCH_WATCHDOG so bench replans its
        # phases INSIDE it and exits cleanly — killing a bench that still
        # believes in its full plan is the mid-dispatch SIGKILL this whole
        # mechanism exists to avoid; the outer timeout (+300 s) is only
        # the backstop.
        b = fits("bench", 10800)
        if b:
            # bench.py defaults its orchestrator deadline to the ~1500 s
            # driver kill window; THIS run is supervised with a real
            # multi-hour budget, so say so explicitly — without
            # QUORUM_TPU_BENCH_DEADLINE_S the session's bench would skip
            # every post-headline phase at the driver-window default.
            # This bench's output is banked back into ONCHIP.json below —
            # it must not merge the existing artifact into itself (bench's
            # _banked_onchip), or every session nests the prior artifact
            # one level deeper.
            env = {"QUORUM_TPU_BENCH_DEADLINE_S": str(b),
                   "QUORUM_TPU_BENCH_ONCHIP_MERGE": "0"}
            if b < 10800:
                env["QUORUM_TPU_BENCH_WATCHDOG"] = str(b)
            bench_got = run_step("bench", [sys.executable, "bench.py"],
                                 budget=b + 300, env_extra=env)
            bank(bench_got)
    if "ab" not in skip:
        # bench.py's own plan now carries the stacked A/B (ab_* keys);
        # rerun it here only when THIS run's arm didn't land — a previous
        # session's banked ab_* keys must not pair stale separate-engine
        # numbers with fresh headline numbers.
        if any(k.startswith("ab_p50") for k in bench_got):
            print("[onchip] bench already carried the stacked A/B — skipping")
        else:
            b = fits("ab", 1200)
            if b:
                bank({(k if k.startswith("ab_") else f"ab_{k}"): v
                      for k, v in run_step(
                    "ab", [sys.executable, "bench.py", "--phase12"],
                    budget=b,
                    env_extra={"QUORUM_TPU_BENCH_STACKED": "0"}).items()})
    if "kvq" not in skip:
        b = fits("kvq", 1800)
        if b:
            bank(run_step(
                "kvq", [sys.executable, "-c", _SERVE_ONE, KVQ_URL, "2",
                        "kvq", "600"], budget=b))
    if "flash" not in skip:
        # ~1000 words ≈ 3000 byte-tokens: long row near the 4096 window,
        # short row at ~60 — the skew the kernel exists for.
        for arm, env in (("flash_off", {"QUORUM_TPU_FLASH_DECODE": "0"}),
                         ("flash_on", {"QUORUM_TPU_FLASH_DECODE": "1"})):
            b = fits(arm, 1500)
            if b:
                bank(run_step(
                    arm, [sys.executable, "-c", _SERVE_ONE, B7_URL, "2",
                          arm, "1000", "skew"], budget=b, env_extra=env))
    if "megachunk" not in skip:
        # decode_loop=4 vs unfused at 7B: SEPARATE processes per arm —
        # decode_loop is structural on the engine, and the unfused arm
        # must compile the exact pre-existing programs (the cache-key pin
        # the CPU suite enforces). Steady-state decode tok/s is the
        # number: the fused arm's only difference is the killed
        # chunk-dispatch boundary between chunks.
        for arm, arm_url in (("loop_off", B7_URL),
                             ("loop_on", B7_URL + "&decode_loop=4")):
            b = fits(arm, 1500)
            if b:
                bank(run_step(
                    arm, [sys.executable, "-c", _SERVE_ONE, arm_url, "2",
                          arm, "600"], budget=b))
    if "spec" not in skip:
        # Spec-compose A/B (PERF.md §5 step 6): spec_decode=4 vs off at
        # 7B, SEPARATE processes per arm (spec engages per engine; the
        # off arm must dispatch the exact pre-existing programs). The
        # runbook drive's generations self-repeat on a real model, so
        # prompt-lookup drafting engages on natural traffic; the banked
        # numbers are steady-state decode tok/s plus the engine-block
        # spec_{turns,accepted,draft_tokens,overlapped}_total counters
        # (acceptance rate = accepted/drafted; overlapped > 0 = the ring
        # stayed resident through verify turns).
        for arm, arm_url in (("spec_off", B7_URL),
                             ("spec_on", B7_URL + "&spec_decode=4")):
            b = fits(arm, 1500)
            if b:
                bank(run_step(
                    arm, [sys.executable, "-c", _SERVE_ONE, arm_url, "2",
                          arm, "600"], budget=b))
    if "disagg" not in skip:
        # Disaggregated vs colocated at 7B (PERF.md §5 step 7): the
        # interference number — one streaming request's inter-token
        # p95/p99 while admission churn runs — per arm, SEPARATE
        # processes (disagg is structural). Needs a multi-chip host
        # (disagg=P+D builds disjoint per-group meshes); on a single v5e
        # chip the step records the skip rather than faking groups.
        # Device count probed in a SUBPROCESS, like probe(): importing
        # jax here would initialize (and exclusively hold) the TPU
        # runtime in the orchestrator, starving every later child step.
        try:
            n_dev = int(subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True, timeout=180,
            ).stdout.strip() or 0)
        except Exception:
            n_dev = 0

        if n_dev >= 2:
            for arm, arm_url in (
                    ("disagg_off", B7_URL),
                    ("disagg_on", B7_URL + "&disagg=1+1&prefill_chunk=512")):
                b = fits(arm, 1500)
                if b:
                    bank(run_step(
                        arm, [sys.executable, "-c", _SERVE_ONE, arm_url,
                              "2", arm, "600"], budget=b))
        else:
            bank({"disagg_skipped": "single-device host (disagg needs "
                                    ">= 2 devices for disjoint groups)"})
    if "sharded" not in skip:
        # Per-group tensor sharding under disagg (ISSUE 14): disagg=2+2&
        # tp=2 vs colocated tp=4 at matched device count, at 7B, SEPARATE
        # processes per arm (the mesh layout is structural). Needs >= 4
        # devices for the matched-count comparison; a single v5e chip
        # banks the skip rather than faking groups (same discipline as
        # the disagg step — the device count is probed in a subprocess so
        # the orchestrator never holds the TPU client).
        try:
            n_dev = int(subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(len(jax.devices()))"],
                capture_output=True, text=True, timeout=180,
            ).stdout.strip() or 0)
        except Exception:
            n_dev = 0
        if n_dev >= 4:
            for arm, arm_url in (
                    ("sharded_tp4", B7_URL + "&tp=4"),
                    ("sharded_disagg_tp2",
                     B7_URL + "&disagg=2+2&tp=2&prefill_chunk=256")):
                b = fits(arm, 1500)
                if b:
                    bank(run_step(
                        arm, [sys.executable, "-c", _SERVE_ONE, arm_url,
                              "2", arm, "600"], budget=b))
        else:
            bank({"sharded_skipped": f"{n_dev} device(s): the matched-"
                                     "count sharded A/B needs >= 4 (a "
                                     "single chip has no group to shard "
                                     "against)"})
    if "zero_drain" not in skip:
        # Zero-drain vs drain-based colocated at 7B (PERF.md §5 step 7b):
        # the SAME interference number as the disagg step, on ONE device
        # group — the software answer where disagg's second group isn't
        # available (runs on a single v5e chip, no device-count probe).
        # SEPARATE processes per arm (zero_drain is structural — it
        # splits the engine cache key and the admission routing).
        for arm, arm_url in (
                ("zero_drain_off", B7_URL),
                ("zero_drain_on", B7_URL + "&zero_drain=1")):
            b = fits(arm, 1500)
            if b:
                bank(run_step(
                    arm, [sys.executable, "-c", _SERVE_ONE, arm_url, "2",
                          arm, "600"], budget=b))
    if "kv_pages" not in skip:
        # Paged-KV A/B (kv_pages=1 vs dense, PERF.md §5 step 7c):
        # SEPARATE processes per arm (kv_pages is structural and the
        # dense arm must compile the exact pre-existing programs — the
        # cache-key pin tests/test_paged_kv.py enforces). The CPU bench
        # (make hostpath-bench --only-paged) already pins 4.0× resident
        # rows per chip at a fixed position budget with tokens identical;
        # this arm measures the gather-through-table tax per decode step
        # at 7B, where the dense path's contiguous cache reads become
        # page-indexed reads. Single chip, no device-count probe.
        for arm, arm_url in (
                ("kv_pages_off", B7_URL),
                ("kv_pages_on", B7_URL + "&kv_pages=1")):
            b = fits(arm, 1500)
            if b:
                bank(run_step(
                    arm, [sys.executable, "-c", _SERVE_ONE, arm_url, "2",
                          arm, "600"], budget=b))
    if "qos" not in skip:
        # QoS scheduler A/B (kv_pages off, PERF.md §5 step 7d): qos=1 vs
        # FIFO at 7B under mixed-class load, SINGLE chip, SEPARATE
        # processes per arm (qos is host policy — same programs both arms
        # — but the FIFO arm must never have seen a preemption). The CPU
        # bench (make hostpath-bench --only-qos) already pins the
        # contract (victim streams token-exact, interactive admitted past
        # the batch backlog); this measures interactive p99 TTFT vs solo
        # and the batch tok/s cost of preemption at 7B, where a parked
        # row's replay rides the prefix cache instead of re-prefilling.
        for arm, arm_url in (
                ("qos_off", B7_URL),
                ("qos_on", B7_URL + "&qos=1")):
            b = fits(arm, 1500)
            if b:
                bank(run_step(
                    arm, [sys.executable, "-c", _SERVE_ONE, arm_url, "2",
                          arm, "600"], budget=b))
    if "qq" not in skip:
        b = fits("qq", 3100, n_children=2)  # two ~1500s precision arms
        if b:
            bank(quant_quality_step(arm_budget=b // 2))
    if "profile" not in skip:
        b = fits("profile", 1500)
        if b:
            prof_dir = os.path.join(REPO, "profiles")
            bank(run_step(
                "profile", [sys.executable, "-c", _SERVE_ONE, B7_URL, "2",
                            "profile", "600"], budget=b,
                env_extra={"QUORUM_TPU_PROFILE_DIR": prof_dir}))
            if os.path.isdir(prof_dir):
                bank({"profile_artifacts": sum(
                    len(fs) for _, _, fs in os.walk(prof_dir))})
    print(f"[onchip] done — see {OUT}")


if __name__ == "__main__":
    main()
