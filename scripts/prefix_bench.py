"""Prefix-store microbench: multi-turn chat under slot churn, store on/off.

The scenario the slot-resident prefix cache loses: more concurrent
conversations than KV slots, each re-sending its whole history every turn.
Round-robining N conversations over S < N slots guarantees every slot is
reclaimed between a conversation's turns, so the automatic (tier-0) cache
never hits on follow-up turns — exactly the load where prefill capacity
matters. With ``prefix_store=host`` the released prefixes survive in host
RAM and follow-up turns restore them, prefilling only the tail.

Reports, per leg (store off / store on):

  - ``prefill_tokens``        prompt tokens actually prefilled on device
  - ``saved_tokens``          prompt tokens skipped (slot reuse + restores)
  - ``store_hits`` / ``store_restored_tokens`` / ``restore_ms_mean``
  - ``wall_s``                leg wall time
  - ``tokens_match``          every turn's sampled output identical across
                              legs (reuse is a scheduling optimization,
                              never a semantic change)

Usage:  python scripts/prefix_bench.py [--conversations N] [--slots S]
        [--turns T] [--new-tokens G] [--chunk C]
Prints one human-readable block and one machine-parsable JSON line.
``make prefix-bench`` runs it; tests/test_prefix_bench.py is the suite's
fast smoke over the same entry point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable as `python scripts/prefix_bench.py` from a checkout without
# `pip install -e`: the repo root (not scripts/) must be importable.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(conversations: int = 5, slots: int = 2, turns: int = 3,
        new_tokens: int = 6, chunk: int = 16,
        store_bytes: int = 64 << 20) -> dict:
    """Drive ``conversations`` multi-turn chats round-robin over ``slots``
    KV slots, once without and once with the host prefix store; return the
    prefill/restore accounting. Conversations must outnumber slots or
    there is no churn to measure."""
    if conversations <= slots:
        raise ValueError(
            f"conversations ({conversations}) must exceed slots ({slots}) "
            "— without churn the slot-resident cache already wins and the "
            "store never fires")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.models.model_config import resolve_spec
    from quorum_tpu.ops.sampling import SamplerConfig

    # Window sized to the conversation growth so every turn fits:
    # initial 2·chunk history + per-turn (new_tokens + 5) user/reply tokens.
    need = 2 * chunk + turns * (new_tokens + 5) + new_tokens + 1
    max_seq = 64
    while max_seq < need:
        max_seq *= 2
    spec = resolve_spec("llama-tiny", {"max_seq": str(max_seq)})
    greedy = SamplerConfig(temperature=0.0)

    def user_tokens(conv: int, turn: int, n: int = 5) -> list[int]:
        return [(11 + 13 * conv + 7 * turn + 3 * i)
                % (spec.vocab_size - 1) + 1 for i in range(n)]

    out: dict = {"conversations": conversations, "slots": slots,
                 "turns": turns, "new_tokens": new_tokens,
                 "store_chunk": chunk}
    streams: dict[str, list[list[int]]] = {}

    for leg, store in (("off", None), ("on", "host")):
        eng = InferenceEngine(
            spec, decode_chunk=4, prefill_chunk=chunk, n_slots=slots,
            prefix_store=store, prefix_store_bytes=store_bytes,
        )
        histories = {c: [1 + (c * 17 + i * 7) % (spec.vocab_size - 1)
                         for i in range(2 * chunk)]
                     for c in range(conversations)}
        outputs: list[list[int]] = []
        prefilled = 0
        t0 = time.perf_counter()
        for turn in range(turns):
            for c in range(conversations):
                prompt = histories[c]
                saved0 = eng.prefix_tokens_saved + eng.prefix_store_tokens_restored
                res = eng.generate(prompt, max_new_tokens=new_tokens,
                                   sampler=greedy, seed=c)
                saved = (eng.prefix_tokens_saved
                         + eng.prefix_store_tokens_restored - saved0)
                prefilled += len(prompt) - saved
                outputs.append(res.token_ids)
                histories[c] = prompt + res.token_ids + user_tokens(c, turn)
            eng.drain_prefix_store()
        wall = time.perf_counter() - t0
        streams[leg] = outputs
        out[f"{leg}_wall_s"] = round(wall, 4)
        out[f"{leg}_prefill_tokens"] = prefilled
        out[f"{leg}_saved_tokens"] = (eng.prefix_tokens_saved
                                      + eng.prefix_store_tokens_restored)
        out[f"{leg}_store_hits"] = eng.prefix_store_hits
        out[f"{leg}_store_restored_tokens"] = eng.prefix_store_tokens_restored
        out[f"{leg}_restore_ms_mean"] = round(
            1000 * eng.prefix_store_restore_s / eng.prefix_store_hits, 3
        ) if eng.prefix_store_hits else 0.0
        if store:
            out["store_bytes_held"] = eng.prefix_store.bytes_held
            out["store_evictions"] = eng.prefix_store.n_evictions
        eng.shutdown()

    out["prefill_tokens_saved_by_store"] = (
        out["off_prefill_tokens"] - out["on_prefill_tokens"])
    out["tokens_match"] = streams["off"] == streams["on"]
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--conversations", type=int, default=5)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--turns", type=int, default=3)
    ap.add_argument("--new-tokens", type=int, default=6)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--store-bytes", type=int, default=64 << 20)
    args = ap.parse_args()
    if args.conversations <= args.slots:
        ap.error("--conversations must exceed --slots (no churn otherwise)")
    m = run(args.conversations, args.slots, args.turns, args.new_tokens,
            args.chunk, args.store_bytes)
    print(f"prefix-store microbench (llama-tiny, {m['conversations']} "
          f"conversations over {m['slots']} slots, {m['turns']} turns):")
    for leg in ("off", "on"):
        print(f"  store {leg:>3}: {m[f'{leg}_prefill_tokens']} prompt tokens "
              f"prefilled, {m[f'{leg}_saved_tokens']} saved, "
              f"{m[f'{leg}_store_hits']} store hits, "
              f"wall {m[f'{leg}_wall_s']}s")
    print(f"  prefill tokens saved by the store: "
          f"{m['prefill_tokens_saved_by_store']}")
    print(f"  restored tokens: {m['on_store_restored_tokens']} "
          f"(mean restore {m['on_restore_ms_mean']} ms)")
    print(f"  token-for-token identical across legs: {m['tokens_match']}")
    print(json.dumps(m), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
