"""Router bench: prefix-affinity routing vs a random baseline at N replicas.

``make router-bench`` measures what the router tier is FOR — converting
extra replicas into prefix-cache hits instead of cold prefills:

  - **fake legs** (N=2 and N=4, seconds): jax-free scripted replicas
    (quorum_tpu/router/fake_replica.py) carrying a REAL PrefixStore each,
    driven through the real router app over real sockets. Measures
    affinity-vs-random prefix-hit rate with zero engine noise.
  - **real leg** (N=2, minutes on CPU): subprocess replicas serving tiny
    ``tpu://`` engines with ``prefix_store=host`` under slot churn
    (conversations > slots — the regime where the host store carries the
    hits), plus a dedicated single-replica baseline process for
    token-for-token output pinning. ``--skip-real`` / ``--mode fake``
    skips it.

Per leg it reports aggregate tok/s, prefix-hit rate (replica-side
``quorum_tpu_engine_prefix_store_hits_total`` deltas over the turns that
COULD hit — everything after each conversation's first), and per-replica
request spread; the affinity and random legs use disjoint conversation
families so one leg's store warmth cannot subsidize the other.

Acceptance (asserted, exit 1 on failure): affinity hit rate strictly above
random at every N, and per-conversation outputs token-for-token identical
to single-replica serving. ``tests/test_router_bench.py`` runs the fake
leg as a fast smoke inside ``make verify``'s test tier.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("QUORUM_TPU_COMPILE_CACHE", "0")

import httpx  # noqa: E402

REPLICA_BOOT_TIMEOUT_S = 240.0
CONCURRENCY = 4

ENGINE_URL = ("tpu://llama-tiny?seed=7&slots=2&queue=32&decode_chunk=4"
              "&prefill_chunk=16&prefix_store=host&prefix_store_chunk=16"
              "&max_seq=512&max_tokens=24")


def conversation_opening(family: str, i: int) -> str:
    """Distinct per-conversation opening, long enough to cover several
    prefix chunks (the store only retains whole chunks)."""
    return (f"[{family}/conv-{i:02d}] You are assisting with scenario "
            f"number {i} of family {family}. The running context is a "
            "long-lived support conversation whose history must be "
            "retained across turns so the key-value prefix cache can "
            "prove itself. Opening question: what should happen next?")


async def _chat(client: httpx.AsyncClient, base: str, body: dict) -> dict:
    r = await client.post(f"{base}/chat/completions", json=body,
                          headers={"Authorization": "Bearer bench"},
                          timeout=120.0)
    if r.status_code != 200:
        raise RuntimeError(f"chat HTTP {r.status_code}: {r.text[:300]}")
    return r.json()


async def drive_conversations(
    client: httpx.AsyncClient, base: str, *, family: str,
    n_conversations: int, turns: int, max_tokens: int, model: str,
    concurrency: int = CONCURRENCY,
) -> dict:
    """Run the multi-turn conversation load; returns outputs + timing."""
    sem = asyncio.Semaphore(concurrency)
    outputs: dict[int, list[str]] = {}
    total_tokens = 0

    async def one(i: int) -> None:
        nonlocal total_tokens
        msgs = [{"role": "user", "content": conversation_opening(family, i)}]
        outs = []
        for t in range(turns):
            async with sem:
                resp = await _chat(client, base, {
                    "model": model, "messages": msgs,
                    "temperature": 0.0, "max_tokens": max_tokens})
            content = resp["choices"][0]["message"]["content"]
            outs.append(content)
            total_tokens += (resp.get("usage") or {}).get(
                "completion_tokens", 0)
            msgs = msgs + [
                {"role": "assistant", "content": content},
                {"role": "user", "content": f"[{family}] follow-up {t}: "
                                            "and after that?"}]
        outputs[i] = outs

    t0 = time.perf_counter()
    await asyncio.gather(*(one(i) for i in range(n_conversations)))
    wall = time.perf_counter() - t0
    return {"outputs": outputs, "wall_s": wall,
            "completion_tokens": total_tokens,
            "tok_s": total_tokens / wall if wall > 0 else 0.0}


_METRIC_RE = re.compile(
    r'^(quorum_tpu_engine_[a-z_]+)\{backend="([^"]+)"\}\s+([0-9.eE+-]+)$')


async def replica_metrics(client: httpx.AsyncClient, url: str) -> dict:
    out: dict[str, float] = {}
    r = await client.get(f"{url}/metrics", timeout=30.0)
    for line in r.text.splitlines():
        m = _METRIC_RE.match(line.strip())
        if m:
            out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(3))
    return out


async def measure_leg(
    client: httpx.AsyncClient, router_base: str, replica_urls: list[str],
    *, family: str, n_conversations: int, turns: int, max_tokens: int,
    model: str, concurrency: int = CONCURRENCY,
) -> dict:
    """One policy leg: drive the load through the router, report tok/s +
    the replica-side prefix-hit rate over the eligible (non-first) turns."""
    before = [await replica_metrics(client, u) for u in replica_urls]
    run = await drive_conversations(
        client, router_base, family=family,
        n_conversations=n_conversations, turns=turns,
        max_tokens=max_tokens, model=model, concurrency=concurrency)
    after = [await replica_metrics(client, u) for u in replica_urls]
    hits = sum(
        a.get("quorum_tpu_engine_prefix_store_hits_total", 0.0)
        - b.get("quorum_tpu_engine_prefix_store_hits_total", 0.0)
        for a, b in zip(after, before))
    requests = [
        a.get("quorum_tpu_engine_requests_total",
              a.get("quorum_tpu_engine_n_completed", 0.0))
        - b.get("quorum_tpu_engine_requests_total",
                b.get("quorum_tpu_engine_n_completed", 0.0))
        for a, b in zip(after, before)]
    eligible = n_conversations * (turns - 1)
    return {
        "tok_s": round(run["tok_s"], 2),
        "wall_s": round(run["wall_s"], 3),
        "completion_tokens": run["completion_tokens"],
        "prefix_hits": int(hits),
        "eligible_turns": eligible,
        "hit_rate": round(hits / eligible, 4) if eligible else 0.0,
        "requests_per_replica": [int(r) for r in requests],
        "outputs": run["outputs"],
    }


# ---- fake mode (in-process replicas, real sockets) -------------------------


async def _run_fake_async(n_replicas: int, *, n_conversations: int,
                          turns: int, max_tokens: int) -> dict:
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.router.fake_replica import (
        FakeReplicaState,
        create_fake_replica_app,
    )
    from quorum_tpu.server.serve import start_server

    import random as _random

    _random.seed(0)  # the random-policy leg is a REPRODUCIBLE baseline
    out: dict = {"n_replicas": n_replicas}
    legs = {}
    for policy, family in (("affinity", "A"), ("random", "B")):
        # Fresh replicas per leg: store warmth must not cross legs.
        servers, urls = [], []
        for i in range(n_replicas):
            st = FakeReplicaState(f"fake-{i}", max_tokens=max_tokens)
            srv = await start_server(
                create_fake_replica_app(st), "127.0.0.1", 0)
            servers.append(srv)
            urls.append(
                f"http://127.0.0.1:{srv.sockets[0].getsockname()[1]}")
        # Single-replica pinning baseline: its own fresh fake replica.
        base_state = FakeReplicaState("fake-single", max_tokens=max_tokens)
        base_srv = await start_server(
            create_fake_replica_app(base_state), "127.0.0.1", 0)
        base_url = (
            f"http://127.0.0.1:{base_srv.sockets[0].getsockname()[1]}")
        cfg = RouterConfig(
            replicas=[(f"fake-{i}", u) for i, u in enumerate(urls)],
            policy=policy, ready_interval=0.0)
        router_app = create_router_app(cfg)
        router_srv = await start_server(router_app, "127.0.0.1", 0)
        router_url = (
            f"http://127.0.0.1:{router_srv.sockets[0].getsockname()[1]}")
        try:
            async with httpx.AsyncClient() as client:
                # Serial turns: the fake legs measure PLACEMENT (hit
                # rate), and serial driving keeps bounded-load spill out
                # of the picture so the smoke is deterministic; the real
                # leg keeps concurrency for an honest tok/s.
                leg = await measure_leg(
                    client, router_url, urls, family=family,
                    n_conversations=n_conversations, turns=turns,
                    max_tokens=max_tokens, model="fake", concurrency=1)
                single = await drive_conversations(
                    client, base_url, family=family,
                    n_conversations=n_conversations, turns=turns,
                    max_tokens=max_tokens, model="fake")
        finally:
            await app_close(router_app)
            for srv in servers + [base_srv, router_srv]:
                srv.close()
        leg["outputs_pinned_vs_single"] = leg.pop(
            "outputs") == single["outputs"]
        legs[policy] = leg
    out.update(legs)
    out["affinity_gt_random"] = (
        legs["affinity"]["hit_rate"] > legs["random"]["hit_rate"])
    return out


async def app_close(router_app) -> None:
    mgr = router_app.state.get("replica_set")
    if mgr is not None:
        await mgr.aclose()


def run_fake(n_replicas: int = 2, *, n_conversations: int = 8,
             turns: int = 3, max_tokens: int = 8) -> dict:
    """Entry point shared with tests/test_router_bench.py."""
    return asyncio.run(_run_fake_async(
        n_replicas, n_conversations=n_conversations, turns=turns,
        max_tokens=max_tokens))


# ---- zero-loss stream resume legs (ISSUE 19) -------------------------------


async def _stream_and_maybe_break(client: httpx.AsyncClient, base: str,
                                  body: dict, *, break_after: int = 0,
                                  on_break=None) -> dict:
    """Stream ``body`` through ``base``; after ``break_after`` content
    chunks call ``on_break(routed_to)`` once (SIGKILL / scripted abort).
    Returns the delivered text plus the timing the resume leg reports."""
    out = {"text": "", "done": False, "error_chunks": 0, "routed": None,
           "chunks": 0, "broke_at": None, "first_after_break": None}
    async with client.stream(
            "POST", f"{base}/chat/completions", json=body,
            headers={"Authorization": "Bearer bench"},
            timeout=120.0) as resp:
        if resp.status_code != 200:
            raise RuntimeError(f"stream HTTP {resp.status_code}")
        out["routed"] = resp.headers.get("x-routed-to")
        async for line in resp.aiter_lines():
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data.strip() == "[DONE]":
                out["done"] = True
                continue
            ev = json.loads(data)
            choice = (ev.get("choices") or [{}])[0]
            delta = choice.get("delta") or {}
            if choice.get("finish_reason") == "error":
                out["error_chunks"] += 1
            elif delta.get("content"):
                out["text"] += delta["content"]
                out["chunks"] += 1
                if (out["broke_at"] is not None
                        and out["first_after_break"] is None):
                    out["first_after_break"] = time.perf_counter()
                if (on_break is not None and out["broke_at"] is None
                        and out["chunks"] >= break_after):
                    on_break(out["routed"])
                    out["broke_at"] = time.perf_counter()
    return out


def _resume_report(base: dict, got: dict, resumed: int) -> dict:
    """The shared resume-leg report: token-for-token vs the uninterrupted
    run, the client-visible resume gap, and the replayed-journal size from
    the router's recorder event."""
    from quorum_tpu.telemetry.recorder import RECORDER

    events = [e for e in RECORDER.snapshot()
              if e.get("kind") == "router-stream-resume"]
    gap = None
    if got["broke_at"] is not None and got["first_after_break"] is not None:
        gap = got["first_after_break"] - got["broke_at"]
    return {
        "token_exact": (got["text"] == base["text"] and got["done"]
                        and got["error_chunks"] == 0),
        "resumed": resumed,
        "replayed_tokens": events[-1].get("replayed") if events else None,
        "resume_latency_s": round(gap, 4) if gap is not None else None,
        "delivered_tokens": got["chunks"],
    }


async def _run_resume_fake_async(*, max_tokens: int = 40) -> dict:
    """Fake resume leg: two scripted replicas behind the resume-ON
    router; the serving replica dies (scripted abort) mid-stream and the
    client-visible sequence must equal the uninterrupted run."""
    from quorum_tpu.observability import ROUTER_STREAM_RESUMES
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.router.fake_replica import (
        FakeReplicaState,
        create_fake_replica_app,
    )
    from quorum_tpu.server.serve import start_server

    states, servers, urls = [], [], []
    for i in range(2):
        st = FakeReplicaState(f"fake-{i}", max_tokens=max_tokens,
                              chunk_delay=0.01)
        srv = await start_server(create_fake_replica_app(st),
                                 "127.0.0.1", 0)
        states.append(st)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{srv.sockets[0].getsockname()[1]}")
    cfg = RouterConfig(
        replicas=[(f"fake-{i}", u) for i, u in enumerate(urls)],
        policy="affinity", ready_interval=0.0)
    router_app = create_router_app(cfg)
    router_srv = await start_server(router_app, "127.0.0.1", 0)
    router_url = (
        f"http://127.0.0.1:{router_srv.sockets[0].getsockname()[1]}")
    try:
        async with httpx.AsyncClient() as client:
            body = {"model": "fake", "stream": True,
                    "max_tokens": max_tokens,
                    "messages": [{"role": "user", "content":
                                  conversation_opening("R", 0)}]}
            base = await _stream_and_maybe_break(client, router_url, body)
            before = ROUTER_STREAM_RESUMES.value_of(outcome="resumed")

            def scripted_abort(name: str) -> None:
                states[int(name.rsplit("-", 1)[1])].abort_after = 0

            got = await _stream_and_maybe_break(
                client, router_url, body, break_after=4,
                on_break=scripted_abort)
            resumed = int(ROUTER_STREAM_RESUMES.value_of(outcome="resumed")
                          - before)
    finally:
        await app_close(router_app)
        for srv in servers + [router_srv]:
            srv.close()
    return _resume_report(base, got, resumed)


def run_resume_fake(*, max_tokens: int = 40) -> dict:
    """Entry point shared with tests/test_router_bench.py."""
    return asyncio.run(_run_resume_fake_async(max_tokens=max_tokens))


async def _resume_leg(client: httpx.AsyncClient,
                      replicas: list[tuple[str, str]], base_url: str,
                      procs_by_name: dict, *, model: str,
                      max_tokens: int = 24) -> dict:
    """Real resume leg (N=2): SIGKILL the serving replica mid-stream;
    the resumed stream must be token-for-token identical to the
    single-replica baseline. Runs LAST — it leaves a corpse."""
    from quorum_tpu.observability import ROUTER_STREAM_RESUMES
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.server.serve import start_server

    cfg = RouterConfig(replicas=replicas, policy="affinity",
                       ready_interval=0.25, timeout=120.0)
    router_app = create_router_app(cfg)
    router_srv = await start_server(router_app, "127.0.0.1", 0)
    router_url = (
        f"http://127.0.0.1:{router_srv.sockets[0].getsockname()[1]}")
    try:
        body = {"model": model, "stream": True, "temperature": 0.0,
                "max_tokens": max_tokens,
                "messages": [{"role": "user", "content":
                              conversation_opening("Z", 0)}]}
        # the single-replica truth for this conversation
        base = await _stream_and_maybe_break(client, base_url, body)

        def sigkill(name: str) -> None:
            procs_by_name[name].kill()

        before = ROUTER_STREAM_RESUMES.value_of(outcome="resumed")
        got = await _stream_and_maybe_break(
            client, router_url, body, break_after=4, on_break=sigkill)
        resumed = int(ROUTER_STREAM_RESUMES.value_of(outcome="resumed")
                      - before)
    finally:
        await app_close(router_app)
        router_srv.close()
    return _resume_report(base, got, resumed)


# ---- cross-cell quorum legs (docs/quorum.md) -------------------------------


async def _first_byte_latency(client: httpx.AsyncClient, base: str,
                              body: dict) -> float:
    """Seconds from POST to the first streamed content delta — the TTFT a
    quorum client actually experiences (role chunks don't count)."""
    t0 = time.perf_counter()
    async with client.stream(
            "POST", f"{base}/chat/completions", json={**body, "stream": True},
            headers={"Authorization": "Bearer bench"},
            timeout=120.0) as resp:
        if resp.status_code != 200:
            raise RuntimeError(f"stream HTTP {resp.status_code}")
        async for line in resp.aiter_lines():
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data.strip() == "[DONE]":
                break
            ev = json.loads(data)
            delta = (ev.get("choices") or [{}])[0].get("delta") or {}
            if delta.get("content"):
                return time.perf_counter() - t0
    raise RuntimeError("stream produced no content delta")


async def _quorum_measurements(client: httpx.AsyncClient, base: str, *,
                               model: str, max_tokens: int, iters: int,
                               quorum: int, family: str) -> dict:
    """The fan-out latency A/B shared by the fake and real quorum legs:
    p50 first-content-byte latency of plain requests vs ``quorum: M``
    through the same router, plus one non-streaming combine's shape."""
    def body(i: int, **kw) -> dict:
        return {"model": model, "temperature": 0.0,
                "max_tokens": max_tokens, **kw,
                "messages": [{"role": "user", "content":
                              conversation_opening(family, i)}]}

    single = [await _first_byte_latency(client, base, body(i))
              for i in range(iters)]
    fanned = [await _first_byte_latency(client, base,
                                        body(i, quorum=quorum))
              for i in range(iters)]
    single_p50 = sorted(single)[len(single) // 2]
    quorum_p50 = sorted(fanned)[len(fanned) // 2]

    r = await client.post(f"{base}/chat/completions",
                          json=body(0, quorum=quorum),
                          headers={"Authorization": "Bearer bench"},
                          timeout=120.0)
    combined = r.json()
    q = combined.get("quorum") or {}
    return {
        "single_ttft_p50_s": round(single_p50, 4),
        "quorum_ttft_p50_s": round(quorum_p50, 4),
        "ttft_ratio": round(quorum_p50 / single_p50, 3)
        if single_p50 > 0 else None,
        "ttft_delta_s": round(quorum_p50 - single_p50, 4),
        "combine_status": r.status_code,
        "combine_outcome": ("full" if q.get("served") == quorum
                            else "degraded" if q.get("served")
                            else "failed"),
        "combine_served": q.get("served"),
        "combined_content": combined.get("choices", [{}])[0]
        .get("message", {}).get("content", ""),
    }


def _ttft_within_gate(leg: dict, *, ratio: float = 1.5,
                      slack_s: float = 0.05) -> bool:
    """The fan-out latency gate: quorum p50 TTFT within ``ratio``× the
    single-member p50 — with a small absolute floor so sub-millisecond
    fake TTFTs don't fail on scheduling jitter alone."""
    return (leg["ttft_ratio"] is not None
            and (leg["ttft_ratio"] <= ratio
                 or leg["ttft_delta_s"] <= slack_s))


async def _run_quorum_fake_async(*, iters: int = 10,
                                 max_tokens: int = 12) -> dict:
    """Fake quorum leg: 4 scripted replicas (20 ms first-byte floor so the
    TTFT ratio measures fan-out overhead, not socket jitter) behind the
    real router. Measures the latency A/B, pins the combine against the
    replicas' deterministic completion, then degrades: shedding one
    assigned member must stay full (spare covers), shedding the spare too
    must serve degraded — never fail."""
    from quorum_tpu.observability import QUORUM_DEGRADED
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.router.fake_replica import (
        FakeReplicaState,
        create_fake_replica_app,
        deterministic_completion,
    )
    from quorum_tpu.server.serve import start_server

    states, servers, urls = [], [], []
    for i in range(4):
        st = FakeReplicaState(f"fake-{i}", max_tokens=max_tokens,
                              chunk_delay=0.02)
        srv = await start_server(create_fake_replica_app(st),
                                 "127.0.0.1", 0)
        states.append(st)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{srv.sockets[0].getsockname()[1]}")
    cfg = RouterConfig(
        replicas=[(f"fake-{i}", u) for i, u in enumerate(urls)],
        policy="affinity", ready_interval=0.0)
    router_app = create_router_app(cfg)
    router_srv = await start_server(router_app, "127.0.0.1", 0)
    router_url = (
        f"http://127.0.0.1:{router_srv.sockets[0].getsockname()[1]}")
    try:
        async with httpx.AsyncClient() as client:
            out = await _quorum_measurements(
                client, router_url, model="fake", max_tokens=max_tokens,
                iters=iters, quorum=3, family="Q")
            prompt = conversation_opening("Q", 0)
            rendered = states[0].tokenizer.render_chat(
                [{"role": "user", "content": prompt}])
            want = "".join(deterministic_completion(rendered, max_tokens))
            out["combined_pinned"] = (
                out.pop("combined_content")
                == cfg.quorum_separator.join([want] * 3))

            # member-kill: shed one serving member → the spare covers
            body = {"model": "fake", "temperature": 0.0,
                    "max_tokens": max_tokens, "quorum": 3,
                    "messages": [{"role": "user", "content": prompt}]}
            r0 = await client.post(f"{router_url}/chat/completions",
                                   json=body, timeout=120.0)
            assigned = r0.headers["x-quorum-replicas"].split(",")
            spare = [f"fake-{i}" for i in range(4)
                     if f"fake-{i}" not in assigned][0]
            by_name = {st.name: st for st in states}
            by_name[assigned[0]].shedding = True
            t0 = time.perf_counter()
            r1 = await client.post(f"{router_url}/chat/completions",
                                   json=body, timeout=120.0)
            out["kill_with_spare_latency_s"] = round(
                time.perf_counter() - t0, 4)
            out["kill_with_spare_outcome"] = (
                "full" if r1.json().get("quorum", {}).get("served") == 3
                else "degraded")

            # ...and with the spare gone too: served degraded, never failed
            by_name[spare].shedding = True
            before = QUORUM_DEGRADED.value
            t0 = time.perf_counter()
            r2 = await client.post(f"{router_url}/chat/completions",
                                   json=body, timeout=120.0)
            out["degraded_latency_s"] = round(time.perf_counter() - t0, 4)
            out["degraded_status"] = r2.status_code
            out["degraded_served"] = r2.json().get(
                "quorum", {}).get("served")
            out["degraded_reason"] = r2.headers.get("x-quorum-degraded")
            out["degraded_counted"] = QUORUM_DEGRADED.value > before
    finally:
        await app_close(router_app)
        for srv in servers + [router_srv]:
            srv.close()
    return out


def run_quorum_fake(*, iters: int = 10, max_tokens: int = 12) -> dict:
    """Entry point shared with tests/test_router_bench.py."""
    return asyncio.run(_run_quorum_fake_async(
        iters=iters, max_tokens=max_tokens))


async def _quorum_leg(client: httpx.AsyncClient,
                      replicas: list[tuple[str, str]], *, model: str,
                      max_tokens: int, iters: int = 5) -> dict:
    """Real quorum leg: quorum=3 over three live engine cells (the two
    bench replicas + the baseline, enrolled as a third ring member —
    identical engines, so the combine pins against 3× one member's
    greedy output). Runs before the resume leg, which leaves a corpse."""
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.server.serve import start_server

    cfg = RouterConfig(replicas=replicas, policy="affinity",
                       ready_interval=0.0, timeout=120.0)
    router_app = create_router_app(cfg)
    router_srv = await start_server(router_app, "127.0.0.1", 0)
    router_url = (
        f"http://127.0.0.1:{router_srv.sockets[0].getsockname()[1]}")
    try:
        out = await _quorum_measurements(
            client, router_url, model=model, max_tokens=max_tokens,
            iters=iters, quorum=3, family="QR")
        # identical engines + temperature 0 → every member emits the same
        # answer; the combine must be exactly three copies of it
        single = await _chat(client, replicas[0][1], {
            "model": model, "temperature": 0.0, "max_tokens": max_tokens,
            "messages": [{"role": "user", "content":
                          conversation_opening("QR", 0)}]})
        want = single["choices"][0]["message"]["content"]
        out["combined_pinned"] = (
            out.pop("combined_content")
            == cfg.quorum_separator.join([want] * 3))
    finally:
        await app_close(router_app)
        router_srv.close()
    return out


# ---- real mode (subprocess tpu:// engine replicas) -------------------------


def _spawn_replica(name: str, model: str,
                   extra_env: dict | None = None
                   ) -> tuple[subprocess.Popen, str]:
    """Spawn one real serving replica (tiny CPU engine, host prefix
    store); returns (process, base url) once it prints PORT=."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               QUORUM_TPU_COMPILE_CACHE="0", **(extra_env or {}))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve-replica",
         "--replica-name", name, "--replica-model", model],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    deadline = time.time() + REPLICA_BOOT_TIMEOUT_S
    port = None
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
    if port is None:
        proc.kill()
        raise RuntimeError(f"replica {name} never printed PORT=")
    return proc, f"http://127.0.0.1:{port}"


def serve_replica_main(name: str, model: str) -> None:
    """Child entry (--serve-replica): a full serving app over one tiny
    real engine, bound to an ephemeral port, PORT= printed for the
    parent."""
    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app
    from quorum_tpu.server.serve import start_server

    cfg = Config(raw={
        "settings": {"timeout": 120},
        "primary_backends": [
            {"name": name, "url": ENGINE_URL, "model": model}],
    })
    app = create_app(cfg, watch_config=False)

    async def _main() -> None:
        server = await start_server(app, "127.0.0.1", 0)
        print(f"PORT={server.sockets[0].getsockname()[1]}", flush=True)
        async with server:
            await server.serve_forever()

    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        pass


async def _run_real_async(n_replicas: int, *, n_conversations: int,
                          turns: int, max_tokens: int) -> dict:
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.server.serve import start_server

    model = "rb"
    procs: list[subprocess.Popen] = []
    out: dict = {"n_replicas": n_replicas}
    try:
        print(f"[router-bench] booting {n_replicas} real replicas + "
              "1 baseline (tiny CPU engines; first compile dominates)",
              flush=True)
        replicas = []
        for i in range(n_replicas):
            # real-0 gets a microsecond interactive TTFT/gap target: the
            # fleet leg saturates ITS interactive burn with real scored
            # requests (no fake telemetry) to drive burn-aware demotion.
            # Observational only — the measured legs' requests carry no
            # deadline, classify as batch, and never touch these targets.
            extra = ({"QUORUM_TPU_SLO_TTFT_INTERACTIVE_S": "0.000001",
                      "QUORUM_TPU_SLO_GAP_INTERACTIVE_S": "0.000001"}
                     if i == 0 else None)
            proc, url = _spawn_replica(f"real-{i}", model, extra_env=extra)
            procs.append(proc)
            replicas.append((f"real-{i}", url))
        base_proc, base_url = _spawn_replica("real-single", model)
        procs.append(base_proc)

        legs = {}
        async with httpx.AsyncClient() as client:
            # Warm every replica's XLA programs with a throwaway family
            # BEFORE the measured legs — otherwise whichever leg runs
            # first eats the cold compiles and its tok/s is meaningless.
            for url in [u for _, u in replicas] + [base_url]:
                await drive_conversations(
                    client, url, family="W", n_conversations=2, turns=2,
                    max_tokens=max_tokens, model=model)
            for policy, family in (("affinity", "A"), ("random", "B")):
                cfg = RouterConfig(replicas=replicas, policy=policy,
                                   ready_interval=0.0)
                router_app = create_router_app(cfg)
                router_srv = await start_server(router_app, "127.0.0.1", 0)
                router_url = ("http://127.0.0.1:"
                              f"{router_srv.sockets[0].getsockname()[1]}")
                try:
                    leg = await measure_leg(
                        client, router_url,
                        [u for _, u in replicas], family=family,
                        n_conversations=n_conversations, turns=turns,
                        max_tokens=max_tokens, model=model)
                    single = await drive_conversations(
                        client, base_url, family=family,
                        n_conversations=n_conversations, turns=turns,
                        max_tokens=max_tokens, model=model)
                finally:
                    await app_close(router_app)
                    router_srv.close()
                leg["outputs_pinned_vs_single"] = leg.pop(
                    "outputs") == single["outputs"]
                legs[policy] = leg
                print(f"[router-bench] real N={n_replicas} {policy}: "
                      f"hit_rate={leg['hit_rate']} tok/s={leg['tok_s']} "
                      f"pinned={leg['outputs_pinned_vs_single']}",
                      flush=True)
        out.update(legs)
        out["affinity_gt_random"] = (
            legs["affinity"]["hit_rate"] > legs["random"]["hit_rate"])

        # ---- fleet observability leg (docs/observability.md) ---------
        # Same live replicas: (1) one sampled request's trace-id must
        # name it across the router's route event, the serving replica's
        # spans, and the engine's dispatch/reap in the MERGED fleet
        # timeline; (2) saturating real-0's interactive burn with real
        # scored requests must measurably cost it placements — demotion
        # counter up, every family-G request served by real-1, outputs
        # still token-for-token identical to single-replica serving.
        async with httpx.AsyncClient() as client:
            out["fleet"] = await _fleet_leg(
                client, replicas, base_url, model=model,
                max_tokens=max_tokens)
            print(f"[router-bench] real N={n_replicas} fleet: "
                  f"{json.dumps(out['fleet'])}", flush=True)

        # ---- cross-cell quorum leg (docs/quorum.md): the baseline
        # enrolls as a third ring member for a real 3-cell fan-out
        async with httpx.AsyncClient() as client:
            out["quorum"] = await _quorum_leg(
                client, replicas + [("real-single", base_url)],
                model=model, max_tokens=max_tokens)
            print(f"[router-bench] real N=3 quorum: "
                  f"{json.dumps(out['quorum'])}", flush=True)

        # ---- zero-loss resume leg (ISSUE 19) — LAST: it kills a replica
        procs_by_name = {name: proc
                         for (name, _), proc in zip(replicas, procs)}
        async with httpx.AsyncClient() as client:
            out["resume"] = await _resume_leg(
                client, replicas, base_url, procs_by_name, model=model)
            print(f"[router-bench] real N={n_replicas} resume: "
                  f"{json.dumps(out['resume'])}", flush=True)
    finally:
        for proc in procs:
            proc.kill()
        for proc in procs:
            proc.wait(timeout=30)
    return out


async def _fleet_leg(client: httpx.AsyncClient,
                     replicas: list[tuple[str, str]], base_url: str, *,
                     model: str, max_tokens: int) -> dict:
    from quorum_tpu.router.app import RouterConfig, create_router_app
    from quorum_tpu.server.serve import start_server

    # burn_threshold 0.4: a saturated replica's interactive window is
    # all-breached TTFT (+ gap when sampled) against good deadlines —
    # burn lands in [0.5, 0.67], comfortably above.
    cfg = RouterConfig(replicas=replicas, policy="affinity",
                       ready_interval=0.0, burn_threshold=0.4)
    router_app = create_router_app(cfg)
    mgr = router_app.state["replica_set"]
    router_srv = await start_server(router_app, "127.0.0.1", 0)
    router_url = (
        f"http://127.0.0.1:{router_srv.sockets[0].getsockname()[1]}")
    leg: dict = {}
    try:
        await mgr.poll_once()  # absorb telemetry + clock offsets

        # (1) trace continuity: sample one request through the router
        r = await client.post(
            f"{router_url}/chat/completions",
            json={"model": model, "temperature": 0.0,
                  "max_tokens": max_tokens,
                  "messages": [{"role": "user", "content":
                                conversation_opening("T", 0)}]},
            headers={"Authorization": "Bearer bench"}, timeout=120.0)
        trace_id = r.headers.get("x-request-id", "")
        served_by = r.headers.get("x-routed-to", "")
        fleet = (await client.get(
            f"{router_url}/debug/fleet/timeline", timeout=30.0)).json()
        # per-request events carry rid; the engine's batched
        # dispatch/reap carry the member list in rids
        mine = [ev for ev in fleet["events"]
                if ev.get("rid") == trace_id
                or trace_id in (ev.get("rids") or [])]
        kinds_by_proc: dict[str, set] = {}
        for ev in mine:
            kinds_by_proc.setdefault(ev["process"], set()).add(ev["kind"])
        leg["sampled_trace_id"] = trace_id
        leg["trace_kinds_by_process"] = {
            p: sorted(k) for p, k in kinds_by_proc.items()}
        leg["trace_joined"] = (
            r.status_code == 200 and len(trace_id) == 32
            and "router-route" in kinds_by_proc.get("router", set())
            and {"admit", "dispatch", "reap"} <= kinds_by_proc.get(
                served_by, set()))

        # (2) burn saturation: real interactive streams at real-0 breach
        # its microsecond TTFT/gap targets; its scored burn demotes it
        burn_url = dict(replicas)["real-0"]
        for i in range(6):
            resp = await client.post(
                f"{burn_url}/chat/completions",
                json={"model": model, "temperature": 0.0, "timeout": 5,
                      "stream": True, "max_tokens": 4,
                      "messages": [{"role": "user", "content":
                                    conversation_opening("S", i)}]},
                headers={"Authorization": "Bearer bench"}, timeout=120.0)
            resp.raise_for_status()
        tele = (await client.get(f"{burn_url}/debug/telemetry",
                                 timeout=30.0)).json()
        leg["real0_interactive_burn"] = (
            tele["slo"].get("interactive") or {}).get("burn_rate")
        await mgr.poll_once()
        demotions_before = mgr.n_burn_demotions
        leg["burn_demoted"] = sorted(mgr.burn_demoted())
        routed_through = await measure_leg(
            client, router_url, [u for _, u in replicas], family="G",
            n_conversations=4, turns=2, max_tokens=max_tokens,
            model=model)
        single = await drive_conversations(
            client, base_url, family="G", n_conversations=4, turns=2,
            max_tokens=max_tokens, model=model)
        leg["burn_demotions"] = mgr.n_burn_demotions - demotions_before
        # the demoted replica lost every placement: real-1 served all
        leg["requests_per_replica"] = routed_through[
            "requests_per_replica"]
        real0_idx = [n for n, _ in replicas].index("real-0")
        leg["demoted_lost_placements"] = (
            leg["burn_demotions"] > 0
            and routed_through["requests_per_replica"][real0_idx] == 0)
        leg["outputs_pinned_vs_single"] = (
            routed_through["outputs"] == single["outputs"])
        del routed_through["outputs"]
    finally:
        await app_close(router_app)
        router_srv.close()
    return leg


def run_real(n_replicas: int = 2, *, n_conversations: int = 8,
             turns: int = 3, max_tokens: int = 16) -> dict:
    return asyncio.run(_run_real_async(
        n_replicas, n_conversations=n_conversations, turns=turns,
        max_tokens=max_tokens))


# ---- CLI --------------------------------------------------------------------


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--mode", choices=("fake", "real", "all"),
                        default="all")
    parser.add_argument("--skip-real", action="store_true",
                        help="alias for --mode fake")
    parser.add_argument("--conversations", type=int, default=8)
    parser.add_argument("--turns", type=int, default=3)
    parser.add_argument("--tokens", type=int, default=16)
    parser.add_argument("--serve-replica", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--replica-name", default="replica",
                        help=argparse.SUPPRESS)
    parser.add_argument("--replica-model", default="rb",
                        help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args.serve_replica:
        serve_replica_main(args.replica_name, args.replica_model)
        return 0

    mode = "fake" if args.skip_real else args.mode
    out: dict = {}
    failures = []
    if mode in ("fake", "all"):
        out["fake"] = {}
        for n in (2, 4):
            leg = run_fake(n, n_conversations=args.conversations,
                           turns=args.turns, max_tokens=8)
            out["fake"][f"n{n}"] = leg
            print(f"[router-bench] fake N={n}: affinity hit_rate="
                  f"{leg['affinity']['hit_rate']} vs random "
                  f"{leg['random']['hit_rate']}", flush=True)
            if not leg["affinity_gt_random"]:
                failures.append(f"fake n{n}: affinity hit rate not above "
                                "random")
            if not leg["affinity"]["outputs_pinned_vs_single"]:
                failures.append(f"fake n{n}: outputs diverged from "
                                "single-replica serving")
        q = run_quorum_fake()
        out["fake"]["quorum"] = q
        print(f"[router-bench] fake quorum: ttft {q['single_ttft_p50_s']}s "
              f"-> {q['quorum_ttft_p50_s']}s ({q['ttft_ratio']}x), "
              f"combine={q['combine_outcome']} "
              f"degraded_served={q['degraded_served']}", flush=True)
        if not _ttft_within_gate(q):
            failures.append("fake quorum: quorum=3 p50 TTFT not within "
                            f"1.5x single-member ({json.dumps(q)})")
        if not (q["combine_outcome"] == "full" and q["combined_pinned"]):
            failures.append("fake quorum: healthy combine not full/pinned")
        if q["kill_with_spare_outcome"] != "full":
            failures.append("fake quorum: spare did not cover a killed "
                            "member")
        if not (q["degraded_status"] == 200 and q["degraded_served"] == 2
                and q["degraded_counted"]):
            failures.append("fake quorum: member kill without spare did "
                            "not serve degraded")
    if mode in ("real", "all"):
        leg = run_real(2, n_conversations=args.conversations,
                       turns=args.turns, max_tokens=args.tokens)
        out["real"] = {"n2": leg}
        if not leg["affinity_gt_random"]:
            failures.append("real n2: affinity hit rate not above random")
        if not leg["affinity"]["outputs_pinned_vs_single"]:
            failures.append("real n2: outputs diverged from "
                            "single-replica serving")
        fleet = leg.get("fleet", {})
        if not fleet.get("trace_joined"):
            failures.append("real n2 fleet: sampled trace-id not joined "
                            "across router + replica + engine in the "
                            "merged timeline")
        if not fleet.get("demoted_lost_placements"):
            failures.append("real n2 fleet: burn-saturated replica did "
                            "not measurably lose placements")
        if not fleet.get("outputs_pinned_vs_single"):
            failures.append("real n2 fleet: outputs diverged under burn "
                            "demotion")
        quorum = leg.get("quorum", {})
        # wider absolute slack than the fake leg: real CPU-engine TTFTs
        # wobble by tens of ms run to run
        if not _ttft_within_gate(quorum, slack_s=0.25):
            failures.append("real quorum: quorum=3 p50 TTFT not within "
                            f"1.5x single-member ({json.dumps(quorum)})")
        if not (quorum.get("combine_outcome") == "full"
                and quorum.get("combined_pinned")):
            failures.append("real quorum: combine not full/pinned "
                            f"({json.dumps(quorum)})")
        resume = leg.get("resume", {})
        if not (resume.get("token_exact") and resume.get("resumed")):
            failures.append("real n2 resume: mid-stream kill did not "
                            "resume token-for-token vs single-replica "
                            f"({json.dumps(resume)})")
    out["failures"] = failures
    print(json.dumps(out), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
