"""Supervise the axon TPU tunnel and fire the on-chip runbook on recovery.

The round-3/4 failure mode: the tunnel is dead for hours and every manual
probe misses the recovery window. This watcher loops a cheap subprocess
probe (the same marker discipline as bench._probe_device — PROBE_OK on a
non-cpu backend, any-line scan) and the moment the chip answers it runs
``scripts/onchip_session.py`` (which banks every measurement to
ONCHIP.json as it lands) and commits the artifact.

Safety:
- ``--launch-deadline-s`` (default 4 h): after this, the watcher EXITS
  instead of launching a multi-hour session — the driver's own
  end-of-round bench must find the chip free, and a mid-computation kill
  can wedge the tunnel for everyone.
- One successful session → commit ONCHIP.json → exit.
- Probes are short subprocesses; the watcher itself never touches jax.

Run detached:  nohup python scripts/tunnel_watch.py > /tmp/tunnel_watch.log 2>&1 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ONCHIP = os.path.join(REPO, "ONCHIP.json")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# One probe/kill discipline for the whole toolchain (bench._probe_device
# pinned the probe in round 3; onchip_session carries the shared helpers) —
# a watcher with its own copies could disagree with the session about
# liveness, or kill only part of a process tree.
from onchip_session import kill_process_tree, probe  # noqa: E402


def _mtime(path: str) -> float:
    try:
        return os.stat(path).st_mtime
    except OSError:
        return 0.0


def commit_onchip(started_after: float) -> bool:
    """Commit ONCHIP.json iff THIS session refreshed it; honest rc checks.

    ``started_after``: the artifact's mtime before the session — an
    unchanged file means the session died before banking anything, and a
    stale artifact from an earlier run must not be committed under a
    message claiming fresh results."""
    if _mtime(ONCHIP) <= started_after:
        print("[watch] session banked nothing new — not committing",
              flush=True)
        return False
    try:
        with open(ONCHIP) as f:
            got = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("[watch] no readable ONCHIP.json to commit", flush=True)
        return False
    n_metrics = sum(
        1 for k, v in got.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
        and v > 0  # headline sentinels (value -1.0) are not measurements
        and k not in ("ts", "onchip_started_ts")
        and not k.endswith("_wall_s"))  # diagnostics, not measurements
    if n_metrics == 0:
        # A dead-at-start session banks only an error record + timestamps;
        # committing that as "results" would be dishonest.
        print("[watch] artifact has no measurements — not committing",
              flush=True)
        return False
    add = subprocess.run(["git", "add", "ONCHIP.json"], cwd=REPO)
    # ``-- ONCHIP.json`` scopes the commit to the artifact alone: anything
    # else the operator had staged must not be swept into this commit.
    commit = subprocess.run(
        ["git", "commit", "-m",
         f"ONCHIP: on-chip session results ({n_metrics} numeric keys)",
         "--", "ONCHIP.json"],
        cwd=REPO)
    ok = add.returncode == 0 and commit.returncode == 0
    print(f"[watch] commit of ONCHIP.json ({n_metrics} numeric keys): "
          f"{'ok' if ok else 'FAILED'}", flush=True)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval-s", type=int, default=300,
                    help="seconds between probes while the tunnel is dead")
    ap.add_argument("--launch-deadline-s", type=int, default=4 * 3600,
                    help="stop launching sessions this long after start "
                         "(leave the chip free for the driver's own bench)")
    ap.add_argument("--session-budget-s", type=int, default=6 * 3600,
                    help="hard cap on one onchip_session run")
    ap.add_argument("--hard-end-s", type=int, default=0,
                    help="absolute cap from watcher start: a launched "
                         "session's budget is TRIMMED so it cannot still "
                         "hold the single-holder TPU client past this "
                         "point (0 = launch deadline + session budget)")
    args = ap.parse_args()

    t0 = time.time()
    hard_end = t0 + (args.hard_end_s
                     or args.launch_deadline_s + args.session_budget_s)
    # No point probing past the moment a launch could no longer get a
    # useful (≥1800 s) budget.
    deadline = min(t0 + args.launch_deadline_s, hard_end - 2100)
    n = 0
    while time.time() < deadline:
        n += 1
        if probe():
            budget = int(min(args.session_budget_s,
                             hard_end - time.time() - 300))
            if budget < 1800:
                print("[watch] tunnel alive but too close to the hard end "
                      "for a useful session — leaving the chip free",
                      flush=True)
                return 0
            print(f"[watch] probe {n}: ALIVE — launching onchip_session "
                  f"(budget {budget}s)", flush=True)
            before = _mtime(ONCHIP)
            rc = None
            # The session plans its own steps inside this budget and exits
            # cleanly (QUORUM_TPU_ONCHIP_BUDGET); the group kill below is
            # only the backstop for a wedged session, not the mechanism.
            env = dict(os.environ)
            env["QUORUM_TPU_ONCHIP_BUDGET"] = str(budget)
            proc = subprocess.Popen(
                [sys.executable, os.path.join("scripts",
                                              "onchip_session.py")],
                cwd=REPO, env=env, start_new_session=True)
            try:
                # Backstop only (the session plans inside its budget); the
                # wait can never extend past the operator's hard end —
                # that is the whole point of --hard-end-s.
                rc = proc.wait(timeout=max(
                    1.0, min(budget + 600, hard_end - time.time())))
            except subprocess.TimeoutExpired:
                kill_process_tree(proc.pid)
                proc.wait()
                print("[watch] onchip_session wedged past its budget — "
                      "killed its process tree; committing whatever was "
                      "banked before the wedge", flush=True)
            committed = commit_onchip(started_after=before)
            if committed:
                return 0
            if rc == 3:
                # Tunnel died again at session start — keep watching.
                print("[watch] session found the tunnel dead; resuming "
                      "the probe loop", flush=True)
                continue
            return 1
        left = deadline - time.time()
        print(f"[watch] probe {n}: dead ({left/60:.0f} min of launch "
              f"window left)", flush=True)
        time.sleep(min(args.interval_s, max(1.0, left)))
    print("[watch] launch window closed — exiting (chip left free for "
          "the driver)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
