"""Supervise the axon TPU tunnel and fire the on-chip runbook on recovery.

The round-3/4 failure mode: the tunnel is dead for hours and every manual
probe misses the recovery window. This watcher loops a cheap subprocess
probe (the same marker discipline as bench._probe_device — PROBE_OK on a
non-cpu backend, any-line scan) and the moment the chip answers it runs
``scripts/onchip_session.py`` (which banks every measurement to
ONCHIP.json as it lands) and commits the artifact.

Safety:
- ``--launch-deadline-s`` (default 4 h): after this, the watcher EXITS
  instead of launching a multi-hour session — the driver's own
  end-of-round bench must find the chip free, and a mid-computation kill
  can wedge the tunnel for everyone.
- One successful session → commit ONCHIP.json → exit.
- Probes are short subprocesses; the watcher itself never touches jax.

Run detached:  nohup python scripts/tunnel_watch.py > /tmp/tunnel_watch.log 2>&1 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ONCHIP = os.path.join(REPO, "ONCHIP.json")


def probe(budget: int = 150) -> bool:
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "x = jnp.ones((256,256), jnp.bfloat16);"
             "(x @ x).block_until_ready();"
             "print('PROBE_OK', jax.default_backend())"],
            capture_output=True, text=True, timeout=budget)
    except subprocess.TimeoutExpired:
        return False
    if p.returncode != 0:
        return False
    return any(
        ln.startswith("PROBE_OK") and not ln.rstrip().endswith(" cpu")
        for ln in (p.stdout or "").splitlines())


def commit_onchip() -> None:
    try:
        with open(ONCHIP) as f:
            got = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("[watch] no readable ONCHIP.json to commit", flush=True)
        return
    n_metrics = sum(1 for v in got.values() if isinstance(v, (int, float)))
    subprocess.run(["git", "add", "ONCHIP.json"], cwd=REPO)
    subprocess.run(
        ["git", "commit", "-m",
         f"ONCHIP: on-chip session results ({n_metrics} numeric keys)"],
        cwd=REPO)
    print(f"[watch] committed ONCHIP.json ({n_metrics} numeric keys)",
          flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval-s", type=int, default=300,
                    help="seconds between probes while the tunnel is dead")
    ap.add_argument("--launch-deadline-s", type=int, default=4 * 3600,
                    help="stop launching sessions this long after start "
                         "(leave the chip free for the driver's own bench)")
    ap.add_argument("--session-budget-s", type=int, default=6 * 3600,
                    help="hard cap on one onchip_session run")
    args = ap.parse_args()

    deadline = time.time() + args.launch_deadline_s
    n = 0
    while time.time() < deadline:
        n += 1
        if probe():
            print(f"[watch] probe {n}: ALIVE — launching onchip_session",
                  flush=True)
            try:
                subprocess.run(
                    [sys.executable, os.path.join("scripts",
                                                  "onchip_session.py")],
                    cwd=REPO, timeout=args.session_budget_s)
            except subprocess.TimeoutExpired:
                print("[watch] onchip_session exceeded its budget",
                      flush=True)
            commit_onchip()
            return 0
        left = deadline - time.time()
        print(f"[watch] probe {n}: dead ({left/60:.0f} min of launch "
              f"window left)", flush=True)
        time.sleep(min(args.interval_s, max(1.0, left)))
    print("[watch] launch window closed — exiting (chip left free for "
          "the driver)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
