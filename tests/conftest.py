"""Shared test configuration.

JAX-dependent tests run on CPU with a virtual 8-device mesh — the standard way
to exercise sharding logic without TPU hardware (see SURVEY.md §4). The env vars
must be set before the first ``import jax`` anywhere in the test process, hence
this conftest sets them at import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


# Minimal built-in async-test support (pytest-asyncio is not in this image):
# run ``async def`` tests via asyncio.run.
@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    if inspect.iscoroutinefunction(pyfuncitem.obj):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(pyfuncitem.obj(**kwargs))
        return True
    return None
