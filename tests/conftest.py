"""Shared test configuration.

JAX-dependent tests run on CPU with a virtual 8-device mesh — the standard way
to exercise sharding logic without TPU hardware (see SURVEY.md §4). The env vars
must be set before the first ``import jax`` anywhere in the test process, hence
this conftest sets them at import time.
"""

import os

# Force, don't setdefault: this image exports JAX_PLATFORMS=axon and a
# sitecustomize that imports jax and registers the real TPU at interpreter
# startup (before conftest runs). Tests must run on the virtual CPU mesh, so
# flip the already-imported jax config — backends initialize lazily, so this
# is effective as long as no jax computation has run yet.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compile cache: OFF for the suite. It used to default on
# here for warm-run speed, and that was the root cause of the flaky
# determinism failures in tests/test_engine.py (and friends): with the
# cache enabled, the FIRST generation on a fresh engine occasionally runs a
# decode program deserialized from an entry another engine instance's
# compile wrote, while later calls recompile a layout-specialized variant —
# two numerically different (both valid) executables of the same program,
# whose float reassociation flips near-tie samples. Two identical
# back-to-back generations then disagree (reproduced ~50% per engine with
# the cache on, 0/12 with it off; see compile_cache.py's CPU caveat).
# Correctness of the determinism contract beats warm-suite time; an
# explicit QUORUM_TPU_COMPILE_CACHE=<dir> in the env still wins for anyone
# who wants the speed and accepts the flake.
os.environ.setdefault("QUORUM_TPU_COMPILE_CACHE", "0")

# Runtime sync sentinel (docs/static_analysis.md): every engine in the
# suite runs its decode loop under jax.transfer_guard("disallow") — an
# implicit host<->device transfer on the token critical path raises
# instead of silently stalling the dispatch ring. The static half is
# `make qlint`; an explicit QUORUM_TPU_TRANSFER_GUARD in the env wins.
os.environ.setdefault("QUORUM_TPU_TRANSFER_GUARD", "disallow")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Sharding-invariant RNG, process-wide and FIRST (before any traced random
# op): on jax 0.4.x the non-partitionable default produces wrong values for
# row-sharded random outputs on multi-axis meshes — the dryrun dp2·sp2·tp2
# embed divergence. quorum_tpu.models.init flips it at import too; doing it
# here as well guarantees every test module (even ones that never touch
# models/) runs the same RNG semantics newer jax defaults to.
try:
    jax.config.update("jax_threefry_partitionable", True)
except AttributeError:  # newer jax: flag retired, always partitionable
    pass

# Lowering-counter hook (quorum_tpu/analysis/compile_watch.py): registered
# before any engine exists so compiles_total() covers the whole suite. The
# warmed-engine zero-recompile sentinel in tests/test_qlint.py snapshots it
# around a second identical generation — any new program family (a cache-key
# drift compile_budget.json missed) fails loudly.
from quorum_tpu.analysis import compile_watch  # noqa: E402

compile_watch.install()

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _shutdown_engines_between_modules():
    """Join every engine's scheduler thread and drop its device state after
    each test module. Without this the suite accumulates dozens of live
    threads + parameter/cache buffers across ~30 modules, and a straggler
    thread running device work while the next module compiles can segfault
    XLA's CPU client (observed on the 1-core CI box)."""
    yield
    from quorum_tpu.engine.engine import shutdown_all_engines

    shutdown_all_engines()


def make_client(config_raw: dict, **fake_backends):
    """Build the ASGI app over FakeBackends and an httpx client bound to it.

    The idiomatic replacement for the reference suite's httpx monkeypatching
    (see SURVEY.md §4): tests inject Backend-protocol doubles by name.
    """
    import httpx

    from quorum_tpu.config import Config
    from quorum_tpu.server.app import create_app

    app = create_app(Config(raw=config_raw), **fake_backends)
    transport = httpx.ASGITransport(app=app)
    return httpx.AsyncClient(transport=transport, base_url="http://testserver")


def two_backend_parallel_config(strategy: str = "concatenate", **strategy_overrides):
    """A 2-backend parallel config skeleton used across endpoint tests."""
    concatenate = {
        "separator": "\n---\n",
        "hide_intermediate_think": True,
        "hide_final_think": False,
        "thinking_tags": ["think"],
        "skip_final_aggregation": False,
    }
    aggregate = {
        "source_backends": "all",
        "aggregator_backend": "",
        "intermediate_separator": "\n\n---\n\n",
        "include_source_names": False,
        "thinking_tags": ["think"],
    }
    if strategy == "concatenate":
        concatenate.update(strategy_overrides)
    else:
        aggregate.update(strategy_overrides)
    return {
        "settings": {"timeout": 5},
        "primary_backends": [
            {"name": "LLM1", "url": "http://test1.example.com/v1", "model": "model-1"},
            {"name": "LLM2", "url": "http://test2.example.com/v1", "model": "model-2"},
        ],
        "iterations": {"aggregation": {"strategy": strategy}},
        "strategy": {"concatenate": concatenate, "aggregate": aggregate},
    }


class ParallelStreamCollector:
    """Buckets a parallel quorum's SSE stream by chunk id: per-member
    ``chatcmpl-parallel-{i}`` content deltas into ``texts[i]`` and the
    ``chatcmpl-parallel-final`` combined text into ``final`` — the
    streaming wire contract several endpoint tests assert against."""

    def __init__(self):
        self.texts: dict[int, list[str]] = {}
        self.final: list[str] = []

    def feed_line(self, line: str) -> None:
        import json

        if not line.startswith("data: ") or line == "data: [DONE]":
            return
        chunk = json.loads(line[len("data: "):])
        cid = chunk.get("id", "")
        for ch in chunk.get("choices") or []:
            delta = (ch.get("delta") or {}).get("content")
            if not delta:
                continue
            if cid == "chatcmpl-parallel-final":
                self.final.append(delta)
            elif cid.startswith("chatcmpl-parallel-"):
                self.texts.setdefault(
                    int(cid.rsplit("-", 1)[1]), []).append(delta)

    def stream(self, i: int) -> str:
        return "".join(self.texts[i])


# Minimal built-in async-test support (pytest-asyncio is not in this image):
# run ``async def`` tests via asyncio.run.
@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    if inspect.iscoroutinefunction(pyfuncitem.obj):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(pyfuncitem.obj(**kwargs))
        return True
    return None
