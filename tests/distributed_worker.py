"""Worker process for tests/test_distributed.py's true multi-process run.

NOT a test module (no ``test_`` prefix): spawned twice by
``test_two_process_train_step``, once per simulated host. Each worker joins
the jax distributed runtime through quorum_tpu's own helpers, builds the
hybrid DCN×ICI mesh, feeds only its local dp rows, and runs one real
training step — the dp gradient all-reduce crosses the process boundary
(the DCN analog on a CPU pair). Prints one JSON line the test asserts on.
"""

import json
import os
import sys

# Script execution puts tests/ on sys.path, not the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Clean CPU platform before jax initializes (same recipe as conftest.py —
# the spawning test also scrubs the env, this is belt-and-braces for direct
# invocation).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    from quorum_tpu.models import resolve_spec
    from quorum_tpu.parallel import MeshConfig
    from quorum_tpu.parallel.distributed import (
        assemble_global_batch,
        hybrid_mesh,
        initialize,
        local_data_shard,
    )
    from quorum_tpu.training.trainer import make_train_step, train_init

    # Coordinator/process env vars set by the spawning test.
    assert initialize() is True, "expected to join a 2-process group"
    assert jax.process_count() == 2
    assert jax.device_count() == 4 and len(jax.local_devices()) == 2

    # Per-slice (ICI) shape tp=2 — each simulated host's 2 local devices;
    # dcn_dp=2 spans the dp axis across the two processes.
    mesh = hybrid_mesh(MeshConfig(tp=2), dcn_dp=2)
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "tp": 2}

    global_batch, seqlen = 4, 32
    start, size = local_data_shard(global_batch)
    assert size == 2 and start == 2 * jax.process_index()

    # Deterministic global batch; each host materializes ONLY its rows.
    full = (np.arange(global_batch * seqlen, dtype=np.int32) % 97 + 3
            ).reshape(global_batch, seqlen)
    tokens = assemble_global_batch(full[start:start + size], mesh, global_batch)
    assert tokens.shape == (global_batch, seqlen)

    spec = resolve_spec("llama-tiny", {"max_seq": str(seqlen)})
    state = train_init(spec, mesh, seed=0)
    step = make_train_step(spec, mesh)
    _, loss = step(state, tokens)
    print(json.dumps({"process": jax.process_index(),
                      "loss": float(jax.device_get(loss))}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
