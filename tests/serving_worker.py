"""Worker process for the TRUE two-process SERVING test.

NOT a test module (no ``test_`` prefix): spawned twice by
``test_distributed.test_two_process_serving``, once per simulated host.
Round 3 proved a real cross-process *train* step; this is the serving
analog (VERDICT r3 next-round item 9): each worker joins the jax
distributed runtime, builds the SAME ``tpu://`` backend over a global
dp×tp mesh that spans both processes (dp is the DCN axis — the slot/batch
dimension of the KV cache shards across hosts, weights shard over tp
within each host), and serves the SAME request SPMD-style through the real
engine+backend stack. This mirrors production multi-host serving, where a
front-end broadcasts each request to every host in the replica and the
hosts execute identical dispatch sequences; the spawning test plays the
front-end. Both hosts must emit byte-identical completions.
"""

import asyncio
import json
import os
import sys

# Script execution puts tests/ on sys.path, not the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec
    from quorum_tpu.parallel.distributed import initialize

    assert initialize() is True, "expected to join a 2-process group"
    assert jax.process_count() == 2
    assert jax.device_count() == 4 and len(jax.local_devices()) == 2

    # dp=2 spans the process (DCN) boundary — make_mesh reshapes the global
    # device list dp-major, so each host's 2 local devices form one tp=2
    # group. slots=2 with dp=2 shards the KV-cache batch axis across hosts.
    be = TpuBackend.from_spec(BackendSpec(
        name="M",
        url="tpu://llama-tiny?tp=2&dp=2&n_kv_heads=4&max_seq=128&slots=2"
            "&max_tokens=8",
        model="m"))

    cache = be.engine._ck
    n_cache_devices = len(cache.sharding.device_set)

    body = {"model": "m", "temperature": 0.0, "max_tokens": 8,
            "messages": [{"role": "user", "content": "two hosts, one engine"}]}
    result = asyncio.run(be.complete(body, {}, 240.0))
    assert result.ok, result.error_message
    content = result.body["choices"][0]["message"]["content"]

    # A second request exercises the warm path (prefix cache + slot reuse)
    # under the same SPMD discipline.
    result2 = asyncio.run(be.complete(body, {}, 240.0))
    assert result2.ok, result2.error_message

    print(json.dumps({
        "process": jax.process_index(),
        "content": content,
        "content_warm": result2.body["choices"][0]["message"]["content"],
        "completion_tokens": result.body["usage"]["completion_tokens"],
        "cache_devices": n_cache_devices,
    }), flush=True)


if __name__ == "__main__":
    sys.exit(main())
