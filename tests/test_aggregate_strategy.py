"""Aggregate strategy parity (/root/reference/tests/test_aggregate_strategy.py):
sources + aggregator call counts, prompt construction, auth propagation,
fallbacks, source_backends selection (fixed quirk 4)."""

import pytest

from quorum_tpu import sse
from quorum_tpu.backends import BackendError, FakeBackend
from quorum_tpu.config import AggregateParams
from quorum_tpu.observability import AGGREGATE_DEGRADED
from quorum_tpu.strategies.aggregate import (
    aggregate_with_status,
    build_aggregation_prompt,
)
from quorum_tpu.strategies.combine import degraded_headers
from quorum_tpu.telemetry.recorder import RECORDER
from tests.conftest import make_client, two_backend_parallel_config

AUTH = {"Authorization": "Bearer sk-test"}


def agg_cfg(**overrides):
    base = {
        "source_backends": ["LLM1", "LLM2"],
        "aggregator_backend": "AGG",
        "include_source_names": True,
        "source_label_format": "Response from {backend_name}:\n",
        "intermediate_separator": "\n---\n",
        "include_original_query": True,
        "query_format": "Original query: {query}\n\n",
        "prompt_template": "Responses:\n{intermediate_results}\nSynthesize.",
    }
    base.update(overrides)
    cfg = two_backend_parallel_config(strategy="aggregate", **base)
    cfg["primary_backends"].append(
        {"name": "AGG", "url": "http://agg.example.com/v1", "model": "agg-model"}
    )
    return cfg


async def test_aggregator_called_and_output_returned():
    f1 = FakeBackend("LLM1", text="alpha")
    f2 = FakeBackend("LLM2", text="beta")
    agg = FakeBackend("AGG", text="synthesized!")
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "messages": [{"role": "user", "content": "the query"}]},
            headers=AUTH,
        )
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "synthesized!"
    # 2 sources + 1 aggregator call
    assert len(f1.calls) == 1 and len(f2.calls) == 1 and len(agg.calls) == 1


async def test_aggregator_prompt_contains_labels_query_and_sources():
    f1 = FakeBackend("LLM1", text="alpha")
    f2 = FakeBackend("LLM2", text="beta")
    agg = FakeBackend("AGG", text="done")
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        await client.post(
            "/chat/completions",
            json={"model": "m", "messages": [{"role": "user", "content": "my question"}]},
            headers=AUTH,
        )
    prompt = agg.calls[0].body["messages"][0]["content"]
    assert "Response from LLM1:\nalpha" in prompt
    assert "Response from LLM2:\nbeta" in prompt
    assert "Original query: my question" in prompt
    assert "{intermediate_results}" not in prompt
    assert "Synthesize." in prompt


async def test_auth_header_propagated_to_all_hops():
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    agg = FakeBackend("AGG", text="c")
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    for fake in (f1, f2, agg):
        assert fake.calls[0].headers["Authorization"] == "Bearer sk-test"
    # aggregator gets only sanitized headers
    assert set(agg.calls[0].headers) == {"Authorization", "Content-Type"}


async def test_env_key_fallback_for_aggregator(monkeypatch):
    monkeypatch.setenv("OPENAI_API_KEY", "sk-env")
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    agg = FakeBackend("AGG", text="c")
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        await client.post("/chat/completions", json={"model": "m"})
    assert agg.calls[0].headers["Authorization"] == "Bearer sk-env"


async def test_aggregator_failure_degrades_to_concatenation():
    f1 = FakeBackend("LLM1", text="alpha")
    f2 = FakeBackend("LLM2", text="beta")
    agg = FakeBackend("AGG", fail_with=BackendError("agg down", status_code=500))
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "alpha\n---\nbeta"


async def test_missing_aggregator_backend_degrades():
    cfg = agg_cfg(aggregator_backend="GHOST")
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "a\n---\nb"


async def test_source_backends_honored():
    """Fix of reference quirk 4: only configured sources are fanned out to."""
    cfg = agg_cfg(source_backends=["LLM2"])
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    agg = FakeBackend("AGG", text="agg-out")
    async with make_client(cfg, LLM1=f1, LLM2=f2, AGG=agg) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 200
    assert f1.calls == []  # excluded source not called
    assert len(f2.calls) == 1
    assert r.json()["choices"][0]["message"]["content"] == "agg-out"


async def test_all_sources_fail_500():
    f1 = FakeBackend("LLM1", fail_with=BackendError("x", status_code=500))
    f2 = FakeBackend("LLM2", fail_with=BackendError("y", status_code=500))
    agg = FakeBackend("AGG", text="never")
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 500
    assert agg.calls == []


async def test_streaming_aggregate_final_chunk_is_aggregator_output():
    f1 = FakeBackend("LLM1", chunks=["al", "pha"])
    f2 = FakeBackend("LLM2", chunks=["beta"])
    agg = FakeBackend("AGG", text="the synthesis")
    async with make_client(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "stream": True, "messages": [{"role": "user", "content": "q"}]},
            headers=AUTH,
        )
        events = list(sse.iter_data_events(r.content))
    final = [e for e in events[:-1] if isinstance(e, dict) and e["id"] == "chatcmpl-parallel-final"]
    assert len(final) == 1
    assert final[0]["choices"][0]["delta"]["content"] == "the synthesis"
    prompt = agg.calls[0].body["messages"][0]["content"]
    assert "alpha" in prompt and "beta" in prompt


async def test_aggregate_not_triggered_in_concatenate_strategy():
    """Fix of reference quirk 9: the configured-but-unselected aggregate block
    must not hijack the concatenate strategy."""
    cfg = two_backend_parallel_config(strategy="concatenate", separator="|")
    cfg["strategy"]["aggregate"]["aggregator_backend"] = "LLM1"
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.json()["choices"][0]["message"]["content"] == "a|b"
    assert len(f1.calls) == 1  # not called a second time as aggregator


def test_prompt_builder_placeholder_variants():
    params = AggregateParams()
    params.include_original_query = False
    for template in (
        "X {intermediate_results} Y",
        "X {{intermediate_results}} Y",
        "X {responses} Y",
    ):
        params.prompt_template = template
        out = build_aggregation_prompt([("A", "body")], params, "")
        assert out == "X body Y"
    params.prompt_template = "no placeholder at all"
    out = build_aggregation_prompt([("A", "body")], params, "")
    assert "body" in out


# ---- degrade visibility (docs/quorum.md) -----------------------------------
# The reference fell back to a separator-join SILENTLY; here every fallback
# is visible three ways: response headers (X-Quorum-Aggregate-Degraded +
# -Error), the quorum_tpu_aggregate_degraded_total{reason=} counter, and a
# flight-recorder event.


async def _degraded_request(cfg, *, headers=AUTH, **fakes):
    """POST one chat completion and return the response with the recorder on."""
    old = RECORDER.enabled
    RECORDER.enabled = True
    try:
        async with make_client(cfg, **fakes) as client:
            return await client.post(
                "/chat/completions",
                json={"model": "m",
                      "messages": [{"role": "user", "content": "q"}]},
                headers=headers,
            )
    finally:
        RECORDER.enabled = old


async def test_degrade_error_visible_in_headers_counter_and_recorder():
    f1 = FakeBackend("LLM1", text="alpha")
    f2 = FakeBackend("LLM2", text="beta")
    agg = FakeBackend("AGG", fail_with=BackendError("agg down", status_code=500))
    before = AGGREGATE_DEGRADED.value_of(reason="error")
    r = await _degraded_request(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg)
    assert r.status_code == 200  # degraded, never failed
    assert r.json()["choices"][0]["message"]["content"] == "alpha\n---\nbeta"
    assert r.headers["x-quorum-aggregate-degraded"] == "error"
    assert "agg down" in r.headers["x-quorum-aggregate-error"]
    assert AGGREGATE_DEGRADED.value_of(reason="error") == before + 1
    evs = [e for e in RECORDER.snapshot() if e["kind"] == "aggregate-degraded"]
    assert evs and evs[-1]["reason"] == "error"
    assert "agg down" in evs[-1]["error"]


async def test_degrade_no_aggregator_reason():
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    before = AGGREGATE_DEGRADED.value_of(reason="no_aggregator")
    r = await _degraded_request(agg_cfg(aggregator_backend="GHOST"),
                                LLM1=f1, LLM2=f2)
    assert r.status_code == 200
    assert r.headers["x-quorum-aggregate-degraded"] == "no_aggregator"
    # no underlying error for a config-shaped degrade
    assert "x-quorum-aggregate-error" not in r.headers
    assert AGGREGATE_DEGRADED.value_of(reason="no_aggregator") == before + 1


async def test_degrade_no_credentials_reason(monkeypatch):
    """The server 401s credential-less requests at the door, so this reason
    only fires for embedded callers — pin it at the library layer, plus the
    header mapping degraded_headers() would produce for it."""
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    agg = FakeBackend("AGG", text="never")  # requires_auth=True by default
    before = AGGREGATE_DEGRADED.value_of(reason="no_credentials")
    out = await aggregate_with_status(
        [("LLM1", "a"), ("LLM2", "b")], agg, AggregateParams(
            intermediate_separator="\n---\n"), "q", headers=None)
    assert out.degraded and out.degraded_reason == "no_credentials"
    assert out.content == "a\n---\nb"
    assert AGGREGATE_DEGRADED.value_of(reason="no_credentials") == before + 1
    assert agg.calls == []  # the hop was skipped, not attempted
    assert degraded_headers(out) == {
        "X-Quorum-Aggregate-Degraded": "no_credentials"}


async def test_degrade_empty_reason():
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    agg = FakeBackend("AGG", text="")  # 200 with no content
    before = AGGREGATE_DEGRADED.value_of(reason="empty")
    r = await _degraded_request(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg)
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "a\n---\nb"
    assert r.headers["x-quorum-aggregate-degraded"] == "empty"
    assert AGGREGATE_DEGRADED.value_of(reason="empty") == before + 1


async def test_real_aggregation_carries_no_degrade_header():
    f1 = FakeBackend("LLM1", text="a")
    f2 = FakeBackend("LLM2", text="b")
    agg = FakeBackend("AGG", text="synth")
    before = AGGREGATE_DEGRADED.value
    r = await _degraded_request(agg_cfg(), LLM1=f1, LLM2=f2, AGG=agg)
    assert r.status_code == 200
    assert "x-quorum-aggregate-degraded" not in r.headers
    assert "x-quorum-aggregate-error" not in r.headers
    assert AGGREGATE_DEGRADED.value == before


async def test_stream_aggregate_degrade_ticks_counter_and_serves_fallback():
    """Streaming already sent its headers when the hop fails, so the ONLY
    degrade signals are the counter + recorder event — and the client still
    gets the separator-join fallback under the final-chunk id, never an
    error chunk."""
    f1 = FakeBackend("LLM1", chunks=["al", "pha"])
    f2 = FakeBackend("LLM2", chunks=["beta"])
    agg = FakeBackend("AGG", fail_with=BackendError("agg down", status_code=500))
    before = AGGREGATE_DEGRADED.value_of(reason="error")
    async with make_client(agg_cfg(stream_aggregate=True),
                           LLM1=f1, LLM2=f2, AGG=agg) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "stream": True,
                  "messages": [{"role": "user", "content": "q"}]},
            headers=AUTH,
        )
        events = list(sse.iter_data_events(r.content))
    assert r.status_code == 200
    finals = [e for e in events[:-1]
              if isinstance(e, dict) and e["id"] == "chatcmpl-parallel-final"]
    joined = "".join(e["choices"][0]["delta"].get("content", "") for e in finals)
    assert joined == "alpha\n---\nbeta"
    assert not any(isinstance(e, dict) and e.get("id") == "error"
                   for e in events[:-1])
    assert AGGREGATE_DEGRADED.value_of(reason="error") == before + 1


async def test_fully_local_two_hop_aggregation():
    """The reference's flagship workflow with ZERO network: fan out to two
    local tpu:// models, then synthesize via a THIRD local tpu:// aggregator
    (reference: remote HTTP hops only, oai_proxy.py:374-486). The aggregator
    runs on-device with no credentials; the final content is its generation,
    not the separator-join fallback."""
    raw = {
        "settings": {"timeout": 120},
        "primary_backends": [
            {"name": "A", "url": "tpu://llama-tiny?seed=21&max_seq=64",
             "model": "m"},
            {"name": "B", "url": "tpu://llama-tiny?seed=22&max_seq=64",
             "model": "m"},
            {"name": "AGG", "url": "tpu://llama-tiny?seed=23&max_seq=64",
             "model": "m"},
        ],
        "iterations": {"aggregation": {"strategy": "aggregate"}},
        "strategy": {
            "concatenate": {"separator": "\n---\n"},
            "aggregate": {
                "source_backends": ["A", "B"],
                "aggregator_backend": "AGG",
                "intermediate_separator": "@@SEP@@",
                "include_source_names": False,
                "suppress_individual_responses": True,
            },
        },
    }
    async with make_client(raw) as client:
        resp = await client.post(
            "/chat/completions",
            json={"model": "m", "max_tokens": 6, "temperature": 0,
                  "messages": [{"role": "user", "content": "hello"}]},
            headers={"Authorization": "Bearer x"},
        )
    assert resp.status_code == 200
    body = resp.json()
    content = body["choices"][0]["message"]["content"]
    # the fallback join would contain the distinctive separator; the real
    # aggregation hop returns the AGG model's own generation
    assert "@@SEP@@" not in content
    assert content  # non-empty synthesis
