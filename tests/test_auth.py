"""Auth semantics parity (/root/reference/tests/test_auth.py):
401 without header+env; env key injected as Bearer toward upstream;
header case normalization."""

import pytest

from quorum_tpu.backends import FakeBackend
from tests.conftest import make_client


CFG = {
    "settings": {"timeout": 5},
    "primary_backends": [
        {"name": "LLM1", "url": "http://test1.example.com/v1", "model": "m"}
    ],
}


async def test_401_without_header_and_env(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    fake = FakeBackend("LLM1", text="hi")
    async with make_client(CFG, LLM1=fake) as client:
        r = await client.post("/chat/completions", json={"model": "m", "messages": []})
        assert r.status_code == 401
        err = r.json()["error"]
        assert err["type"] == "auth_error"
        assert "OPENAI_API_KEY" in err["message"]
    assert fake.calls == []


async def test_env_key_injected(monkeypatch):
    monkeypatch.setenv("OPENAI_API_KEY", "sk-env-key")
    fake = FakeBackend("LLM1", text="hi")
    async with make_client(CFG, LLM1=fake) as client:
        r = await client.post("/chat/completions", json={"model": "m", "messages": []})
        assert r.status_code == 200
    assert fake.calls[0].headers["Authorization"] == "Bearer sk-env-key"


async def test_header_case_normalized(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    fake = FakeBackend("LLM1", text="hi")
    async with make_client(CFG, LLM1=fake) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "messages": []},
            headers={"authorization": "Bearer sk-user"},
        )
        assert r.status_code == 200
    auth_headers = {
        k: v for k, v in fake.calls[0].headers.items() if k.lower() == "authorization"
    }
    assert auth_headers == {"Authorization": "Bearer sk-user"}


async def test_header_takes_precedence_over_env(monkeypatch):
    monkeypatch.setenv("OPENAI_API_KEY", "sk-env")
    fake = FakeBackend("LLM1", text="hi")
    async with make_client(CFG, LLM1=fake) as client:
        await client.post(
            "/chat/completions",
            json={"model": "m", "messages": []},
            headers={"Authorization": "Bearer sk-header"},
        )
    assert fake.calls[0].headers["Authorization"] == "Bearer sk-header"


async def test_host_header_not_forwarded(monkeypatch):
    monkeypatch.setenv("OPENAI_API_KEY", "sk-env")
    fake = FakeBackend("LLM1", text="hi")
    async with make_client(CFG, LLM1=fake) as client:
        await client.post("/chat/completions", json={"model": "m", "messages": []})
    assert "host" not in {k.lower() for k in fake.calls[0].headers}
