"""Backend layer: model-override precedence, registry, HTTP + fake backends."""

import httpx
import pytest

from quorum_tpu import oai, sse
from quorum_tpu.backends import (
    BackendError,
    FakeBackend,
    HttpBackend,
    build_registry,
    prepare_body,
)
from quorum_tpu.config import Config


class TestPrepareBody:
    def test_config_model_overrides_request(self):
        out = prepare_body({"model": "req-model", "messages": []}, "cfg-model")
        assert out["model"] == "cfg-model"

    def test_request_model_used_when_config_blank(self):
        out = prepare_body({"model": "req-model", "messages": []}, "")
        assert out["model"] == "req-model"

    def test_no_model_anywhere_raises_400(self):
        with pytest.raises(BackendError) as ei:
            prepare_body({"messages": []}, "")
        assert ei.value.status_code == 400
        assert ei.value.body["error"]["type"] == "invalid_request_error"

    def test_original_body_not_mutated(self):
        body = {"model": "a", "messages": [{"role": "user", "content": "x"}]}
        prepare_body(body, "b")
        assert body["model"] == "a"


class TestFakeBackend:
    async def test_complete(self):
        b = FakeBackend("LLM1", text="hello", usage={"prompt_tokens": 2, "completion_tokens": 3, "total_tokens": 5})
        r = await b.complete({"model": "m", "messages": []}, {}, 5.0)
        assert r.ok
        assert r.content == "hello"
        assert r.usage["total_tokens"] == 5
        assert r.body["backend"] == "LLM1"
        assert b.calls[0].body["model"] == "m"

    async def test_stream_shape(self):
        b = FakeBackend("LLM1", chunks=["he", "llo"])
        events = [e async for e in b.stream({"model": "m", "messages": []}, {}, 5.0)]
        assert events[0]["choices"][0]["delta"] == {"role": "assistant"}
        contents = [oai.extract_delta_content(e) for e in events]
        assert "".join(contents) == "hello"
        assert events[-1]["choices"][0]["finish_reason"] == "stop"

    async def test_failure(self):
        b = FakeBackend("bad", fail_with=BackendError("boom", status_code=503))
        with pytest.raises(BackendError) as ei:
            await b.complete({"model": "m"}, {}, 5.0)
        assert ei.value.status_code == 503

    async def test_mid_stream_failure(self):
        b = FakeBackend("bad", chunks=["a", "b", "c"], fail_mid_stream=2)
        got = []
        with pytest.raises(BackendError):
            async for e in b.stream({"model": "m"}, {}, 5.0):
                got.append(oai.extract_delta_content(e))
        assert "".join(got) == "ab"


def _mock_client(handler):
    return httpx.AsyncClient(transport=httpx.MockTransport(handler))


class TestHttpBackend:
    async def test_complete_tags_backend_and_parses(self):
        def handler(request: httpx.Request) -> httpx.Response:
            assert request.url.path.endswith("/chat/completions")
            import json

            body = json.loads(request.content)
            assert body["model"] == "cfg-model"  # override applied
            assert "content-length" not in dict(request.headers).get("x-echo", "")
            return httpx.Response(200, json=oai.completion(content="hi", model="cfg-model"))

        b = HttpBackend("LLM1", "http://up.example/v1", "cfg-model", client=_mock_client(handler))
        r = await b.complete({"model": "other", "messages": []}, {"host": "x", "authorization": "Bearer k"}, 5.0)
        assert r.ok and r.content == "hi"
        assert r.body["backend"] == "LLM1"

    async def test_upstream_error_status_passthrough(self):
        def handler(request):
            return httpx.Response(429, json={"error": {"message": "rate limited"}})

        b = HttpBackend("LLM1", "http://up.example/v1", "m", client=_mock_client(handler))
        r = await b.complete({"model": "m"}, {}, 5.0)
        assert not r.ok
        assert r.status_code == 429
        assert r.body["error"]["message"] == "rate limited"

    async def test_transport_exception_becomes_backend_error(self):
        def handler(request):
            raise httpx.ConnectError("nope")

        b = HttpBackend("LLM1", "http://up.example/v1", "m", client=_mock_client(handler))
        with pytest.raises(BackendError) as ei:
            await b.complete({"model": "m"}, {}, 5.0)
        assert ei.value.status_code == 500
        assert ei.value.body["error"]["type"] == "proxy_error"

    async def test_invalid_json_normalized(self):
        def handler(request):
            return httpx.Response(200, content=b"<html>oops</html>")

        b = HttpBackend("LLM1", "http://up.example/v1", "m", client=_mock_client(handler))
        r = await b.complete({"model": "m"}, {}, 5.0)
        assert not r.ok
        assert "error" in r.body

    async def test_stream_yields_incremental_chunks(self):
        frames = (
            sse.encode_event(oai.chunk(id="c", model="m", delta={"role": "assistant"}))
            + sse.encode_event(oai.chunk(id="c", model="m", delta={"content": "he"}))
            + sse.encode_event(oai.chunk(id="c", model="m", delta={"content": "llo"}))
            + sse.encode_done()
        )

        def handler(request):
            import json

            assert json.loads(request.content)["stream"] is True
            return httpx.Response(
                200,
                headers={"content-type": "text/event-stream"},
                content=frames,
            )

        b = HttpBackend("LLM1", "http://up.example/v1", "m", client=_mock_client(handler))
        events = [e async for e in b.stream({"model": "m"}, {}, 5.0)]
        assert "".join(oai.extract_delta_content(e) for e in events) == "hello"
        # DONE sentinel consumed, not yielded
        assert all(isinstance(e, dict) for e in events)

    async def test_stream_http_error_raises_with_body(self):
        def handler(request):
            return httpx.Response(500, json={"error": {"message": "upstream down"}})

        b = HttpBackend("LLM1", "http://up.example/v1", "m", client=_mock_client(handler))
        with pytest.raises(BackendError) as ei:
            async for _ in b.stream({"model": "m"}, {}, 5.0):
                pass
        assert ei.value.status_code == 500
        assert ei.value.body["error"]["message"] == "upstream down"


class TestRegistry:
    def cfg(self):
        return Config(raw={
            "primary_backends": [
                {"name": "LLM1", "url": "http://a.example/v1", "model": "m1"},
                {"name": "LLM2", "url": "http://b.example/v1", "model": "m2"},
                {"name": "SKIP", "url": "", "model": ""},
            ],
            "settings": {"timeout": 5},
        })

    def test_build_skips_invalid_and_keeps_order(self):
        reg = build_registry(self.cfg())
        assert [b.name for b in reg.backends] == ["LLM1", "LLM2"]
        assert isinstance(reg.get("LLM1"), HttpBackend)

    def test_overrides_inject_fakes(self):
        fake = FakeBackend("LLM2", text="x")
        reg = build_registry(self.cfg(), LLM2=fake)
        assert reg.get("LLM2") is fake
        assert isinstance(reg.get("LLM1"), HttpBackend)

    def test_select_all_and_subset(self):
        reg = build_registry(self.cfg())
        assert [b.name for b in reg.select("all")] == ["LLM1", "LLM2"]
        assert [b.name for b in reg.select(None)] == ["LLM1", "LLM2"]
        assert [b.name for b in reg.select(["LLM2"])] == ["LLM2"]
        # unknown names resolve to nothing — callers surface a config error
        # instead of silently fanning out to excluded backends
        assert reg.select(["nope"]) == []

    def test_unsupported_scheme_skipped(self):
        cfg = Config(raw={
            "primary_backends": [{"name": "X", "url": "ftp://weird"}],
            "settings": {},
        })
        reg = build_registry(cfg)
        assert len(reg) == 0
