"""Driver-critical pure helpers of bench.py's probe-gated orchestration.

The driver records whatever bench.py prints; these helpers decide what
survives a wedged-tunnel run, so they get direct pins: JSON-line salvage
from truncated child output, and the probe's rejection of a CPU-fallback
jax (which would otherwise record CPU numbers as the TPU headline).
"""

import importlib.util
import os
import sys


def _load_bench():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # bench respects JAX_PLATFORMS=cpu at import (the conftest env).
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_json_line_salvage():
    bench = _load_bench()
    stdout = 'log noise\n{"checkpoint": 1}\n{"trunca'
    assert bench._last_json_line(stdout) == {"checkpoint": 1}
    assert bench._last_json_line("no json at all") is None
    assert bench._last_json_line(None) is None
    # latest intact line wins
    assert bench._last_json_line('{"a":1}\n{"b":2}') == {"b": 2}


def test_probe_rejects_cpu_fallback(monkeypatch):
    bench = _load_bench()

    class FakeProc:
        def __init__(self, stdout, rc=0):
            self.stdout = stdout
            self.returncode = rc

    outcomes = {
        "PROBE_OK tpu": True,
        "warning noise\nPROBE_OK axon": True,
        "PROBE_OK cpu": False,   # fast tunnel failure → cpu fallback
        "": False,
    }
    import subprocess as sp
    for stdout, want in outcomes.items():
        monkeypatch.setattr(sp, "run", lambda *a, _s=stdout, **k: FakeProc(_s))
        assert bench._probe_device(budget=1) is want, stdout

    def timeout_run(*a, **k):
        raise sp.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(sp, "run", timeout_run)
    assert bench._probe_device(budget=1) is False
