"""Driver-critical pure helpers of bench.py's probe-gated orchestration.

The driver records whatever bench.py prints; these helpers decide what
survives a wedged-tunnel run, so they get direct pins: JSON-line salvage
from truncated child output, and the probe's rejection of a CPU-fallback
jax (which would otherwise record CPU numbers as the TPU headline).
"""

import importlib.util
import os
import sys


def _load_bench():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # bench respects JAX_PLATFORMS=cpu at import (the conftest env).
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_json_line_salvage():
    bench = _load_bench()
    stdout = 'log noise\n{"checkpoint": 1}\n{"trunca'
    assert bench._last_json_line(stdout) == {"checkpoint": 1}
    assert bench._last_json_line("no json at all") is None
    assert bench._last_json_line(None) is None
    # latest intact line wins
    assert bench._last_json_line('{"a":1}\n{"b":2}') == {"b": 2}


def test_probe_rejects_cpu_fallback(monkeypatch):
    bench = _load_bench()

    class FakeProc:
        def __init__(self, stdout, rc=0):
            self.stdout = stdout
            self.returncode = rc

    outcomes = {
        "PROBE_OK tpu": True,
        "warning noise\nPROBE_OK axon": True,
        # Teardown noise AFTER the marker must not read as a dead tunnel
        # (ADVICE r3: the old check required the marker on the LAST line).
        "PROBE_OK tpu\nruntime shutdown notice": True,
        "PROBE_OK cpu": False,   # fast tunnel failure → cpu fallback
        "PROBE_OK cpu\nnoise": False,
        "": False,
    }
    import subprocess as sp
    for stdout, want in outcomes.items():
        monkeypatch.setattr(sp, "run", lambda *a, _s=stdout, **k: FakeProc(_s))
        assert bench._probe_device(budget=1) is want, stdout

    def timeout_run(*a, **k):
        raise sp.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(sp, "run", timeout_run)
    assert bench._probe_device(budget=1) is False


def test_probe_until_retries_across_window(monkeypatch):
    """r03 regression: one failed probe must not end the retry horizon —
    _probe_until keeps asking (with backoff) until success or deadline."""
    import time as _time

    bench = _load_bench()
    calls = {"n": 0}

    def flaky_probe(budget=120):
        calls["n"] += 1
        return calls["n"] >= 3  # dead twice, then the tunnel recovers

    slept = []
    monkeypatch.setattr(bench, "_probe_device", flaky_probe)
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    assert bench._probe_until(_time.time() + 3600) is True
    assert calls["n"] == 3
    assert len(slept) == 2 and slept[1] > slept[0]  # backoff grows

    # Past-deadline: gives up after the first failed probe, returns False.
    calls["n"] = -10**9
    assert bench._probe_until(_time.time() - 1) is False


def test_watchdog_budget_derived_and_overridable(monkeypatch):
    """ADVICE r3: the watchdog budget must exceed the phase-budget sum (a
    slow-but-healthy run must not be shot by its own watchdog); an env
    override still wins, and a malformed one falls back to derived."""
    bench = _load_bench()
    monkeypatch.delenv("QUORUM_TPU_BENCH_WATCHDOG", raising=False)
    phase_sum = bench._PHASE12_BUDGET + sum(
        b for _, _, gate, b, _ in bench._7B_PHASES if gate != "0")
    assert bench._derived_watchdog_budget() >= phase_sum + 600

    monkeypatch.setenv("QUORUM_TPU_BENCH_WATCHDOG", "123")
    assert bench._derived_watchdog_budget() == 123
    monkeypatch.setenv("QUORUM_TPU_BENCH_WATCHDOG", "not-a-number")
    assert bench._derived_watchdog_budget() >= phase_sum + 600
