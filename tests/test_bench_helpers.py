"""Driver-critical pure helpers of bench.py's probe-gated orchestration.

The driver records whatever bench.py prints; these helpers decide what
survives a wedged-tunnel run, so they get direct pins: JSON-line salvage
from truncated child output, and the probe's rejection of a CPU-fallback
jax (which would otherwise record CPU numbers as the TPU headline).
"""

import importlib.util
import os
import sys

import pytest


def _load_bench():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # bench respects JAX_PLATFORMS=cpu at import (the conftest env).
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_json_line_salvage():
    bench = _load_bench()
    stdout = 'log noise\n{"checkpoint": 1}\n{"trunca'
    assert bench._last_json_line(stdout) == {"checkpoint": 1}
    assert bench._last_json_line("no json at all") is None
    assert bench._last_json_line(None) is None
    # latest intact line wins
    assert bench._last_json_line('{"a":1}\n{"b":2}') == {"b": 2}


def test_probe_rejects_cpu_fallback(monkeypatch):
    bench = _load_bench()

    class FakeProc:
        def __init__(self, stdout, rc=0):
            self.stdout = stdout
            self.returncode = rc

    outcomes = {
        "PROBE_OK tpu": True,
        "warning noise\nPROBE_OK axon": True,
        # Teardown noise AFTER the marker must not read as a dead tunnel
        # (ADVICE r3: the old check required the marker on the LAST line).
        "PROBE_OK tpu\nruntime shutdown notice": True,
        "PROBE_OK cpu": False,   # fast tunnel failure → cpu fallback
        "PROBE_OK cpu\nnoise": False,
        "": False,
    }
    import subprocess as sp
    for stdout, want in outcomes.items():
        monkeypatch.setattr(sp, "run", lambda *a, _s=stdout, **k: FakeProc(_s))
        assert bench._probe_device(budget=1) is want, stdout

    def timeout_run(*a, **k):
        raise sp.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(sp, "run", timeout_run)
    assert bench._probe_device(budget=1) is False


def test_probe_until_retries_across_window(monkeypatch):
    """r03 regression: one failed probe must not end the retry horizon —
    _probe_until keeps asking (with backoff) until success or deadline."""
    import time as _time

    bench = _load_bench()
    calls = {"n": 0}

    def flaky_probe(budget=120):
        calls["n"] += 1
        return calls["n"] >= 3  # dead twice, then the tunnel recovers

    slept = []
    monkeypatch.setattr(bench, "_probe_device", flaky_probe)
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    assert bench._probe_until(_time.time() + 3600) is True
    assert calls["n"] == 3
    assert len(slept) == 2 and slept[1] > slept[0]  # backoff grows

    # Past-deadline: gives up after the first failed probe, returns False.
    calls["n"] = -10**9
    assert bench._probe_until(_time.time() - 1) is False


def test_ab_keys_rekeys_top_level_schema():
    """The separate-engines A/B arm must merge BESIDE the stacked headline
    (ab_* keys), never clobber it."""
    bench = _load_bench()
    got = {"metric": "p50_ttft_ms", "value": 91.0, "unit": "ms",
           "p50_total_ms": 300.0, "req_per_s": 2.5, "tokens_per_s": 290.0,
           "mfu_pct": 0.1, "stacked": False, "ab_error": "x"}
    out = bench._ab_keys(got)
    assert out == {"ab_p50_ttft_ms": 91.0, "ab_p50_total_ms": 300.0,
                   "ab_req_per_s": 2.5, "ab_tokens_per_s": 290.0,
                   "ab_stacked": False, "ab_error": "x"}
    # none of the headline's own keys survive un-prefixed
    assert not set(out) & {"value", "metric", "tokens_per_s"}


def test_tpu_orchestration_plan_end_to_end(monkeypatch, capsys):
    """The TPU main() path with stubbed probes/children: every enabled
    phase runs in PRIORITY order (headline → north-star int8 b7q → A/B arm
    (STACKED=0 env) → b7 → ckpt), the A/B arm's schema lands re-keyed
    BESIDE the headline, and the final merged JSON line prints. Would have
    caught the round-4 regression where a mis-placed helper severed
    main()'s tail (no JSON, no exit code)."""
    import asyncio
    import json

    from quorum_tpu import compile_cache

    bench = _load_bench()
    # main() imports tpu_host_configured from compile_cache at call time.
    monkeypatch.setattr(compile_cache, "tpu_host_configured", lambda: True)
    monkeypatch.setattr(bench, "_probe_device", lambda budget=120: True)
    monkeypatch.setattr(bench, "_probe_until", lambda deadline: True)

    calls = []

    def fake_child(flag, prefix, budget, env_extra=None):
        calls.append((prefix, env_extra))
        if prefix == "phase12":
            return {"metric": "p50_ttft_ms", "value": 50.0, "unit": "ms",
                    "vs_baseline": 2.0, "p50_total_ms": 100.0,
                    "req_per_s": 4.0, "tokens_per_s": 400.0, "stacked": True}
        if prefix == "ab":
            return {"metric": "p50_ttft_ms", "value": 80.0, "unit": "ms",
                    "p50_total_ms": 110.0, "req_per_s": 3.0,
                    "tokens_per_s": 300.0, "stacked": False}
        return {f"{prefix}_decode_tok_s": 1.0}

    monkeypatch.setattr(bench, "run_child_phase", fake_child)
    asyncio.run(bench.main())
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert lines, "main() printed no JSON line"
    rec = json.loads(lines[-1])
    assert [c[0] for c in calls] == ["phase12", "b7q", "ab", "b7", "ckpt"]
    assert calls[2][1] == {"QUORUM_TPU_BENCH_STACKED": "0"}
    assert rec["value"] == 50.0 and rec["ab_p50_ttft_ms"] == 80.0
    assert rec["tokens_per_s"] == 400.0 and rec["ab_tokens_per_s"] == 300.0
    assert rec["ab_stacked"] is False and rec["stacked"] is True
    assert rec["b7_decode_tok_s"] == 1.0 and rec["b7q_decode_tok_s"] == 1.0


def test_watchdog_budget_derived_and_overridable(monkeypatch):
    """ADVICE r3: the watchdog budget must exceed the phase-budget sum (a
    slow-but-healthy run must not be shot by its own watchdog); an env
    override still wins, and a malformed one falls back to derived."""
    bench = _load_bench()
    monkeypatch.delenv("QUORUM_TPU_BENCH_WATCHDOG", raising=False)
    phase_sum = bench._PHASE12_BUDGET + sum(
        b for _, _, gate, b, _ in bench._7B_PHASES if gate != "0")
    assert bench._derived_watchdog_budget() >= phase_sum + 600

    monkeypatch.setenv("QUORUM_TPU_BENCH_WATCHDOG", "123")
    assert bench._derived_watchdog_budget() == 123
    monkeypatch.setenv("QUORUM_TPU_BENCH_WATCHDOG", "not-a-number")
    assert bench._derived_watchdog_budget() >= phase_sum + 600


def test_deadline_cap_default_and_override(monkeypatch):
    """VERDICT r4 item 1: the orchestrator's internal deadline must default
    WELL UNDER the driver's observed ~1800 s kill window (round 4 derived
    9720 s from its own phase budgets and was shot mid-probe with no JSON
    out); an env override still wins for interactive sessions."""
    bench = _load_bench()
    monkeypatch.delenv("QUORUM_TPU_BENCH_DEADLINE_S", raising=False)
    monkeypatch.delenv("QUORUM_TPU_BENCH_WATCHDOG", raising=False)
    assert bench._deadline_cap() == bench._DEFAULT_DEADLINE_S
    assert bench._deadline_cap() <= 1500 < 1800
    monkeypatch.setenv("QUORUM_TPU_BENCH_DEADLINE_S", "7200")
    assert bench._deadline_cap() == 7200
    monkeypatch.setenv("QUORUM_TPU_BENCH_DEADLINE_S", "not-a-number")
    assert bench._deadline_cap() == bench._DEFAULT_DEADLINE_S
    # a smaller derived budget (e.g. most phases disabled) wins the min
    monkeypatch.delenv("QUORUM_TPU_BENCH_DEADLINE_S", raising=False)
    monkeypatch.setenv("QUORUM_TPU_BENCH_WATCHDOG", "900")
    assert bench._deadline_cap() == 900


def test_emit_snapshot_carries_banked_metrics_and_status(capsys):
    """Every snapshot line must parse on its own, carry everything banked
    so far, satisfy the headline schema (sentinel value until the real
    headline lands), and say where the run currently is."""
    import json

    bench = _load_bench()
    bench._PHASE_NOW = "probing before b7q"
    bench._BANKED.update({"b7_decode_tok_s": 33.5})
    bench._emit_snapshot()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "p50_ttft_ms" and rec["value"] == -1.0
    assert rec["b7_decode_tok_s"] == 33.5
    assert "probing before b7q" in rec["status"]

    # once the headline landed, its value survives on later snapshots
    bench._BANKED.update({"value": 50.0, "metric": "p50_ttft_ms",
                          "unit": "ms", "vs_baseline": 2.0})
    bench._PHASE_NOW = "running ab (budget 600s)"
    bench._emit_snapshot()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 50.0 and "running ab" in rec["status"]
    # the sentinel/status never leak back into the banked dict itself
    assert "status" not in bench._BANKED


def test_probe_until_emits_snapshot_per_failure(monkeypatch, capsys):
    """The probe-backoff loop is where round 4 died blank: every failed
    probe must flush a cumulative snapshot so an external kill mid-backoff
    still leaves parseable output."""
    import json
    import time as _time

    bench = _load_bench()
    calls = {"n": 0}

    def flaky(budget=None):
        calls["n"] += 1
        return calls["n"] >= 3

    monkeypatch.setattr(bench, "_probe_device", flaky)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    bench._PHASE_NOW = "probing before phase12"
    assert bench._probe_until(_time.time() + 3600) is True
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert len(lines) == 2  # one snapshot per failed probe
    for ln in lines:
        rec = json.loads(ln)
        assert rec["value"] == -1.0 and "phase12" in rec["status"]


def test_child_crash_preserves_checkpointed_metrics(monkeypatch, capsys):
    """An in-child exception (tunnel dead mid-co-batch) must not bury
    already-checkpointed numbers under an error-only last JSON line — the
    parent keeps only the child's LAST line."""
    import asyncio
    import json

    bench = _load_bench()
    monkeypatch.setattr(bench, "BENCH_7BQ", "1")

    async def fake_bench_7b(model, url, prefix, quant, long_ctx=False):
        bench._child_checkpoint({f"{prefix}_model": model + "+int8",
                                 f"{prefix}_decode_tok_s": 12.5})
        raise RuntimeError("tunnel died mid-co-batch")

    monkeypatch.setattr(bench, "bench_7b", fake_bench_7b)
    asyncio.run(bench.seven_b_main(quant=True))
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    rec = json.loads(lines[-1])
    assert rec["b7q_decode_tok_s"] == 12.5
    assert rec["b7q_model"].endswith("+int8")  # checkpointed tag survives
    assert "tunnel died" in rec["b7q_error"]


def test_deadline_cap_trusts_explicit_watchdog_env(monkeypatch):
    """The on-chip session supervisor sizes the run via
    QUORUM_TPU_BENCH_WATCHDOG — an explicitly-sized window must not be
    second-guessed down to the driver-window default."""
    bench = _load_bench()
    monkeypatch.delenv("QUORUM_TPU_BENCH_DEADLINE_S", raising=False)
    monkeypatch.setenv("QUORUM_TPU_BENCH_WATCHDOG", "10800")
    assert bench._deadline_cap() == 10800


def test_sigkill_mid_probe_leaves_parseable_snapshot():
    """VERDICT r4 item 1's done-criterion: hard-kill (SIGKILL — the
    driver's rc-124 timeout discipline) a real ``python bench.py`` run
    while it sits in its probe-backoff loop, and the last intact stdout
    line must parse with the headline schema and per-phase status.
    BENCH_r04.json recorded ``parsed: null`` because the only JSON print
    sat at the very end of main()."""
    import select
    import signal
    import subprocess as sp
    import time as _time

    bench = _load_bench()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # A TPU-configured host whose device can never come up: the platform
    # list says tpu (tpu_host_configured → orchestrator path) but no TPU
    # runtime exists in the test env, so every probe subprocess fails fast
    # and the orchestrator sits in exactly the loop round 4 died in.
    env["JAX_PLATFORMS"] = "tpu"
    env["QUORUM_TPU_BENCH_DEADLINE_S"] = "600"
    env["QUORUM_TPU_BENCH_PROBE_BUDGET"] = "45"
    proc = sp.Popen([sys.executable, os.path.join(repo, "bench.py")],
                    stdout=sp.PIPE, stderr=sp.DEVNULL, cwd=repo, env=env)
    buf = b""
    try:
        deadline = _time.time() + 120
        while _time.time() < deadline and b"{" not in buf:
            ready, _, _ = select.select([proc.stdout], [], [], 1.0)
            if ready:
                chunk = os.read(proc.stdout.fileno(), 65536)
                if not chunk:
                    break
                buf += chunk
        assert b"{" in buf, f"no snapshot before kill; got: {buf[-500:]!r}"
        proc.send_signal(signal.SIGKILL)
        try:
            rest, _ = proc.communicate(timeout=30)
        except sp.TimeoutExpired:
            rest = b""
    finally:
        proc.kill()
        proc.wait()
    out = (buf + (rest or b"")).decode(errors="replace")
    rec = bench._last_json_line(out)
    assert rec is not None, f"no parseable JSON line survived: {out[-500:]!r}"
    assert rec["metric"] == "p50_ttft_ms" and rec["value"] == -1.0
    assert "phase12" in rec.get("status", "") or "phase12_error" in rec


@pytest.mark.slow  # engine-scale: int8 engine + 8192 window + 5k prefill
def test_7bq_child_end_to_end_tiny(monkeypatch):
    """The int8 north-star child (--7bq: quantized serving + prefix-cache
    + co-batch + 5k-token chunked-prefill long-context) end to end on a
    tiny model — this exact path must work first-try in a live tunnel
    window, and the final JSON line must carry the b7q_* schema including
    the long-context keys."""
    import subprocess as sp

    bench = _load_bench()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["QUORUM_TPU_BENCH_7B_QUANT"] = "1"
    env["QUORUM_TPU_BENCH_7B_QUANT_MODEL"] = "llama-tiny"
    env["QUORUM_TPU_BENCH_7B_MAX_TOKENS"] = "24"
    proc = sp.run([sys.executable, os.path.join(repo, "bench.py"), "--7bq"],
                  capture_output=True, text=True, cwd=repo, env=env,
                  timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = bench._last_json_line(proc.stdout)
    assert rec, proc.stdout[-500:]
    assert "b7q_error" not in rec, rec
    assert rec["b7q_model"] == "llama-tiny+int8"
    assert rec["b7q_decode_tok_s"] > 0 and rec["b7q_ttft_ms"] > 0
    assert rec["b7q_tok_s_c2"] > 0
    assert rec["b7q_prefix_cold_ttft_ms"] >= rec["b7q_prefix_warm_ttft_ms"] > 0
    # the long-context phase really ran against the 8192 window
    assert rec["b7q_long_prompt_tokens"] == 5000
    assert rec["b7q_long_ttft_ms"] > 0 and rec["b7q_long_decode_tok_s"] > 0


def test_banked_onchip_merges_nested(monkeypatch, capsys, tmp_path):
    """A prior session's ONCHIP.json rides the driver artifact under the
    nested 'onchip' key — real-silicon numbers from a mid-session tunnel
    window survive a driver-time dead tunnel — while an error-only (dead
    at start) artifact is ignored."""
    import asyncio
    import json

    from quorum_tpu import compile_cache

    bench = _load_bench()
    real_loader = bench._banked_onchip  # before the stub below replaces it
    monkeypatch.setattr(compile_cache, "tpu_host_configured", lambda: True)
    monkeypatch.setattr(bench, "_probe_until", lambda deadline: True)
    monkeypatch.setattr(
        bench, "run_child_phase",
        lambda flag, prefix, budget, env_extra=None: (
            {"metric": "p50_ttft_ms", "value": 50.0, "unit": "ms",
             "vs_baseline": 2.0} if prefix == "phase12" else {}))

    good = {"b7_decode_tok_s": 34.6, "onchip_started_ts": 1.0}
    monkeypatch.setattr(bench, "_banked_onchip", lambda: good)
    asyncio.run(bench.main())
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    rec = json.loads(lines[-1])
    assert rec["onchip"]["b7_decode_tok_s"] == 34.6
    assert rec["value"] == 50.0  # fresh keys stay top-level
    assert "b7_decode_tok_s" not in rec  # banked never flattens

    # the loader itself: error-only artifacts read as None
    onchip = tmp_path / "ONCHIP.json"
    monkeypatch.setattr(bench.os.path, "dirname", lambda p: str(tmp_path))
    onchip.write_text(json.dumps(
        {"onchip_error": "tunnel dead at session start", "ts": 5.0}))
    assert real_loader() is None
    # headline sentinels of a failed bench step are not measurements
    onchip.write_text(json.dumps(
        {"metric": "p50_ttft_ms", "value": -1.0, "vs_baseline": 0.0,
         "error": "phases 1/2 failed", "onchip_started_ts": 5.0}))
    assert real_loader() is None
    # valid JSON that is not an object must not crash the run
    onchip.write_text("[1, 2, 3]")
    assert real_loader() is None
    # a legacy self-embedded copy is stripped, never re-nested
    onchip.write_text(json.dumps(
        {"onchip_error": None, "onchip_started_ts": 5.0,
         "kvq_decode_tok_s": 30.2, "kvq_wall_s": 60.0,
         "onchip": {"old": 1}}))
    assert real_loader() == {"onchip_error": None, "onchip_started_ts": 5.0,
                             "kvq_decode_tok_s": 30.2, "kvq_wall_s": 60.0}
    # the supervised session's own bench step never merges (it would bank
    # the merge straight back into ONCHIP.json, nesting forever)
    monkeypatch.setenv("QUORUM_TPU_BENCH_ONCHIP_MERGE", "0")
    assert real_loader() is None
    monkeypatch.delenv("QUORUM_TPU_BENCH_ONCHIP_MERGE")
    onchip.unlink()
    assert real_loader() is None


def test_classify_round_sentinels_are_not_measurements():
    """The driver's probe-failure/watchdog sentinel records (value -1.0,
    vs_baseline 0.0 — BENCH_r03–r05's exact shape) must classify as
    no_measurement, never as a measured (regressed) value."""
    bench = _load_bench()
    sentinel = {"metric": "p50_ttft_ms", "value": -1.0, "unit": "ms",
                "vs_baseline": 0.0,
                "error": "skipped: device probe failed (tunnel dead)"}
    assert bench.classify_round(sentinel) == "no_measurement"
    # the in-progress snapshot shape (status marker, headline still -1.0)
    assert bench.classify_round(
        {"metric": "p50_ttft_ms", "value": -1.0, "vs_baseline": 0.0,
         "status": "in progress: probing b7q"}) == "no_measurement"
    # a real measured round
    assert bench.classify_round(
        {"metric": "p50_ttft_ms", "value": 73.96,
         "vs_baseline": 5.83}) == "measured"
    # parsed: null (round 4's rc-124 hard kill) and junk shapes
    assert bench.classify_round(None) == "unparsed"
    assert bench.classify_round("tail text") == "unparsed"
    assert bench.classify_round({}) == "unparsed"
    # a zero value is no measurement either (nothing can serve in 0 ms)
    assert bench.classify_round(
        {"metric": "p50_ttft_ms", "value": 0.0}) == "no_measurement"


def test_summarize_trajectory_excludes_sentinel_rounds(tmp_path):
    """Value statistics span measured rounds ONLY: a trajectory whose last
    rounds are dead-tunnel sentinels keeps the earlier real numbers as
    best/latest instead of charting -1.0 as a collapse."""
    import json as _json

    bench = _load_bench()
    rows = [
        ("BENCH_r01.json", {"parsed": {"metric": "p50_ttft_ms",
                                       "value": 313.4}}),
        ("BENCH_r02.json", {"parsed": {"metric": "p50_ttft_ms",
                                       "value": 73.96,
                                       "vs_baseline": 5.83}}),
        ("BENCH_r03.json", {"parsed": {"metric": "p50_ttft_ms",
                                       "value": -1.0, "vs_baseline": 0.0,
                                       "error": "skipped: probe failed"}}),
        ("BENCH_r04.json", {"parsed": None}),
    ]
    paths = []
    for name, rec in rows:
        p = tmp_path / name
        p.write_text(_json.dumps(rec))
        paths.append(str(p))
    out = bench.summarize_trajectory(paths)
    assert [r["status"] for r in out["rounds"]] == [
        "measured", "measured", "no_measurement", "unparsed"]
    assert out["measured_rounds"] == 2
    assert out["sentinel_rounds"] == 1
    assert out["unparsed_rounds"] == 1
    assert out["latest_measured"] == 73.96   # NOT -1.0
    assert out["best_measured"] == 73.96
    assert out["first_measured"] == 313.4
    assert out["best_vs_first"] == 4.24
    # sentinel rounds surface their reason instead of a value
    assert "error" in out["rounds"][2] and "value" not in out["rounds"][2]


def test_summarize_trajectory_real_repo_artifacts():
    """The committed BENCH_r01–r05 artifacts themselves: rounds 3–5 were
    probe-failure/hard-kill rounds and must never read as regressions from
    round 2's 73.96 ms headline."""
    bench = _load_bench()
    out = bench.summarize_trajectory()
    if out["measured_rounds"] == 0:
        pytest.skip("no measured driver rounds in this checkout")
    assert out["latest_measured"] > 0
    assert out["best_measured"] > 0
    for r in out["rounds"]:
        if r["status"] == "measured":
            assert r["value"] > 0
