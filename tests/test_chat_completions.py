"""Non-streaming endpoint parity (/root/reference/tests/test_chat_completions.py):
model override precedence, request-model fallback, multi-backend gather in
non-parallel mode, validation errors, timeout propagation."""

import pytest

from quorum_tpu.backends import BackendError, FakeBackend
from tests.conftest import make_client

AUTH = {"Authorization": "Bearer sk-test"}


def single_cfg(model="cfg-model"):
    return {
        "settings": {"timeout": 7},
        "primary_backends": [
            {"name": "LLM1", "url": "http://test1.example.com/v1", "model": model}
        ],
    }


async def test_basic_completion():
    fake = FakeBackend("LLM1", text="The answer is 42.")
    async with make_client(single_cfg(), LLM1=fake) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "x", "messages": [{"role": "user", "content": "q"}]},
            headers=AUTH,
        )
    assert r.status_code == 200
    data = r.json()
    assert data["object"] == "chat.completion"
    assert data["choices"][0]["message"]["content"] == "The answer is 42."
    assert data["backend"] == "LLM1"


async def test_v1_alias():
    fake = FakeBackend("LLM1", text="ok")
    async with make_client(single_cfg(), LLM1=fake) as client:
        r = await client.post(
            "/v1/chat/completions", json={"model": "x", "messages": []}, headers=AUTH
        )
    assert r.status_code == 200


async def test_config_model_overrides_request_model():
    fake = FakeBackend("LLM1", model="cfg-model", text="ok")
    async with make_client(single_cfg("cfg-model"), LLM1=fake) as client:
        await client.post(
            "/chat/completions",
            json={"model": "request-model", "messages": []},
            headers=AUTH,
        )
    assert fake.calls[0].body["model"] == "request-model"  # raw body recorded
    # effective model applied by prepare_body inside the backend:
    # FakeBackend echoes the effective model in its response
    r2 = await fake.complete({"model": "request-model"}, {}, 5)
    assert r2.body["model"] == "cfg-model"


async def test_request_model_used_when_config_blank():
    fake = FakeBackend("LLM1", model="", text="ok")
    async with make_client(single_cfg(""), LLM1=fake) as client:
        r = await client.post(
            "/chat/completions", json={"model": "req-model", "messages": []}, headers=AUTH
        )
    assert r.status_code == 200


async def test_400_when_no_model_anywhere():
    fake = FakeBackend("LLM1", model="", text="ok")
    async with make_client(single_cfg(""), LLM1=fake) as client:
        r = await client.post("/chat/completions", json={"messages": []}, headers=AUTH)
    assert r.status_code == 400
    err = r.json()["error"]
    assert err["type"] == "invalid_request_error"
    assert "Model must be specified" in err["message"]
    assert fake.calls == []


async def test_500_when_no_valid_backends():
    cfg = {"settings": {}, "primary_backends": [{"name": "X", "url": "", "model": "m"}]}
    async with make_client(cfg) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 500
    assert r.json()["error"]["type"] == "configuration_error"


async def test_invalid_json_body_400():
    fake = FakeBackend("LLM1", text="ok")
    async with make_client(single_cfg(), LLM1=fake) as client:
        r = await client.post(
            "/chat/completions", content=b"{not json", headers={**AUTH, "content-type": "application/json"}
        )
    assert r.status_code == 400


async def test_multi_backend_gather_non_parallel_returns_first_success():
    """No strategy config → non-parallel, but ALL backends are still called
    (oai_proxy.py:1132-1137; asserted by the reference's
    test_chat_completions.py:256-304)."""
    cfg = {
        "settings": {"timeout": 5},
        "primary_backends": [
            {"name": "LLM1", "url": "http://test1.example.com/v1", "model": "m1"},
            {"name": "LLM2", "url": "http://test2.example.com/v1", "model": "m2"},
        ],
    }
    f1 = FakeBackend("LLM1", text="first")
    f2 = FakeBackend("LLM2", text="second")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "first"
    assert len(f1.calls) == 1 and len(f2.calls) == 1


async def test_first_failure_falls_back_to_other_backend():
    cfg = {
        "settings": {"timeout": 5},
        "primary_backends": [
            {"name": "LLM1", "url": "http://test1.example.com/v1", "model": "m1"},
            {"name": "LLM2", "url": "http://test2.example.com/v1", "model": "m2"},
        ],
    }
    f1 = FakeBackend("LLM1", fail_with=BackendError("down", status_code=502))
    f2 = FakeBackend("LLM2", text="survivor")
    async with make_client(cfg, LLM1=f1, LLM2=f2) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] == "survivor"


async def test_all_fail_500_with_first_error():
    f1 = FakeBackend("LLM1", fail_with=BackendError("kaboom", status_code=500))
    async with make_client(single_cfg(), LLM1=f1) as client:
        r = await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert r.status_code == 500
    err = r.json()["error"]
    assert err["type"] == "proxy_error"
    assert "All backends failed" in err["message"]
    assert "kaboom" in err["message"]


async def test_timeout_propagated_to_backend():
    fake = FakeBackend("LLM1", text="ok")
    async with make_client(single_cfg(), LLM1=fake) as client:
        await client.post("/chat/completions", json={"model": "m"}, headers=AUTH)
    assert fake.calls[0].timeout == 7.0


async def test_unknown_route_404_and_wrong_method_405():
    fake = FakeBackend("LLM1", text="ok")
    async with make_client(single_cfg(), LLM1=fake) as client:
        assert (await client.get("/nope")).status_code == 404
        assert (await client.get("/chat/completions")).status_code == 405


async def test_malformed_max_tokens_is_single_400():
    """Request-level junk must be one 400 up front, not N backend failures
    collapsing into a 500 proxy_error (docs/api.md contract)."""
    fake = FakeBackend("LLM1", text="never reached")
    async with make_client(single_cfg(), LLM1=fake) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "messages": [{"role": "user", "content": "q"}],
                  "max_tokens": 0},
            headers=AUTH,
        )
    assert r.status_code == 400
    assert r.json()["error"]["type"] == "invalid_request_error"


async def test_malformed_temperature_is_single_400():
    fake = FakeBackend("LLM1", text="never reached")
    async with make_client(single_cfg(), LLM1=fake) as client:
        r = await client.post(
            "/chat/completions",
            json={"model": "m", "messages": [{"role": "user", "content": "q"}],
                  "temperature": "abc"},
            headers=AUTH,
        )
    assert r.status_code == 400
    assert r.json()["error"]["type"] == "invalid_request_error"
