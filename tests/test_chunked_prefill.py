"""Chunked prefill + bounded admission queue (VERDICT r2 weakness 6).

Long-prompt admissions must not stall in-flight decodes: the scheduler
advances each admission one prompt segment per iteration, running a decode
chunk for active slots in between. And the pending queue is bounded —
overload surfaces as a 503, not unbounded memory growth.
"""

import threading
import time

import pytest

from quorum_tpu.engine.engine import InferenceEngine, QueueFullError
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

TINY = resolve_spec("llama-tiny")  # max_seq 128


def test_chunked_matches_single_shot_prefill():
    """A long prompt admitted in 16-token segments must generate exactly the
    same tokens as single-shot prefill: the segment path writes the same
    K/V, and the first token is sampled from the same logits and the same
    PRNG stream (see InferenceEngine._register_fn)."""
    prompt = [(7 + 13 * i) % 500 for i in range(100)]
    eng_one = InferenceEngine(TINY, decode_chunk=4, n_slots=2, prefill_chunk=0)
    eng_seg = InferenceEngine(TINY, decode_chunk=4, n_slots=2, prefill_chunk=16)
    assert eng_one.prefill_chunk == 0  # chunking disabled → single-shot
    assert eng_seg.prefill_chunk == 16

    for sampler in (SamplerConfig(temperature=0.0),
                    SamplerConfig(temperature=0.8, top_p=0.9)):
        one = eng_one.generate(prompt, max_new_tokens=12, sampler=sampler,
                               seed=3).token_ids
        seg = eng_seg.generate(prompt, max_new_tokens=12, sampler=sampler,
                               seed=3).token_ids
        assert seg == one


def test_long_admission_does_not_stall_active_stream():
    """While a 100-token prompt is being admitted in 16-token segments, an
    already-active stream must keep emitting tokens (the round-2 engine ran
    every admission to completion before the next decode chunk)."""
    eng = InferenceEngine(TINY, decode_chunk=2, n_slots=2, prefill_chunk=16)
    # Warm the compile caches so timing reflects scheduling, not XLA.
    eng.generate([1] * 100, max_new_tokens=4)
    eng.generate([1, 2, 3], max_new_tokens=4)

    events = []  # (who, token-index) in arrival order
    long_prompt = [(3 + 11 * i) % 500 for i in range(100)]
    started = threading.Event()
    submitted = threading.Event()

    def active_stream():
        for i, _ in enumerate(eng.generate_stream([5, 6, 7], max_new_tokens=40)):
            events.append(("active", i))
            started.set()
            if submitted.is_set():
                time.sleep(0.001)  # let the scheduler interleave

    def long_admission():
        started.wait(timeout=30)
        submitted.set()
        for i, _ in enumerate(eng.generate_stream(long_prompt, max_new_tokens=4)):
            events.append(("long", i))

    t1 = threading.Thread(target=active_stream)
    t2 = threading.Thread(target=long_admission)
    t1.start(); t2.start()
    t1.join(timeout=60); t2.join(timeout=60)
    assert not t1.is_alive() and not t2.is_alive()

    # Tokens the active stream emitted strictly between the long request's
    # submission window and its first token:
    long_first = next(i for i, (who, _) in enumerate(events) if who == "long")
    active_before = [e for e in events[:long_first] if e[0] == "active"]
    assert len(active_before) >= 6, (
        f"active stream starved during long admission: {events[:long_first]}"
    )
    # And the long request still completed correctly.
    assert sum(1 for who, _ in events if who == "long") == 4


def test_chunked_admission_correct_under_concurrent_decode():
    """The critical interleaving property: while a chunked admission is in
    progress, interleaved decode chunks for OTHER slots must not corrupt the
    admitted prompt's K/V (decode's dummy writes for inactive rows used to
    land at position 0 — exactly where segment 0 had just written). The long
    request's tokens under load must equal its tokens when run alone."""
    long_prompt = [(3 + 11 * i) % 500 for i in range(100)]
    solo = InferenceEngine(TINY, decode_chunk=2, n_slots=2, prefill_chunk=16)
    expect = solo.generate(long_prompt, max_new_tokens=6,
                           sampler=SamplerConfig(temperature=0.0)).token_ids

    eng = InferenceEngine(TINY, decode_chunk=2, n_slots=2, prefill_chunk=16)
    eng.generate([1] * 100, max_new_tokens=4)  # warm compile caches
    eng.generate([1, 2, 3], max_new_tokens=4)

    got = {}
    started = threading.Event()

    def active_stream():
        for i, _ in enumerate(eng.generate_stream([5, 6, 7], max_new_tokens=60)):
            started.set()
            time.sleep(0.001)

    def long_request():
        started.wait(timeout=30)
        got["toks"] = eng.generate(long_prompt, max_new_tokens=6,
                                   sampler=SamplerConfig(temperature=0.0)).token_ids

    t1 = threading.Thread(target=active_stream)
    t2 = threading.Thread(target=long_request)
    t1.start(); t2.start()
    t1.join(timeout=120); t2.join(timeout=120)
    assert not t1.is_alive() and not t2.is_alive()
    assert got["toks"] == expect


def test_history_bucketed_decode_matches_full_cache_read():
    """Decode attention reads only the live cache prefix (a power-of-two
    'history' bucket ≪ max_seq for short conversations — the decode-side
    HBM-bandwidth fix). The generated tokens must be identical to an engine
    whose bucket equals max_seq."""
    import dataclasses

    big = dataclasses.replace(TINY, max_seq=128)
    eng = InferenceEngine(big, decode_chunk=4, n_slots=2)
    prompt = [5, 6, 7]  # bucket stays at 16 while max_seq is 128
    toks = eng.generate(prompt, max_new_tokens=8,
                        sampler=SamplerConfig(temperature=0.0)).token_ids
    assert ((4, False, 16) in eng._decode_cache
            or (4, False, 32) in eng._decode_cache), (
        f"expected a small history bucket, got {list(eng._decode_cache)}")

    # Force the full-width bucket by generating near max_seq, same engine:
    # correctness across bucket sizes is covered by continuing generation.
    long_prompt = [(3 + i) % 500 for i in range(100)]
    toks_long = eng.generate(long_prompt, max_new_tokens=8,
                             sampler=SamplerConfig(temperature=0.0)).token_ids
    assert (4, False, 128) in eng._decode_cache
    assert len(toks_long) == 8

    # Cross-check: an engine built with max_seq equal to the bucket (16) has
    # NO padding to skip — its output for the short prompt must match.
    small = dataclasses.replace(TINY, max_seq=16)
    eng_small = InferenceEngine(small, decode_chunk=4, n_slots=2)
    toks_small = eng_small.generate(prompt, max_new_tokens=8,
                                    sampler=SamplerConfig(temperature=0.0)).token_ids
    assert toks == toks_small


def test_admission_queue_bound_raises_queue_full():
    eng = InferenceEngine(TINY, decode_chunk=2, n_slots=1, max_pending=2)
    blocker = threading.Event()
    threads = []

    def occupy():
        for _ in eng.generate_stream([1, 2], max_new_tokens=64):
            if blocker.wait(timeout=30):
                return

    t = threading.Thread(target=occupy)
    t.start()
    threads.append(t)
    time.sleep(0.5)  # let it claim the only slot
    # Fill the pending queue to its bound...
    queued = [eng._submit([3], max_new_tokens=1, sampler=SamplerConfig(),
                          seed=0, eos_id=None, cancel=None, decode_chunk=None)
              for _ in range(2)]
    # ...and the next submission must be rejected, not enqueued.
    with pytest.raises(QueueFullError):
        eng.generate([4], max_new_tokens=1)
    blocker.set()
    for q in queued:
        q.cancel.set()
    t.join(timeout=30)
    assert not t.is_alive()


def test_queue_full_maps_to_503():
    import asyncio

    from quorum_tpu.backends.base import BackendError
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    backend = TpuBackend.from_spec(BackendSpec(
        name="busy", url="tpu://llama-tiny?slots=1&queue=1&seed=9", model="t"))
    eng = backend.engine
    blocker = threading.Event()

    def occupy():
        for _ in eng.generate_stream([1, 2], max_new_tokens=64):
            if blocker.wait(timeout=30):
                return

    t = threading.Thread(target=occupy)
    t.start()
    time.sleep(0.5)
    held = eng._submit([3], max_new_tokens=1, sampler=SamplerConfig(),
                       seed=0, eos_id=None, cancel=None, decode_chunk=None)

    async def call():
        body = {"model": "t", "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 4}
        with pytest.raises(BackendError) as exc:
            await backend.complete(body, {}, timeout=30)
        return exc.value

    err = asyncio.run(call())
    assert err.status_code == 503
    assert err.body["error"]["type"] == "overloaded_error"

    async def call_stream():
        body = {"model": "t", "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 4, "stream": True}
        chunks = []
        with pytest.raises(BackendError) as exc:
            async for c in backend.stream(body, {}, timeout=30):
                chunks.append(c)
        # the 503 must arrive BEFORE any SSE chunk — a started 200 stream
        # can't be turned into an error status
        assert chunks == []
        return exc.value

    err2 = asyncio.run(call_stream())
    assert err2.status_code == 503
    blocker.set()
    held.cancel.set()
    t.join(timeout=30)
    assert not t.is_alive()
