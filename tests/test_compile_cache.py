"""Persistent XLA compilation cache (quorum_tpu/compile_cache.py).

Restart compiles become disk reads: a fresh process serving the same model
reloads its executables from ``QUORUM_TPU_COMPILE_CACHE`` instead of
recompiling. The reference proxy has no equivalent (it compiles nothing);
this is TPU-runtime surface, validated here on CPU via the explicit opt-in
(default-on applies only to TPU-configured hosts — XLA:CPU AOT entries are
host-feature-sensitive).
"""

import json
import os
import subprocess
import sys

import pytest

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import json, os, sys, time
t0 = time.time()
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.ops.sampling import SamplerConfig
spec = resolve_spec("gpt2-tiny", {"max_seq": "128"})
eng = InferenceEngine(spec, decode_chunk=4, n_slots=2)
toks = eng.generate([3, 4, 5], max_new_tokens=8,
                    sampler=SamplerConfig(temperature=0.8, top_p=0.9),
                    seed=1).token_ids
import jax
print(json.dumps({"tokens": toks, "wall": time.time() - t0,
                  "cache_dir": jax.config.jax_compilation_cache_dir}))
"""


def _run_child(cache_env: str) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if cache_env:
        env["QUORUM_TPU_COMPILE_CACHE"] = cache_env
    else:
        env.pop("QUORUM_TPU_COMPILE_CACHE", None)
    proc = subprocess.run([sys.executable, "-c", _CHILD], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_opt_in_cache_populates_and_reloads(tmp_path):
    cache = str(tmp_path / "xla")
    cold = _run_child(cache)
    assert cold["cache_dir"] == cache
    entries = os.listdir(cache)
    assert entries, "cold run wrote no cache entries"
    warm = _run_child(cache)
    # Same executables → byte-identical sampling; no new entries compiled.
    assert warm["tokens"] == cold["tokens"]
    assert sorted(os.listdir(cache)) == sorted(entries)


def test_cpu_host_defaults_off(tmp_path):
    # Without the explicit opt-in, a CPU-configured host must not set up a
    # cache (XLA:CPU AOT reloads are host-feature-sensitive).
    got = _run_child("")
    assert not got["cache_dir"]


def test_disable_knob_wins(tmp_path):
    got = _run_child("0")
    assert not got["cache_dir"]


def test_tpu_host_detection(monkeypatch):
    """ADVICE r3: a stock TPU VM (libtpu installed, neither env var set)
    must count as a TPU host; an explicit JAX_PLATFORMS=cpu still opts out."""
    import importlib.util

    from quorum_tpu import compile_cache

    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert compile_cache.tpu_host_configured() is False

    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    assert compile_cache.tpu_host_configured() is True

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert compile_cache.tpu_host_configured() is True  # axon hook wins

    # Stock TPU VM: no env vars at all, libtpu importable.
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    real_find = importlib.util.find_spec
    monkeypatch.setattr(
        importlib.util, "find_spec",
        lambda name, *a: object() if name == "libtpu" else real_find(name, *a))
    assert compile_cache.tpu_host_configured() is True

    monkeypatch.setattr(importlib.util, "find_spec", lambda name, *a: None)
    assert compile_cache.tpu_host_configured() is False
