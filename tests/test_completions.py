"""Legacy OpenAI ``/completions``: raw-prompt generation + teacher-forced
scoring (beyond reference — it proxies only /chat/completions).

The scoring mode (``echo=true, logprobs=k, max_tokens=0``) is the contract
eval harnesses use for perplexity; pins here cover its exactness properties
(batch-of-one equals batched scoring, determinism, top-k containment), the
legacy wire shape for generation and streaming, and the documented 400
families.
"""

import json

import numpy as np
import pytest

from tests.conftest import make_client

# Engine-scale / compile-heavy: slow tier (make test skips, make test-all
# and CI run everything).
pytestmark = pytest.mark.slow

URL = "tpu://llama-tiny?seed=1&max_seq=256&slots=4&max_tokens=8"


def cfg(url: str = URL, model: str = "tiny"):
    return {
        "settings": {"timeout": 300},
        "primary_backends": [{"name": "C1", "url": url, "model": model}],
    }


async def post(client, body):
    return await client.post("/v1/completions", json=body,
                             headers={"Authorization": "Bearer t"})


async def test_generation_wire_shape_and_determinism():
    async with make_client(cfg()) as client:
        body = {"model": "tiny", "prompt": "once upon a time",
                "max_tokens": 8, "temperature": 0.0, "seed": 3}
        r1 = await post(client, body)
        assert r1.status_code == 200, r1.text
        got = r1.json()
        assert got["object"] == "text_completion"
        assert got["id"].startswith("cmpl-")
        assert got["backend"] == "C1" and got["model"] == "tiny"
        (choice,) = got["choices"]
        assert choice["index"] == 0 and choice["logprobs"] is None
        assert choice["text"] and choice["finish_reason"] in ("stop", "length")
        assert got["usage"]["completion_tokens"] >= 1
        # byte tokenizer: one id per prompt byte, no specials added
        assert got["usage"]["prompt_tokens"] == len("once upon a time")
        r2 = await post(client, body)
        assert r2.json()["choices"][0]["text"] == choice["text"]


async def test_echo_prepends_prompt():
    async with make_client(cfg()) as client:
        got = (await post(client, {"prompt": "echo base", "echo": True,
                                   "max_tokens": 4,
                                   "temperature": 0.0})).json()
        assert got["choices"][0]["text"].startswith("echo base")
        assert len(got["choices"][0]["text"]) > len("echo base")


async def test_scoring_mode_shape_and_batch_independence():
    """max_tokens=0 + echo + logprobs: one logprob per prompt token (first
    null), identical whether the prompt is scored alone or co-batched
    beside a longer one, and identical across calls."""
    async with make_client(cfg()) as client:
        body = {"prompt": "anchor scoring text", "echo": True,
                "logprobs": 0, "max_tokens": 0}
        alone = (await post(client, body)).json()
        (choice,) = alone["choices"]
        lp = choice["logprobs"]
        n_tok = alone["usage"]["prompt_tokens"]
        assert alone["usage"]["completion_tokens"] == 0
        assert len(lp["tokens"]) == len(lp["token_logprobs"]) == n_tok
        assert lp["token_logprobs"][0] is None
        assert all(isinstance(x, float) and x <= 0.0
                   for x in lp["token_logprobs"][1:])
        assert choice["text"] == "anchor scoring text"
        assert lp["text_offset"][0] == 0
        assert lp["text_offset"] == sorted(lp["text_offset"])

        again = (await post(client, body)).json()
        batched = (await post(client, {
            "prompt": ["anchor scoring text",
                       "a considerably longer companion prompt " * 4],
            "echo": True, "logprobs": 0, "max_tokens": 0})).json()
        a = alone["choices"][0]["logprobs"]["token_logprobs"][1:]
        b = again["choices"][0]["logprobs"]["token_logprobs"][1:]
        c = batched["choices"][0]["logprobs"]["token_logprobs"][1:]
        np.testing.assert_array_equal(a, b)
        np.testing.assert_allclose(a, c, atol=2e-4)
        assert [ch["index"] for ch in batched["choices"]] == [0, 1]


async def test_scoring_topk_contains_chosen_when_ranked():
    """With logprobs=3, every scored position's top dict has 3 entries and
    the actual token's logprob never beats the best alternative."""
    async with make_client(cfg()) as client:
        got = (await post(client, {"prompt": "ranking probe", "echo": True,
                                   "logprobs": 3, "max_tokens": 0})).json()
        lp = got["choices"][0]["logprobs"]
        assert lp["top_logprobs"][0] is None
        for actual, top in zip(lp["token_logprobs"][1:],
                               lp["top_logprobs"][1:]):
            # <= 3: distinct ids can decode to the same TEXT (bytes inside
            # a multi-byte char all render the replacement char) and the
            # legacy dict format can only carry one entry per text.
            assert 1 <= len(top) <= 3
            assert actual <= max(top.values()) + 1e-5


async def test_generation_logprobs_align_with_text():
    async with make_client(cfg()) as client:
        got = (await post(client, {"prompt": "align me", "logprobs": 2,
                                   "max_tokens": 6,
                                   "temperature": 0.0})).json()
        (choice,) = got["choices"]
        lp = choice["logprobs"]
        # Per-token decodes: a multi-byte char split across tokens renders
        # replacement chars in tokens[] while the assembled text carries
        # the real char (same convention as chat logprobs content[].token),
        # so lengths/ordering are pinned rather than byte-exact joins.
        assert len(lp["tokens"]) == got["usage"]["completion_tokens"]
        assert len(lp["tokens"]) == len(lp["token_logprobs"]) == len(
            lp["top_logprobs"]) == len(lp["text_offset"])
        assert all(1 <= len(t) <= 2 for t in lp["top_logprobs"])
        assert lp["text_offset"][0] == 0
        assert lp["text_offset"] == sorted(lp["text_offset"])


def test_moe_scoring_batch_composition_independent():
    """MoE teacher-forced scoring must pass lengths: an earlier row's PAD
    tokens (identical embeddings → identical routing) would otherwise
    flood one expert's capacity queue ahead of a later row's real tokens
    and silently change its logprobs. cf=1.5 < E/k=2 keeps drops live."""
    import numpy as np

    from quorum_tpu.engine.engine import InferenceEngine
    from quorum_tpu.engine.score import score_token_batch
    from quorum_tpu.models.model_config import resolve_spec

    spec = resolve_spec("mixtral-tiny", {"max_seq": "128",
                                         "moe_capacity_factor": "1.5"})
    eng = InferenceEngine(spec, decode_chunk=4, n_slots=1)
    short = [(i % 97) + 3 for i in range(20)]
    long = [(i % 89) + 5 for i in range(120)]
    alone = score_token_batch(eng, [long])[0]["token_logprobs"][1:]
    # co-batched beside the short prompt: its 108 pad positions sit in the
    # flattened stream BEFORE long's real tokens
    batched = score_token_batch(eng, [short, long])[1]["token_logprobs"][1:]
    eng.shutdown()
    np.testing.assert_allclose(alone, batched, atol=2e-4)


async def test_pretokenized_prompt():
    async with make_client(cfg()) as client:
        got = (await post(client, {"prompt": [[5, 6, 7, 8]],
                                   "max_tokens": 4,
                                   "temperature": 0.0})).json()
        assert got["usage"]["prompt_tokens"] == 4
        assert got["choices"][0]["text"]


async def test_streaming_legacy_wire():
    async with make_client(cfg()) as client:
        resp = await client.post(
            "/v1/completions",
            json={"prompt": "stream me", "max_tokens": 6,
                  "temperature": 0.0, "stream": True},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 200
        lines = [ln for ln in resp.text.splitlines()
                 if ln.startswith("data: ")]
        assert lines[-1] == "data: [DONE]"
        frames = [json.loads(ln[len("data: "):]) for ln in lines[:-1]]
        assert frames, "no frames"
        assert all(f["object"] == "text_completion" for f in frames)
        text = "".join(f["choices"][0]["text"] for f in frames
                       if f["choices"])
        assert text
        finishes = [f["choices"][0]["finish_reason"] for f in frames
                    if f["choices"]]
        assert finishes[-1] in ("stop", "length")
        # no chat-style delta/role keys anywhere on the legacy wire
        assert all("delta" not in (f["choices"] or [{}])[0] for f in frames)

        # streamed text matches the non-streaming result (greedy)
        flat = (await post(client, {"prompt": "stream me", "max_tokens": 6,
                                    "temperature": 0.0})).json()
        assert text == flat["choices"][0]["text"]


@pytest.mark.parametrize("body,fragment", [
    ({"prompt": "x", "n": 2}, "'n' > 1"),
    ({"prompt": "x", "max_tokens": 0}, "scoring"),
    ({"prompt": "x", "logprobs": 6}, "logprobs"),
    ({"prompt": "x", "best_of": 2}, "best_of"),
    ({"prompt": "x", "suffix": "y"}, "suffix"),
    ({"prompt": ""}, "prompt"),
    ({"prompt": []}, "prompt"),
    ({"prompt": ["text", [5, 6]]}, "must not mix"),
    ({"prompt": "x " * 500, "echo": True, "logprobs": 0, "max_tokens": 0},
     "max_seq"),
    ({"prompt": ["a", "b"], "stream": True}, "exactly one prompt"),
    ({"prompt": "x", "stream": True, "logprobs": 1}, "stream"),
    ({"prompt": "x", "stream": True, "n": 2}, "'n' > 1"),
    ({"prompt": "x", "stream": True, "best_of": 3}, "best_of"),
])
async def test_invalid_requests_400(body, fragment):
    async with make_client(cfg()) as client:
        resp = await post(client, {"model": "tiny", **body})
        assert resp.status_code == 400, resp.text
        err = resp.json()["error"]
        assert err["type"] == "invalid_request_error"
        assert fragment in err["message"], err["message"]


async def test_stream_accepts_serialized_defaults_and_config_model():
    """logprobs=false / best_of=1 / n=1 are serialized client defaults —
    streaming must accept them like the flat path; a request without a
    model falls to the configured backend, and frames carry its configured
    model string like flat responses."""
    async with make_client(cfg()) as client:
        resp = await client.post(
            "/v1/completions",
            json={"prompt": "defaults",
                  "max_tokens": 3, "temperature": 0.0, "stream": True,
                  "logprobs": False, "best_of": 1, "n": 1},
            headers={"Authorization": "Bearer t"})
        assert resp.status_code == 200, resp.text
        frames = [json.loads(ln[len("data: "):])
                  for ln in resp.text.splitlines()
                  if ln.startswith("data: ") and ln != "data: [DONE]"]
        assert frames and all(f["model"] == "tiny" for f in frames)
        flat = (await post(client, {"prompt": "defaults", "max_tokens": 3,
                                    "temperature": 0.0})).json()
        assert flat["model"] == "tiny"


async def test_unknown_model_is_404_not_silent_fallback():
    """ADVICE r4: a typo'd model on the no-fan-out endpoints must answer
    OpenAI's model_not_found, never be silently scored by a different
    model's backend (eval harnesses key results on `model`)."""
    async with make_client(cfg()) as client:
        resp = await post(client, {"model": "something-else",
                                   "prompt": "x", "max_tokens": 2})
        assert resp.status_code == 404, resp.text
        err = resp.json()["error"]
        assert err["code"] == "model_not_found"
        assert err["param"] == "model"
        assert "something-else" in err["message"]
        # the configured name still serves
        ok = await post(client, {"model": "tiny", "prompt": "x",
                                 "max_tokens": 2, "temperature": 0.0})
        assert ok.status_code == 200, ok.text


async def test_best_of_one_is_a_noop():
    """best_of=1 is the documented OpenAI default — clients that serialize
    defaults must not be rejected."""
    async with make_client(cfg()) as client:
        resp = await post(client, {"prompt": "defaults", "best_of": 1,
                                   "n": 1, "max_tokens": 2,
                                   "temperature": 0.0})
        assert resp.status_code == 200, resp.text


async def test_raw_prompt_ids_not_injectable_from_wire():
    """_raw_prompt_ids is internal: a wire body carrying it must not bypass
    chat templating on /chat/completions (stripped at the route)."""
    async with make_client(cfg()) as client:
        body = {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "legit"}]}
        clean = await client.post("/v1/chat/completions", json=body,
                                  headers={"Authorization": "Bearer t"})
        injected = await client.post(
            "/v1/chat/completions", json={**body, "_raw_prompt_ids": [5, 6]},
            headers={"Authorization": "Bearer t"})
        assert clean.status_code == injected.status_code == 200
        assert (clean.json()["choices"][0]["message"]["content"]
                == injected.json()["choices"][0]["message"]["content"])
        # a templated chat prompt is longer than the injected 2 ids
        assert injected.json()["usage"]["prompt_tokens"] > 2


async def test_no_capable_backend_500_and_auth(monkeypatch):
    from quorum_tpu.backends.fake import FakeBackend

    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    config = {"settings": {"timeout": 60},
              "primary_backends": [
                  {"name": "F", "url": "http://fake.example", "model": "m"}]}
    async with make_client(config, F=FakeBackend("F", model="m")) as client:
        resp = await post(client, {"prompt": "x"})
        assert resp.status_code == 500
        assert resp.json()["error"]["type"] == "configuration_error"
        noauth = await client.post("/v1/completions", json={"prompt": "x"})
        assert noauth.status_code == 401


async def test_http_backend_relays_completions():
    import httpx

    from quorum_tpu.backends.http_backend import HttpBackend

    seen = {}

    def handler(request):
        seen["path"] = request.url.path
        seen["body"] = json.loads(request.content)
        return httpx.Response(200, json={
            "object": "text_completion", "id": "cmpl-up",
            "choices": [{"index": 0, "text": "hi", "logprobs": None,
                         "finish_reason": "stop"}],
            "model": "cfg-model",
            "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                      "total_tokens": 2}})

    client = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    be = HttpBackend("H", "http://up.example/v1", model="cfg-model",
                     client=client)
    res = await be.text_complete({"model": "req", "prompt": "x"},
                                 {"Authorization": "Bearer k"}, 30)
    assert res.ok and res.body["backend"] == "H"
    assert seen["path"] == "/v1/completions"
    assert seen["body"]["model"] == "cfg-model" and seen["body"]["stream"] is False
    await be.aclose()


async def test_echo_logprobs_offsets_multibyte_utf8():
    """ADVICE r4: echo-mode token texts / text_offset must track the
    echoed prompt string even when byte-level tokens split a multi-byte
    UTF-8 character — per-token decode would emit replacement chars whose
    lengths drift every later offset."""
    async with make_client(cfg()) as client:
        prompt = "café au läit"  # é/ä are 2 UTF-8 bytes → 2 byte-tokens
        resp = await post(client, {"model": "tiny", "prompt": prompt,
                                   "echo": True, "logprobs": 0,
                                   "max_tokens": 0})
        assert resp.status_code == 200, resp.text
        choice = resp.json()["choices"][0]
        assert choice["text"] == prompt
        lp = choice["logprobs"]
        toks, offs = lp["tokens"], lp["text_offset"]
        assert len(toks) == len(offs) == len(lp["token_logprobs"])
        assert "".join(toks) == prompt  # no replacement chars, no drift
        pos = 0
        for t, o in zip(toks, offs):
            assert o == pos  # each offset indexes its token's start
            assert prompt[o:o + len(t)] == t
            pos += len(t)
