"""Config loading, schema superset, and tpu:// URL parsing."""

import pytest

from quorum_tpu.config import (
    BackendSpec,
    Config,
    DEFAULT_CONFIG,
    load_config,
)


THREE_BACKENDS_YAML = """
settings:
  timeout: 30
primary_backends:
  - name: LLM1
    url: http://test1.example.com/v1
    model: model-a
  - name: LLM2
    url: http://test2.example.com/v1
    model: ""
  - name: LLM3
    url: ""
    model: model-c
iterations:
  aggregation:
    strategy: concatenate
strategy:
  concatenate:
    separator: "\\n---\\n"
    hide_intermediate_think: true
    hide_final_think: false
    thinking_tags: ["think"]
  aggregate:
    aggregator_backend: LLM1
    source_backends: ["LLM1", "LLM2"]
    suppress_individual_responses: true
"""


def test_load_from_path(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text(THREE_BACKENDS_YAML)
    cfg = load_config(p)
    assert cfg.timeout == 30
    assert [b.name for b in cfg.backends] == ["LLM1", "LLM2", "LLM3"]
    # Invalid (empty-url) backend filtered, parity with oai_proxy.py:1010.
    assert [b.name for b in cfg.valid_backends] == ["LLM1", "LLM2"]
    assert cfg.strategy_name == "concatenate"
    assert cfg.parallel_enabled() is True


def test_fallback_to_default_on_missing_file(tmp_path):
    cfg = load_config(tmp_path / "nope.yaml")
    assert cfg.raw == DEFAULT_CONFIG
    assert cfg.timeout == 60
    assert cfg.backends[0].url == "https://api.openai.com/v1"
    assert cfg.parallel_enabled() is False


def test_fallback_on_invalid_yaml(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text("just a scalar")
    cfg = load_config(p)
    assert cfg.raw == DEFAULT_CONFIG


def test_env_var_path(tmp_path, monkeypatch):
    p = tmp_path / "custom.yaml"
    p.write_text(THREE_BACKENDS_YAML)
    monkeypatch.setenv("QUORUM_TPU_CONFIG", str(p))
    cfg = load_config()
    assert cfg.timeout == 30


def test_strategy_params(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text(THREE_BACKENDS_YAML)
    cfg = load_config(p)
    c = cfg.concatenate
    assert c.separator == "\n---\n"
    assert c.hide_intermediate_think is True
    assert c.thinking_tags == ["think"]
    a = cfg.aggregate
    assert a.aggregator_backend == "LLM1"
    assert a.source_backends == ["LLM1", "LLM2"]
    assert a.suppress_individual_responses is True
    assert "{intermediate_results}" in a.prompt_template


def test_parallel_requires_strategy_keys():
    cfg = Config(raw={
        "primary_backends": [
            {"name": "a", "url": "http://a/v1"},
            {"name": "b", "url": "http://b/v1"},
        ],
        "settings": {"timeout": 5},
    })
    # >1 backend but no iterations/strategy keys → not parallel
    # (oai_proxy.py:1043-1044 parity).
    assert cfg.parallel_enabled() is False


def test_tpu_url_parsing():
    b = BackendSpec(name="local", url="tpu://gpt2?family=gpt2&d_model=256&n_layers=2")
    assert b.is_tpu
    assert b.tpu_model_id == "gpt2"
    assert b.tpu_options == {"family": "gpt2", "d_model": "256", "n_layers": "2"}
    h = BackendSpec(name="remote", url="https://api.openai.com/v1")
    assert not h.is_tpu
    assert h.scheme == "https"
