"""Grammar-compilation unit tests (fast tier — no engine, no jax device
work): the regex→byte-DFA pipeline, the JSON Schema lowering, token-table
construction over real tokenizers, dead-end trimming, and the compile
cache. The device half (on-device masking inside decode chunks) is pinned
by tests/test_constrained_decoding.py."""

import json

import numpy as np
import pytest

from quorum_tpu.constrain import (
    CompiledGrammar,
    GrammarError,
    GrammarUnsatisfiable,
    clear_compile_cache,
    compile_ast,
    compile_pattern,
    compile_response_format,
    json_value_ast,
    lift_to_tokens,
    schema_ast,
)
from quorum_tpu.constrain.grammar import json_object_ast
from quorum_tpu.engine.tokenizer import ByteTokenizer
from quorum_tpu.observability import (
    CONSTRAIN_CACHE_HITS,
    CONSTRAIN_CACHE_MISSES,
    CONSTRAIN_COMPILE,
)


# ---- byte-level regex → DFA ------------------------------------------------


def test_alternation_and_literals():
    d = compile_pattern("ab|ac")
    assert d.matches(b"ab") and d.matches(b"ac")
    assert not d.matches(b"a") and not d.matches(b"abc") \
        and not d.matches(b"bc")


def test_classes_ranges_and_bounded_repetition():
    d = compile_pattern("[a-c]{2,4}")
    assert d.matches(b"ab") and d.matches(b"abca")
    assert not d.matches(b"a") and not d.matches(b"abcab") \
        and not d.matches(b"ad")


def test_negated_class_and_escapes():
    d = compile_pattern(r'"[^"\\]*"')
    assert d.matches(b'""') and d.matches(b'"hi there"')
    assert not d.matches(b'"a"b"')
    hexd = compile_pattern(r"\x41+")
    assert hexd.matches(b"AAA") and not hexd.matches(b"B")


def test_json_integer_pattern():
    d = compile_pattern(r"-?(0|[1-9]\d*)")
    for ok in (b"0", b"7", b"-123", b"90210"):
        assert d.matches(ok), ok
    for bad in (b"01", b"-", b"", b"1.5"):
        assert not d.matches(bad), bad


def test_unsupported_syntax_is_a_grammar_error():
    for pattern in ("a(?=b)", "(", "a{5,2}", "[z-a]", "", "a\\q"):
        with pytest.raises(GrammarError):
            compile_pattern(pattern)


def test_dfa_is_trimmed_every_state_reaches_accept():
    d = compile_pattern("abc|abd")
    # From every state, some byte path must reach an accept state — the
    # property that makes runtime dead-ends impossible.
    n = d.n_states
    live = d.accept.copy()
    for _ in range(n):
        tgt = np.where(d.trans >= 0, live[np.clip(d.trans, 0, n - 1)], False)
        live = live | tgt.any(axis=1)
    assert live.all()


# ---- JSON Schema lowering --------------------------------------------------


def _accepts(schema, value) -> bool:
    dfa = compile_ast(schema_ast(schema))
    return dfa.matches(
        json.dumps(value, separators=(",", ":"),
                   ensure_ascii=True).encode())


def test_schema_object_properties_in_order():
    schema = {"type": "object", "properties": {
        "ok": {"type": "boolean"},
        "dir": {"enum": ["N", "S"]},
        "n": {"type": "integer"}}}
    assert _accepts(schema, {"ok": True, "dir": "N", "n": -42})
    assert not _accepts(schema, {"ok": True})          # all props required
    assert not _accepts(schema, {"ok": "yes", "dir": "N", "n": 1})
    # canonical form: whitespace is NOT accepted
    dfa = compile_ast(schema_ast(schema))
    assert not dfa.matches(b'{"ok": true,"dir":"N","n":1}')


def test_schema_scalars_arrays_bounds():
    assert _accepts({"type": "number"}, -2.5e3)
    assert _accepts({"type": "null"}, None)
    assert _accepts({"type": ["integer", "null"]}, None)
    arr = {"type": "array", "items": {"type": "integer"},
           "minItems": 1, "maxItems": 3}
    assert _accepts(arr, [1]) and _accepts(arr, [1, 2, 3])
    assert not _accepts(arr, []) and not _accepts(arr, [1, 2, 3, 4])
    s = {"type": "string", "minLength": 2, "maxLength": 4}
    assert _accepts(s, "ab") and _accepts(s, "abcd")
    assert not _accepts(s, "a") and not _accepts(s, "abcde")


def test_schema_enum_const_oneof():
    assert _accepts({"enum": ["N", "S", 3, None]}, 3)
    assert _accepts({"const": "fixed"}, "fixed")
    assert not _accepts({"const": "fixed"}, "other")
    assert _accepts({"oneOf": [{"type": "integer"}, {"type": "boolean"}]},
                    True)


def test_schema_unsupported_keywords_rejected():
    for schema in ({"$ref": "#/x"}, {"allOf": []},
                   {"type": "string", "pattern": "a+"},
                   {"type": "object", "patternProperties": {}},
                   # validating keywords the automaton cannot enforce must
                   # 400, never silently loosen (a 200 whose content fails
                   # jsonschema would break the guaranteed-valid contract)
                   {"type": "integer", "minimum": 0},
                   {"type": "number", "multipleOf": 2},
                   {"type": "integer", "exclusiveMaximum": 10},
                   {"type": "object", "minProperties": 1},
                   {"type": "array", "items": {"type": "integer"},
                    "uniqueItems": True}):
        with pytest.raises(GrammarError):
            schema_ast(schema)


def test_schema_required_subset_honored():
    props = {"a": {"type": "boolean"}, "b": {"type": "null"}}
    # required ⊆ properties: satisfied by construction (all emitted)
    assert _accepts({"type": "object", "properties": props,
                     "required": ["a"]}, {"a": True, "b": None})
    # required naming an undeclared property cannot be honored
    with pytest.raises(GrammarError):
        schema_ast({"type": "object", "properties": props,
                    "required": ["c"]})


def test_json_value_depth_bound():
    dfa = compile_ast(json_value_ast(1))
    assert dfa.matches(b'[1,"a",null]')
    assert not dfa.matches(b"[[1]]")  # nesting beyond the depth budget
    top = compile_ast(json_object_ast(1))
    assert top.matches(b'{"a":1}') and not top.matches(b"3")


# ---- token lifting over real tokenizers ------------------------------------


def _grammar(schema, vocab=512):
    tok = ByteTokenizer(vocab)
    return tok, compile_response_format(
        {"type": "json_schema", "json_schema": {"schema": schema}},
        tok, vocab)


def test_token_dfa_walks_conforming_document():
    schema = {"type": "object", "properties": {
        "ok": {"type": "boolean"}, "n": {"type": "integer"}}}
    tok, g = _grammar(schema)
    doc = '{"ok":false,"n":12}'
    end = g.advance_tokens(g.start, tok.encode(doc))
    assert end >= 0 and g.accept[end]
    # a wrong token dead-ends immediately
    assert g.advance_tokens(g.start, tok.encode("[")) == -1
    # partial documents are non-accepting but alive
    mid = g.advance_tokens(g.start, tok.encode('{"ok":'))
    assert mid >= 0 and not g.accept[mid]


def test_specials_and_zero_text_tokens_disallowed():
    _, g = _grammar({"type": "boolean"})
    # pad/bos/eos produce no text: allowing them would let the model stall
    # the grammar forever. EOS is handled separately via accept states.
    assert (g.trans[:, :3] == -1).all()


def test_folding_vocab_aliases_share_transitions():
    # vocab 512 folds ids ≥ 259 back onto bytes: every alias of an allowed
    # byte must be allowed and transition identically.
    tok, g = _grammar({"type": "boolean"})
    t_id = tok.encode("t")[0]
    alias = t_id + 256  # same byte under the fold
    assert tok.token_byte(alias) == tok.token_byte(t_id)
    assert g.trans[g.start, t_id] == g.trans[g.start, alias] >= 0


def test_accept_sink_allows_nothing():
    # After a complete fixed-shape document the state must allow NO token
    # (EOS only, via accept) — that forced EOS is what maps grammar
    # completion onto finish_reason "stop" on device.
    tok, g = _grammar({"const": "x"})
    end = g.advance_tokens(g.start, tok.encode('"x"'))
    assert g.accept[end]
    assert not g.allowed(end).any()


def test_unsatisfiable_vocab_raises():
    # vocab 20 → byte_slots 17: '{' (0x7b) has no producing token.
    tok = ByteTokenizer(20)
    with pytest.raises(GrammarUnsatisfiable):
        compile_response_format({"type": "json_object"}, tok, 20)


def test_malformed_response_format_raises_grammar_error():
    tok = ByteTokenizer(512)
    for rf in ({"type": "json_schema"},
               {"type": "json_schema", "json_schema": {}},
               {"type": "regex"},
               {"type": "regex", "pattern": ""},
               {"type": "xml"},
               "json"):
        with pytest.raises(GrammarError):
            compile_response_format(rf, tok, 512)


def test_response_format_text_is_none():
    tok = ByteTokenizer(512)
    assert compile_response_format({"type": "text"}, tok, 512) is None


# ---- compile cache + metrics -----------------------------------------------


def test_compile_cache_hits_and_metrics():
    clear_compile_cache()
    tok = ByteTokenizer(512)
    rf = {"type": "regex", "pattern": "ab+c"}
    h0 = CONSTRAIN_CACHE_HITS.value
    m0 = CONSTRAIN_CACHE_MISSES.value
    c0 = CONSTRAIN_COMPILE.snapshot().get((), {}).get("count", 0)
    g1 = compile_response_format(rf, tok, 512)
    g2 = compile_response_format(rf, tok, 512)
    assert g2 is g1  # cached per (grammar, tokenizer)
    assert CONSTRAIN_CACHE_MISSES.value == m0 + 1
    assert CONSTRAIN_CACHE_HITS.value == h0 + 1
    assert CONSTRAIN_COMPILE.snapshot()[()]["count"] == c0 + 1
    # a different vocab is a different tokenizer key
    g3 = compile_response_format(rf, ByteTokenizer(300), 300)
    assert g3 is not g1 and g3.vocab_size == 300


def test_lift_preserves_grammar_against_random_walks():
    """Property check: any token path the lifted DFA allows must decode to
    a byte string the byte DFA accepts once an accept state is reached."""
    tok = ByteTokenizer(512)
    schema = {"type": "object", "properties": {
        "a": {"enum": ["x", "yy"]},
        "b": {"type": "integer"}}}
    dfa = compile_ast(schema_ast(schema))
    g = compile_response_format(
        {"type": "json_schema", "json_schema": {"schema": schema}},
        tok, 512)
    rng = np.random.default_rng(0)
    for _ in range(20):
        state, ids = g.start, []
        for _ in range(400):
            if g.accept[state]:
                break
            allowed = np.flatnonzero(g.allowed(state))
            assert allowed.size, "non-accept state with nothing allowed"
            t = int(rng.choice(allowed))
            ids.append(t)
            state = int(g.trans[state, t])
        assert g.accept[state]
        text = tok.decode(ids)
        assert dfa.matches(text.encode()), text
        json.loads(text)


class _FakeHF:
    """Minimal HF-tokenizer stand-in for the byte-table unit tests."""

    def __init__(self, tokens, specials=()):
        self._tokens = tokens
        self.all_special_ids = list(specials)

    def convert_ids_to_tokens(self, ids):
        return [self._tokens[i] for i in ids]


def test_hf_byte_table_sentencepiece_convention():
    """Sentencepiece vocabularies: '▁'→space, <0xHH> byte-fallback tokens
    are single raw bytes (NOT their 6-char ASCII spelling — that would
    let a raw control byte through a JSON string mask), and accented
    tokens encode UTF-8 (NOT the GPT-2 byte map)."""
    from quorum_tpu.constrain.grammar import _hf_token_bytes

    hf = _FakeHF(["<s>", "▁hi", "<0x0A>", "ü", "abc"], specials=[0])
    table = _hf_token_bytes(hf, 5)
    assert table[0] is None            # special
    assert table[1] == b" hi"
    assert table[2] == b"\n"           # byte fallback, not b"<0x0A>"
    assert table[3] == "ü".encode()    # UTF-8 pair, not GPT-2-mapped 0xFC
    assert table[4] == b"abc"


def test_hf_byte_table_gpt2_bytelevel_convention():
    """Byte-level vocabularies (detected by the 'Ġ' marker): every char
    maps through bytes_to_unicode; tokens outside the alphabet are
    disallowed rather than mis-encoded."""
    from quorum_tpu.constrain.grammar import _hf_token_bytes

    hf = _FakeHF(["Ġhi", "ab", "<|end|>☃"])  # snowman: outside map
    table = _hf_token_bytes(hf, 3)
    assert table[0] == b" hi"          # Ġ is the byte-level space
    assert table[1] == b"ab"
    assert table[2] is None


def test_table_bytes_reported():
    _, g = _grammar({"type": "boolean"})
    assert g.table_bytes == g.trans.nbytes + g.accept.nbytes
    assert isinstance(g, CompiledGrammar)
