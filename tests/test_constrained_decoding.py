"""On-device constrained decoding, end to end (ISSUE 5 acceptance):

- a ``response_format`` json_schema STREAMING request at decode_pipeline=4
  yields output that json.loads-parses and validates, with the hostpath
  counters pinning zero additional blocking syncs per chunk vs an
  unconstrained request (the DFA never forces a host round-trip);
- unconstrained batches compile and dispatch the exact pre-constrain
  decode program variant (cache-key pin, mirroring the logprobs-gating
  contract);
- the constrained-vs-unconstrained determinism pin: a grammar the
  unconstrained stream already satisfies masks nothing, so the token
  streams are identical — at K=1 and K=4;
- spec-decode composition: constrained requests SPECULATE (grammar-aware
  drafts through the dfa-verify program variant) and the emitted stream
  equals the non-speculative constrained stream token for token;
- members=M stacking: per-member rows carry independent DFA states.

Everything runs the tiny preset on CPU — the same compiled code paths as
TPU (engine-scale: slow tier)."""

import json
import threading

import pytest

from quorum_tpu.analysis import budget
from quorum_tpu.constrain import compile_response_format
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.engine.tokenizer import ByteTokenizer
from quorum_tpu.models.model_config import MODEL_PRESETS
from quorum_tpu.ops.sampling import SamplerConfig

pytestmark = pytest.mark.slow

TINY = MODEL_PRESETS["llama-tiny"]
TOK = ByteTokenizer(TINY.vocab_size)
GREEDY = SamplerConfig(temperature=0.0)
SCHEMA = {"type": "object", "properties": {
    "ok": {"type": "boolean"},
    "dir": {"enum": ["N", "S", "E", "W"]},
    "n": {"type": "integer"}}}


def _grammar(rf=None):
    rf = rf or {"type": "json_schema", "json_schema": {"schema": SCHEMA}}
    return compile_response_format(rf, TOK, TINY.vocab_size)


def _run(eng, grammar, *, max_new=64, temp=0.8, seed=3, prompt="go"):
    req = eng.submit(
        TOK.encode(prompt), max_new_tokens=max_new,
        sampler=SamplerConfig(temperature=temp), seed=seed,
        eos_id=TOK.eos_id, grammar=grammar)
    return list(eng.stream_results(req))


def _text(toks):
    return TOK.decode([t for t in toks if t != TOK.eos_id])


def test_constrained_stream_at_k4_parses_with_no_extra_syncs():
    """The headline acceptance: a schema-constrained generation on a
    depth-4 ring parses and validates, and the dispatch accounting shows
    the SAME blocking-sync profile as an equal-length unconstrained run —
    the DFA is inside the chunk program, so it can never add a host
    round-trip (hostpath-bench counter contract)."""
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4)
    try:
        g = _grammar()
        toks = _run(eng, g, seed=11)
        obj = json.loads(_text(toks))
        assert isinstance(obj["ok"], bool) and obj["dir"] in "NSEW"
        assert isinstance(obj["n"], int)
        assert toks[-1] == TOK.eos_id  # grammar sink forced EOS → "stop"
        assert eng.n_overrun == 0

        # Sync accounting on an apples-to-apples pair: a wildcard grammar
        # (every byte allowed — the constrained VARIANT runs, with table
        # gathers and state advances, but masks nothing) against the
        # plain variant, same seed and budget. The streams are identical
        # (no-op masking), so the scheduler makes identical decisions and
        # any dispatch/sync difference would be the DFA's doing.
        wild = compile_response_format(
            {"type": "regex", "pattern": "[\\x00-\\xff]*"},
            TOK, TINY.vocab_size)
        n = 32  # a decode_chunk multiple: both admission paths need n/4
        _run(eng, wild, max_new=n, seed=12)          # warm constrained
        _run(eng, None, max_new=n, seed=12)          # warm plain
        c0, o0 = eng.n_decode_chunks, eng.n_overlapped
        toks_c = _run(eng, wild, max_new=n, seed=13)
        c1, o1 = eng.n_decode_chunks, eng.n_overlapped
        toks_u = _run(eng, None, max_new=n, seed=13)
        c2, o2 = eng.n_decode_chunks, eng.n_overlapped
        assert toks_u == toks_c  # no-op masking: identical stream
        assert (c1 - c0) == (c2 - c1), "chunk counts must match"
        syncs_con = (c1 - c0) - (o1 - o0)
        syncs_un = (c2 - c1) - (o2 - o1)
        assert syncs_con == syncs_un, (
            f"constrained decoding added blocking syncs: {syncs_con} vs "
            f"{syncs_un}")
        assert (o1 - o0) > 0  # the ring really pipelined under the DFA
    finally:
        eng.shutdown()


def test_unconstrained_batches_run_the_pre_constrain_program_variant():
    """The gating pin (mirrors the logprobs contract): plain decode
    programs are cached under the pre-constrain 3-tuple key with no
    mask/table operands; the constrained variant lives under its own
    tagged key; and unconstrained traffic AFTER constrained traffic adds
    no constrained-variant compiles."""
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1)
    try:
        eng.generate(TOK.encode("hi"), max_new_tokens=8, sampler=GREEDY)
        # families against the shared budget (classification also pins the
        # exact key shapes — analysis/compile_budget.json)
        assert budget.decode_families(eng._decode_cache) == {"plain"}

        _run(eng, _grammar(), max_new=32, temp=0.0)
        fams = budget.decode_families(eng._decode_cache)
        assert "dfa" in fams, "constrained traffic must use the tagged variant"
        # one literal end-to-end sentinel this file keeps: the plain key
        # stays the bare pre-constrain 3-tuple with no tag component
        assert any(isinstance(k, tuple) and len(k) == 3
                   and isinstance(k[0], int) for k in eng._decode_cache)

        before = set(eng._decode_cache)
        eng.generate(TOK.encode("hi"), max_new_tokens=8, sampler=GREEDY)
        after = set(eng._decode_cache)
        # the unconstrained request re-used plain keys; anything new is a
        # plain variant (a fresh history bucket), never a "dfa" one
        assert budget.decode_families(after - before) <= {"plain"}
    finally:
        eng.shutdown()


def test_noop_masking_is_token_identical_at_k1_and_k4():
    """Determinism pin: a grammar the unconstrained generation already
    satisfies must produce the IDENTICAL token stream — masking a token
    that would be sampled anyway is a no-op (Gumbel-argmax sampling:
    the restricted winner equals the unrestricted one whenever the
    unrestricted winner is allowed) — at K=1 and K=4."""
    e1 = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1)
    e4 = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4)
    try:
        for temp, seed in ((0.0, 3), (0.9, 7)):
            base = _run(e1, None, max_new=24, temp=temp, seed=seed)
            assert TOK.eos_id not in base  # budget finish: exact prefix
            # a pattern accepting exactly this byte stream, then anything
            pattern = "".join("\\x%02x" % b
                              for t in base for b in TOK.token_byte(t))
            pattern += "[\\x00-\\xff]*"
            g = compile_response_format(
                {"type": "regex", "pattern": pattern}, TOK,
                TINY.vocab_size)
            for eng in (e1, e4):
                got = _run(eng, g, max_new=24, temp=temp, seed=seed)
                assert got == base, (
                    f"K={eng.decode_pipeline} temp={temp}: constrained "
                    "stream diverged from its unconstrained self")
    finally:
        e1.shutdown()
        e4.shutdown()


def test_spec_decode_composes_and_matches_token_for_token():
    """Spec-decode composition (ISSUE 10): a constrained request on a
    spec_decode engine SPECULATES — the dfa-verify variant masks each
    position with its draft-prefix DFA state — and its stream equals the
    non-speculative constrained stream bit for bit, with drafts actually
    accepted (the oracle proposes the reference continuation)."""
    plain = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=2)
    spec = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=2,
                           spec_decode=4)
    try:
        g = _grammar()
        want = _run(plain, g, seed=9)
        # Oracle drafting (the suite's spec-decode idiom): the draft IS
        # the constrained reference continuation, so acceptance is bounded
        # only by the verify program's own masking/sampling parity.
        body = [t for t in want if t != TOK.eos_id]
        spec._draft = lambda req, g_: (
            body[req.emitted: req.emitted + g_]
            if req.emitted + g_ <= len(body) else None)
        turns0 = spec.n_spec_turns
        acc0 = spec.n_spec_accepted
        got = _run(spec, g, seed=9)
        assert got == want, (
            "constrained + spec_decode diverged from the non-speculative "
            "constrained stream")
        assert spec.n_spec_turns > turns0, (
            "constrained rows must take speculative verify turns now")
        assert spec.n_spec_accepted > acc0, (
            "oracle drafts under a grammar were never accepted")
        fams = budget.decode_families(spec._decode_cache)
        assert "dfa_verify" in fams, fams
    finally:
        plain.shutdown()
        spec.shutdown()


def test_mixed_batch_constrains_only_grammar_rows():
    """A constrained and an unconstrained request co-batched in one chunk:
    the unconstrained row rides the constrained program variant in the
    FREE state and must produce exactly the stream it produces alone."""
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1)
    try:
        solo = eng.generate(TOK.encode("solo"), max_new_tokens=24,
                            sampler=GREEDY).token_ids
        g = _grammar()
        cancel = threading.Event()
        r_con = eng.submit(TOK.encode("go"), max_new_tokens=64,
                           sampler=SamplerConfig(temperature=0.8), seed=5,
                           eos_id=TOK.eos_id, grammar=g, cancel=cancel)
        r_un = eng.submit(TOK.encode("solo"), max_new_tokens=24,
                          sampler=GREEDY, eos_id=None)
        con = list(eng.stream_results(r_con))
        un = list(eng.stream_results(r_un))
        assert un == solo
        json.loads(_text(con))
    finally:
        eng.shutdown()


def test_members_rows_carry_independent_states():
    """members=2 stacking: each member's constrained request advances its
    own DFA state; both streams must be grammar-valid."""
    eng = InferenceEngine(TINY, decode_chunk=4, members=2)
    try:
        g = _grammar()
        reqs = [eng.submit(TOK.encode("go"), max_new_tokens=64,
                           sampler=SamplerConfig(temperature=0.8),
                           seed=20 + m, eos_id=TOK.eos_id, grammar=g,
                           member=m)
                for m in range(2)]
        outs = [list(eng.stream_results(r)) for r in reqs]
        texts = [_text(t) for t in outs]
        for text in texts:
            obj = json.loads(text)
            assert obj["dir"] in "NSEW"
    finally:
        eng.shutdown()


def test_grammar_reuse_and_arena_stability_across_requests():
    """Same grammar across sequential requests reuses the arena offset
    (no re-upload, bucket unchanged); a second grammar extends it while
    the first's offsets stay valid."""
    eng = InferenceEngine(TINY, decode_chunk=4)
    try:
        g1 = _grammar()
        _run(eng, g1, seed=1)
        bucket1 = eng._g_bucket
        states1 = eng._g_states
        _run(eng, g1, seed=2)
        assert eng._g_states == states1 and eng._g_bucket == bucket1
        g2 = _grammar({"type": "regex", "pattern": "yes|no"})
        out = _run(eng, g2, seed=3, temp=0.0)
        assert _text(out) in ("yes", "no")
        assert eng._g_states > states1
        # and g1 still decodes correctly against the grown arena
        json.loads(_text(_run(eng, g1, seed=4)))
    finally:
        eng.shutdown()


def test_constrained_metrics_and_span_attr():
    eng = InferenceEngine(TINY, decode_chunk=4)
    try:
        _run(eng, _grammar(), seed=6)
        m = eng.metrics()
        assert m["constrained_requests_total"] == 1
        assert m["constrain_masked_tokens_total"] > 0
    finally:
        eng.shutdown()


def test_submit_rejections():
    eng = InferenceEngine(TINY, decode_chunk=4, prefill_chunk=0)
    try:
        g = _grammar()
        with pytest.raises(ValueError, match="chunked prefill"):
            eng.submit(TOK.encode("x"), max_new_tokens=8,
                       eos_id=TOK.eos_id, grammar=g)
        with pytest.raises(ValueError, match="EOS"):
            eng.submit(TOK.encode("x"), max_new_tokens=8, grammar=g)
    finally:
        eng.shutdown()


def test_arena_cap_contains_to_one_request():
    """A grammar that would grow the device arena past CONSTRAIN_ARENA_MAX
    fails ALONE (GrammarArenaFull — the backend maps it to a retryable
    503); resident grammars and unconstrained traffic keep serving."""
    import quorum_tpu.engine.engine as em

    eng = InferenceEngine(TINY, decode_chunk=4)
    old = em.CONSTRAIN_ARENA_MAX
    em.CONSTRAIN_ARENA_MAX = 8
    try:
        small = _grammar({"type": "regex", "pattern": "ab"})
        assert small.n_states <= 7
        out = _run(eng, small, max_new=8, temp=0.0)
        assert _text(out) == "ab"
        big = _grammar()  # the schema grammar: far more than 8 states
        req = eng.submit(TOK.encode("x"), max_new_tokens=8,
                         sampler=GREEDY, eos_id=TOK.eos_id, grammar=big)
        with pytest.raises(em.GrammarArenaFull):
            list(eng.stream_results(req))
        # contained: the resident grammar and plain traffic still serve
        assert _text(_run(eng, small, max_new=8, temp=0.0)) == "ab"
        assert len(eng.generate(TOK.encode("y"), max_new_tokens=4,
                                sampler=GREEDY).token_ids) == 4
    finally:
        em.CONSTRAIN_ARENA_MAX = old
        eng.shutdown()


def test_constrained_logprobs_are_json_safe():
    """Masked alternatives must never surface as -Infinity in the wire
    body (RFC 8259 has no Infinity literal): a near-sink grammar state
    allows fewer tokens than top_logprobs, and the response must still be
    strict-JSON round-trippable with finite logprobs throughout."""
    import asyncio
    import math

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(BackendSpec(
        name="lp", url="tpu://llama-tiny?seed=4", model="m"))
    res = asyncio.run(b.complete(
        {"model": "m", "messages": [{"role": "user", "content": "go"}],
         "max_tokens": 8, "temperature": 0.7, "seed": 3,
         "logprobs": True, "top_logprobs": 5,
         "response_format": {"type": "regex", "pattern": "yes|no"}},
        {}, 60))
    body = json.dumps(res.body, allow_nan=False)  # raises on inf/nan
    content = res.body["choices"][0]
    assert content["message"]["content"] in ("yes", "no")
    for e in content["logprobs"]["content"]:
        assert math.isfinite(e["logprob"])
        for t in e["top_logprobs"]:
            assert math.isfinite(t["logprob"])
    assert body


def test_backend_stream_and_finish_reason_via_api():
    """Backend-level wire contract: streaming a json_schema request at
    K=4 yields deltas whose concatenation parses and validates, with
    finish_reason "stop" (grammar completion forces EOS)."""
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    b = TpuBackend.from_spec(BackendSpec(
        name="con", url="tpu://llama-tiny?seed=3&decode_pipeline=4",
        model="m"))
    base = {"model": "m", "messages": [{"role": "user", "content": "go"}],
            "max_tokens": 64, "temperature": 0.8, "seed": 21,
            "response_format": {"type": "json_schema",
                                "json_schema": {"schema": SCHEMA}}}

    async def collect():
        finish, parts = None, []
        async for ch in b.stream(dict(base), {}, 60):
            for choice in ch.get("choices", []):
                parts.append(choice.get("delta", {}).get("content") or "")
                if choice.get("finish_reason"):
                    finish = choice["finish_reason"]
        return "".join(parts), finish

    text, finish = asyncio.run(collect())
    obj = json.loads(text)
    assert isinstance(obj["ok"], bool) and obj["dir"] in "NSEW"
    assert finish == "stop"

    # non-streaming parity + json_object mode
    r = asyncio.run(b.complete(
        {**base, "response_format": {"type": "json_object"}}, {}, 60))
    body = r.body["choices"][0]
    assert isinstance(json.loads(body["message"]["content"]), dict)
    assert body["finish_reason"] == "stop"
