"""Golden wire-fixture contract tests (VERDICT r2 missing item 2).

The reference anchors its compatibility on the vendored OpenAI OpenAPI spec
(/root/reference/api_reference/chat_completions.yaml). quorum_tpu's
machine-readable equivalent is tests/fixtures/*.json: each fixture pins a
request and the exact response / SSE-transcript *shape* it must produce —
key sets match exactly; `<STR>`/`<INT>`/`<NUM>`/`<ANY>`/`<RE:...>`
placeholders stand for variable values; a `{"<repeat>": frame, "min": n}`
list element matches n-or-more consecutive frames.

Fixtures run against the real ASGI app with real tpu:// (llama-tiny) engines
on the CPU backend — the full serving path, not mocks.
"""

import json
import re
from pathlib import Path

import pytest

from tests.conftest import make_client

FIXTURES = Path(__file__).parent / "fixtures"


def match(expected, actual, path="$"):
    """Assert `actual` matches the fixture shape `expected`."""
    if isinstance(expected, str) and expected.startswith("<") and expected.endswith(">"):
        tag = expected[1:-1]
        if tag == "ANY":
            return
        if tag == "STR":
            assert isinstance(actual, str), f"{path}: want str, got {actual!r}"
            return
        if tag == "INT":
            assert isinstance(actual, int) and not isinstance(actual, bool), (
                f"{path}: want int, got {actual!r}")
            return
        if tag == "NUM":
            assert isinstance(actual, (int, float)) and not isinstance(actual, bool), (
                f"{path}: want number, got {actual!r}")
            return
        if tag.startswith("RE:"):
            assert isinstance(actual, str) and re.fullmatch(tag[3:], actual), (
                f"{path}: {actual!r} !~ /{tag[3:]}/")
            return
        raise ValueError(f"unknown placeholder {expected}")
    if isinstance(expected, dict):
        assert isinstance(actual, dict), f"{path}: want object, got {actual!r}"
        assert set(expected) == set(actual), (
            f"{path}: key mismatch — fixture {sorted(expected)} vs "
            f"actual {sorted(actual)}")
        for k in expected:
            match(expected[k], actual[k], f"{path}.{k}")
        return
    if isinstance(expected, list):
        match_frames(expected, actual, path)
        return
    assert expected == actual, f"{path}: {actual!r} != {expected!r}"


def match_frames(expected_seq, actual_seq, path="$"):
    """Sequence matcher: literal elements match one item; a
    {"<repeat>": shape, "min"/"count": n} element greedily consumes
    consecutive matching items."""
    assert isinstance(actual_seq, list), f"{path}: want array, got {actual_seq!r}"
    ai = 0
    for ei, exp in enumerate(expected_seq):
        if isinstance(exp, dict) and "<repeat>" in exp:
            shape = exp["<repeat>"]
            need = exp.get("count", exp.get("min", 1))
            exact = "count" in exp
            taken = 0
            while ai < len(actual_seq):
                try:
                    match(shape, actual_seq[ai], f"{path}[{ai}]")
                except AssertionError:
                    break
                ai += 1
                taken += 1
                if exact and taken == need:
                    break
            assert taken >= need, (
                f"{path}: repeat group {ei} matched {taken} < {need} frames "
                f"(next actual: {actual_seq[ai] if ai < len(actual_seq) else '<end>'})")
        else:
            assert ai < len(actual_seq), f"{path}: ran out of frames at {ei}"
            match(exp, actual_seq[ai], f"{path}[{ai}]")
            ai += 1
    assert ai == len(actual_seq), (
        f"{path}: {len(actual_seq) - ai} unexpected trailing frames: "
        f"{actual_seq[ai:]}")


def single_backend_config():
    return {
        "settings": {"timeout": 300},
        "primary_backends": [
            {"name": "LLM1", "url": "tpu://llama-tiny?seed=1", "model": "tiny"},
        ],
    }


def parallel_config():
    return {
        "settings": {"timeout": 300},
        "primary_backends": [
            {"name": "LLM1", "url": "tpu://llama-tiny?seed=1", "model": "tiny"},
            {"name": "LLM2", "url": "tpu://llama-tiny?seed=2", "model": "tiny"},
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {
            "concatenate": {"separator": "\n---\n",
                            "hide_intermediate_think": True,
                            "hide_final_think": False,
                            "thinking_tags": ["think"]},
            "aggregate": {"source_backends": "all", "aggregator_backend": ""},
        },
    }


def load(name):
    return json.loads((FIXTURES / name).read_text())


async def post(config, fixture):
    async with make_client(config) as client:
        return await client.post(
            "/v1/chat/completions", json=fixture["request"],
            headers={"Authorization": "Bearer fixture"},
        )


@pytest.mark.parametrize("name", [
    "nonstream_single.json",
    "nonstream_n_logprobs.json",
    "reject_tools.json",
])
async def test_nonstream_fixture(name):
    fx = load(name)
    resp = await post(single_backend_config(), fx)
    assert resp.status_code == fx["status"], resp.text
    match(fx["response"], resp.json())


@pytest.mark.parametrize("name,config", [
    ("stream_single.json", single_backend_config()),
    ("stream_include_usage.json", single_backend_config()),
    ("stream_parallel_concatenate.json", parallel_config()),
])
async def test_stream_fixture(name, config):
    fx = load(name)
    async with make_client(config) as client:
        resp = await client.post(
            "/v1/chat/completions", json=fx["request"],
            headers={"Authorization": "Bearer fixture"},
        )
        assert resp.status_code == fx["status"]
        lines = [ln for ln in resp.text.splitlines() if ln.startswith("data: ")]
    assert fx["done_sentinel"] and lines[-1] == "data: [DONE]"
    frames = [json.loads(ln[len("data: "):]) for ln in lines[:-1]]
    match_frames(fx["frames"], frames)
