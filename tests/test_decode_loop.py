"""Megachunk decode-loop gating and clamping (fast tier).

The cache-key pin (same gating pattern as the PR 5 unconstrained pin): a
``decode_loop=1`` engine must compile ONLY the pre-existing "plain"
program family — never a "loop"-tagged one — so unfused users pay zero
recompiles for this feature existing. The key shapes themselves are pinned
once, in ``quorum_tpu/analysis/compile_budget.json``; these tests assert
FAMILIES via quorum_tpu.analysis.budget (classification raises on any
unknown or shape-drifted key), keeping one literal end-to-end sentinel.

The effective-C clamp unit tests pin the scheduler-side safety rails:
admission pressure → 1 (an admission must not wait C chunks), short
remaining budgets → the smallest power-of-two cover, and a tight in-flight
deadline → halved until one dispatch fits inside it (the PR 4
DEADLINE_SLACK_S backstop must never fire because a dispatch legitimately
covered C chunks).
"""

import time

import pytest

from quorum_tpu.analysis import budget
from quorum_tpu.engine.engine import MAX_DECODE_LOOP, InferenceEngine
from quorum_tpu.models.model_config import MODEL_PRESETS
from quorum_tpu.ops.sampling import SamplerConfig

TINY = MODEL_PRESETS["llama-tiny"]
GREEDY = SamplerConfig(temperature=0.0)


class _Row:
    """The slice of _Request the clamp reads."""

    def __init__(self, budget=100, emitted=0, deadline=None):
        self.budget = budget
        self.emitted = emitted
        self.deadline = deadline


def test_decode_loop_1_pins_the_unfused_program_keys():
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=2,
                          decode_loop=1)
    try:
        eng.generate([5, 6, 7], max_new_tokens=12, sampler=GREEDY)
        keys = set(eng._decode_cache)
        assert keys, "the generation must have compiled decode programs"
        assert budget.decode_families(keys) == {"plain"}, (
            f"decode_loop=1 must compile only the plain family, got {keys}")
    finally:
        eng.shutdown()


def test_decode_loop_4_uses_tagged_keys_only_for_fused_dispatches():
    eng = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1,
                          decode_loop=4)
    try:
        eng.generate([5, 6, 7], max_new_tokens=16, sampler=GREEDY)
        fams = budget.decode_families(eng._decode_cache)
        assert "loop" in fams, "a 4-chunk generation must fuse"
        assert "loop_dfa" not in fams  # no grammar rows in this batch
        # the one literal end-to-end sentinel this file keeps: the fused
        # key carries n_chunks=4 right after its tag
        loop_keys = {k for k in eng._decode_cache if k[0] == "loop"}
        assert all(k[1] == 4 for k in loop_keys)
    finally:
        eng.shutdown()


def test_decode_loop_range_validated():
    with pytest.raises(ValueError):
        InferenceEngine(TINY, decode_loop=0)
    with pytest.raises(ValueError):
        InferenceEngine(TINY, decode_loop=MAX_DECODE_LOOP + 1)


def test_decode_loop_floored_to_power_of_two():
    """A non-pow2 C would double the fused program-shape families (the
    per-dispatch clamps halve); the engine floors it at construction."""
    eng = InferenceEngine(TINY, decode_loop=6)
    try:
        assert eng.decode_loop == 4
    finally:
        eng.shutdown()


def test_url_knobs_validated_at_config_time():
    """A typo in decode_loop=/flash_decode= must fail the URL before any
    multi-GB engine construction, not per-request."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    for url in ("tpu://llama-tiny?decode_loop=0",
                "tpu://llama-tiny?decode_loop=9999",
                "tpu://llama-tiny?flash_decode=maybe"):
        with pytest.raises(ValueError):
            TpuBackend.from_spec(BackendSpec(name="bad", url=url, model="m"))


class TestEffectiveLoopClamp:
    @pytest.fixture()
    def eng(self):
        e = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1,
                            decode_loop=8)
        yield e
        e.shutdown()

    def test_full_fusion_when_unpressured(self, eng):
        active = [(0, _Row(budget=100))]
        assert eng._effective_loop(active, 4, 0) == 8

    def test_budget_clamps_to_pow2_cover(self, eng):
        # 10 tokens left at chunk 4 → 3 chunks → pow2 cover 4, not 8
        active = [(0, _Row(budget=10))]
        assert eng._effective_loop(active, 4, 0) == 4
        # tokens already in flight count against the remaining budget
        assert eng._effective_loop(active, 4, 8) == 1

    def test_admission_pressure_disables_fusion(self, eng):
        active = [(0, _Row(budget=100))]
        req = eng.submit([1, 2, 3], max_new_tokens=4, sampler=GREEDY)
        try:
            with eng._cond:
                pressured = eng._admission_pressure()
            # the scheduler may have admitted it already; only a still-
            # pending request exerts pressure
            if pressured:
                assert eng._effective_loop(active, 4, 0) == 1
        finally:
            list(eng.stream_results(req))

    def test_queued_request_deadline_clamps_too(self, eng, monkeypatch):
        """A queued request with no free slot exerts no admission
        pressure, but its deadline sweep runs only between dispatches —
        its deadline must clamp C exactly like an active row's."""
        class _Pending:
            deadline = time.monotonic() + 0.25
        eng._chunk_ewma_s = 0.1
        monkeypatch.setattr(eng, "_admission_pressure", lambda: False)
        with eng._cond:
            eng._pending.append(_Pending())
        try:
            active = [(0, _Row(budget=100))]  # no deadline of its own
            assert eng._effective_loop(active, 4, 0) <= 2
        finally:
            with eng._cond:
                eng._pending.clear()

    def test_deadline_clamps_the_dispatch_length(self, eng):
        eng._chunk_ewma_s = 0.1  # 100 ms per chunk, estimated
        tight = time.monotonic() + 0.25  # fits 2 chunks, not 8
        active = [(0, _Row(budget=100, deadline=tight))]
        assert eng._effective_loop(active, 4, 0) <= 2
        # an already-blown deadline degrades to single-chunk dispatch
        late = [(0, _Row(budget=100, deadline=time.monotonic() - 1))]
        assert eng._effective_loop(late, 4, 0) == 1
        # no latency estimate yet → no clamp (first dispatch measures)
        eng._chunk_ewma_s = 0.0
        assert eng._effective_loop(active, 4, 0) == 8
