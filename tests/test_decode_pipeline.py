"""Depth-K decode-dispatch pipeline: K>1 must be token-for-token identical
to K=1 across every finish mode, with on-device finish accounting keeping
overrun at zero for EOS/budget finishes (ISSUE: deep decode-dispatch
pipeline).

The K=1 engine is the oracle: same programs, ring capped at one chunk (the
host blocks on every dispatch). Everything here runs the tiny preset on the
CPU backend — the same compiled code paths as TPU."""

import threading

import pytest

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import MODEL_PRESETS, resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

pytestmark = pytest.mark.slow

TINY = MODEL_PRESETS["llama-tiny"]
GREEDY = SamplerConfig(temperature=0.0)


def _pair(**kw):
    """(K=1 oracle, K=4 pipelined) engines over identical weights."""
    return (InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1, **kw),
            InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4, **kw))


def test_greedy_token_for_token():
    e1, e4 = _pair()
    a = e1.generate([5, 6, 7], max_new_tokens=32, sampler=GREEDY)
    b = e4.generate([5, 6, 7], max_new_tokens=32, sampler=GREEDY)
    assert a.token_ids == b.token_ids
    assert len(b.token_ids) == 32
    assert e4.n_overrun == 0  # budget finish is detected on device


def test_sampled_token_for_token():
    e1, e4 = _pair()
    s = SamplerConfig(temperature=0.9, top_p=0.95)
    for seed in (7, 42):
        a = e1.generate([5, 6, 7], max_new_tokens=24, sampler=s, seed=seed)
        b = e4.generate([5, 6, 7], max_new_tokens=24, sampler=s, seed=seed)
        assert a.token_ids == b.token_ids, f"seed {seed} diverged"
    assert e4.n_overrun == 0


def test_eos_mid_chunk_token_for_token():
    """EOS landing mid-chunk with 3 further chunks in flight: the row stops
    on device — identical output, zero overrun, no K extra chunks of
    garbage."""
    e1, e4 = _pair()
    probe = e1.generate([9, 8], max_new_tokens=32, sampler=GREEDY)
    eos = probe.token_ids[9]  # stop at a position inside chunk 3
    a = e1.generate([9, 8], max_new_tokens=32, sampler=GREEDY, eos_id=eos)
    b = e4.generate([9, 8], max_new_tokens=32, sampler=GREEDY, eos_id=eos)
    assert a.token_ids == b.token_ids
    assert a.finish_reason == b.finish_reason == "stop"
    assert e4.n_overrun == 0


def test_stop_sequence_parity_via_backend():
    """Host-side stop-string hits cancel the row by masking it out of
    not-yet-dispatched chunks; the delivered text must match K=1 exactly
    (the discarded in-flight tail is overrun, not output)."""
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec
    from quorum_tpu.engine.engine import release_engine

    def backend(k):
        return TpuBackend.from_spec(BackendSpec(
            name=f"p{k}",
            url=f"tpu://llama-tiny?seed=5&decode_pipeline={k}", model="m"))

    b1 = backend(1)
    base = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 24, "temperature": 0.0}
    probe = asyncio.run(b1.complete(base, {}, 60))
    text = probe.body["choices"][0]["message"]["content"]
    stop = text[3:5] if len(text) >= 5 else text[-1]
    r1 = asyncio.run(b1.complete({**base, "stop": [stop]}, {}, 60))
    # get_engine keys engines on weight identity only (decode_pipeline is
    # structural, first-construction-wins), so b4 built now would silently
    # reuse b1's K=1 engine: evict it first — the same seed rebuilds
    # identical weights on a genuinely depth-4 ring.
    release_engine(b1.engine)
    b4 = backend(4)
    assert b4.engine.decode_pipeline == 4
    r4 = asyncio.run(b4.complete({**base, "stop": [stop]}, {}, 60))
    c1 = r1.body["choices"][0]
    c4 = r4.body["choices"][0]
    assert c4["message"]["content"] == c1["message"]["content"]
    assert c4["finish_reason"] == c1["finish_reason"]


def test_cancel_does_not_corrupt_later_requests():
    """Abandoning a stream mid-generation (cancel at a chunk boundary with
    chunks in flight) must leave the engine producing exactly the K=1
    stream for the next request."""
    e1, e4 = _pair()
    cancel = threading.Event()
    it = e4.generate_stream([5, 6, 7], max_new_tokens=40, sampler=GREEDY,
                            cancel=cancel)
    for _, tok in zip(range(5), it):
        pass
    it.close()  # abandons the iterator -> cancel fires, slot drains
    after1 = e1.generate([3, 4], max_new_tokens=16, sampler=GREEDY)
    after4 = e4.generate([3, 4], max_new_tokens=16, sampler=GREEDY)
    assert after4.token_ids == after1.token_ids


def test_admission_pressure_drains_and_matches():
    """More requests than slots at K=4: the ring must shrink for waiting
    admissions (no K-chunk admission delay) and every stream must still be
    its K=1 self."""
    spec = resolve_spec("llama-tiny", {})
    e1 = InferenceEngine(spec, decode_chunk=4, decode_pipeline=1, n_slots=2)
    e4 = InferenceEngine(spec, decode_chunk=4, decode_pipeline=4, n_slots=2)
    prompts = [[5, 6, 7], [9, 8], [3, 4, 5], [11, 12]]

    def run_all(eng):
        reqs = [eng.submit(p, max_new_tokens=12, sampler=GREEDY, seed=0)
                for p in prompts]
        return [list(eng.stream_results(r)) for r in reqs]

    assert run_all(e4) == run_all(e1)


def test_spec_verify_turns_drain_the_ring():
    """Speculative verification (host-synchronous turns) interleaved with
    pipelined chunks: output parity holds, and the repetitive prompt still
    finishes in fewer dispatches than tokens (speculation engaged)."""
    e1 = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1,
                         spec_decode=4)
    e4 = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=4,
                         spec_decode=4)
    prompt = [7, 8, 7, 8, 7, 8, 7, 8]
    a = e1.generate(list(prompt), max_new_tokens=24, sampler=GREEDY)
    b = e4.generate(list(prompt), max_new_tokens=24, sampler=GREEDY)
    assert a.token_ids == b.token_ids


def test_dispatch_accounting_counters():
    """The acceptance counters: a >=8-chunk generation at K=4 must block
    the host on strictly fewer dispatches than K=1 (n_decode_chunks -
    overlapped_chunks_total), with zero overrun when the row finishes on
    device."""
    e1, e4 = _pair()
    e1.generate([5, 6, 7], max_new_tokens=40, sampler=GREEDY)  # 10 chunks
    e4.generate([5, 6, 7], max_new_tokens=40, sampler=GREEDY)
    m1, m4 = e1.metrics(), e4.metrics()
    assert m1["decode_chunks_total"] >= 8
    syncs1 = m1["decode_chunks_total"] - m1["overlapped_chunks_total"]
    syncs4 = m4["decode_chunks_total"] - m4["overlapped_chunks_total"]
    assert m1["overlapped_chunks_total"] == 0  # K=1 never dispatches ahead
    assert syncs4 < syncs1
    assert m4["overrun_tokens_total"] == 0
    assert m4["decode_pipeline"] == 4 and m1["decode_pipeline"] == 1


# ---- megachunk decode loop (decode_loop=C, ISSUE 6) ------------------------
#
# decode_loop=C fuses up to C chunk bodies into ONE dispatch
# (transformer.decode_loop). The C=1 engine is the oracle at BOTH ring
# depths: the fused program replays the identical per-chunk body, so every
# leg must be token-for-token.

def _loop_pair(k: int, **kw):
    """(decode_loop=1 oracle, decode_loop=4 megachunk) engines at ring
    depth ``k`` over identical weights."""
    return (InferenceEngine(TINY, decode_chunk=4, decode_pipeline=k,
                            decode_loop=1, **kw),
            InferenceEngine(TINY, decode_chunk=4, decode_pipeline=k,
                            decode_loop=4, **kw))


@pytest.mark.parametrize("k", [1, 4])
def test_loop_greedy_and_sampled_token_for_token(k):
    e1, e4 = _loop_pair(k)
    a = e1.generate([5, 6, 7], max_new_tokens=32, sampler=GREEDY)
    b = e4.generate([5, 6, 7], max_new_tokens=32, sampler=GREEDY)
    assert a.token_ids == b.token_ids and len(b.token_ids) == 32
    s = SamplerConfig(temperature=0.9, top_p=0.95)
    for seed in (7, 42):
        a = e1.generate([5, 6, 7], max_new_tokens=24, sampler=s, seed=seed)
        b = e4.generate([5, 6, 7], max_new_tokens=24, sampler=s, seed=seed)
        assert a.token_ids == b.token_ids, f"seed {seed} diverged at K={k}"
    assert e4.n_overrun == 0  # budget finishes stay on device under fusion


@pytest.mark.parametrize("k", [1, 4])
def test_loop_eos_mid_chunk_token_for_token(k):
    """EOS landing mid-chunk inside a megachunk: the on-device early exit
    must skip the remaining fused chunks — identical output, zero overrun,
    no C extra chunks of garbage."""
    e1, e4 = _loop_pair(k)
    probe = e1.generate([9, 8], max_new_tokens=32, sampler=GREEDY)
    eos = probe.token_ids[9]  # a position inside fused chunk 3
    a = e1.generate([9, 8], max_new_tokens=32, sampler=GREEDY, eos_id=eos)
    b = e4.generate([9, 8], max_new_tokens=32, sampler=GREEDY, eos_id=eos)
    assert a.token_ids == b.token_ids
    assert a.finish_reason == b.finish_reason == "stop"
    assert e4.n_overrun == 0


def test_loop_stop_sequence_parity_via_backend():
    """Host-side stop-string finishes under megachunks: the delivered text
    must match decode_loop=1 exactly; the already-dispatched fused tail is
    bounded overrun (≤ C−1 chunks), never output."""
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec
    from quorum_tpu.engine.engine import release_engine

    def backend(c):
        return TpuBackend.from_spec(BackendSpec(
            name=f"l{c}",
            url=f"tpu://llama-tiny?seed=5&decode_pipeline=4&decode_loop={c}",
            model="m"))

    b1 = backend(1)
    base = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 24, "temperature": 0.0}
    probe = asyncio.run(b1.complete(base, {}, 60))
    text = probe.body["choices"][0]["message"]["content"]
    stop = text[3:5] if len(text) >= 5 else text[-1]
    r1 = asyncio.run(b1.complete({**base, "stop": [stop]}, {}, 60))
    # decode_loop is structural (first-construction-wins on the shared
    # engine): evict the C=1 engine so the C=4 URL really builds one.
    release_engine(b1.engine)
    b4 = backend(4)
    assert b4.engine.decode_loop == 4
    r4 = asyncio.run(b4.complete({**base, "stop": [stop]}, {}, 60))
    c1, c4 = r1.body["choices"][0], r4.body["choices"][0]
    assert c4["message"]["content"] == c1["message"]["content"]
    assert c4["finish_reason"] == c1["finish_reason"]


def test_loop_cancel_does_not_corrupt_later_requests():
    """Abandoning a stream mid-megachunk: the wasted fused tail is
    bounded (counted as overrun), and the engine must produce exactly the
    decode_loop=1 stream for the next request."""
    e1, e4 = _loop_pair(4)
    cancel = threading.Event()
    it = e4.generate_stream([5, 6, 7], max_new_tokens=40, sampler=GREEDY,
                            cancel=cancel)
    for _, tok in zip(range(5), it):
        pass
    it.close()  # abandons the iterator -> cancel fires, slot drains
    after1 = e1.generate([3, 4], max_new_tokens=16, sampler=GREEDY)
    after4 = e4.generate([3, 4], max_new_tokens=16, sampler=GREEDY)
    assert after4.token_ids == after1.token_ids


@pytest.mark.parametrize("k", [1, 4])
def test_loop_constrained_token_for_token(k):
    """A schema-constrained stream under megachunks: the DFA state rides
    the fused carry (zero extra host syncs), and the stream equals the
    decode_loop=1 constrained stream token for token."""
    import json as _json

    from quorum_tpu.constrain import compile_response_format
    from quorum_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer(TINY.vocab_size)
    schema = {"type": "object", "properties": {
        "ok": {"type": "boolean"}, "n": {"type": "integer"}}}
    rf = {"type": "json_schema", "json_schema": {"schema": schema}}
    e1, e4 = _loop_pair(k)

    def run(eng):
        g = compile_response_format(rf, tok, TINY.vocab_size)
        req = eng.submit(tok.encode("go"), max_new_tokens=64,
                         sampler=SamplerConfig(temperature=0.8), seed=11,
                         eos_id=tok.eos_id, grammar=g)
        return list(eng.stream_results(req))

    a, b = run(e1), run(e4)
    assert a == b
    body = tok.decode([t for t in b if t != tok.eos_id])
    obj = _json.loads(body)
    assert isinstance(obj, dict)
    assert e4.n_overrun == 0


def test_loop_members_token_for_token():
    """Stacked members under megachunks: every member's stream equals its
    decode_loop=1 self (the fused loop advances all members per chunk
    body, exactly as the unfused dispatch did)."""
    e1 = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1,
                         decode_loop=1, members=2)
    e4 = InferenceEngine(TINY, decode_chunk=4, decode_pipeline=1,
                         decode_loop=4, members=2)
    for m in (0, 1):
        a = e1.generate([5, 6, 7], max_new_tokens=16, sampler=GREEDY,
                        member=m)
        b = e4.generate([5, 6, 7], max_new_tokens=16, sampler=GREEDY,
                        member=m)
        assert a.token_ids == b.token_ids, f"member {m} diverged"


def test_loop_dispatch_counter_acceptance():
    """The ISSUE acceptance: dispatches per 64-token request drop ~C× at
    decode_loop=C (64 tokens / chunk 4 = 16 chunks → ≤ 5 dispatches at
    C=4 vs 16 unfused), chunk-segment accounting stays exact, and blocking
    syncs stay ≤ the unfused count."""
    e1, e4 = _loop_pair(2)
    e1.generate([5, 6, 7], max_new_tokens=64, sampler=GREEDY)
    e4.generate([5, 6, 7], max_new_tokens=64, sampler=GREEDY)
    m1, m4 = e1.metrics(), e4.metrics()
    assert m1["decode_chunks_total"] >= 16
    assert m4["decode_chunks_total"] <= m1["decode_chunks_total"] // 3
    # every fused dispatch's segments are accounted: 16 chunks either way
    assert m4["decode_loop_chunks_total"] == m1["decode_loop_chunks_total"]
    assert m4["decode_loop"] == 4 and m1["decode_loop"] == 1
    assert m4["overrun_tokens_total"] == 0
