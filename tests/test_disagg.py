"""Disaggregated prefill/decode serving (``disagg=P+D``, ISSUE 8).

Fast tier: knob parsing/validation, the colocated cache-key pin (disagg
off compiles the exact pre-existing program variants and runs ONE
scheduler loop), and a 1+1-group smoke on the virtual CPU mesh — output
pinned token-for-token against the colocated engine with a live
device→device KV handoff, plus the ``engine.kv_handoff`` fault site's
containment contract (a failed handoff dooms only its own request and
requeues nothing else).

Slow tier: the full acceptance pin at ``disagg=4+4`` on the 8-device mesh
with ``decode_pipeline=4 × decode_loop=4`` across the
greedy / sampled / EOS-mid-chunk / constrained / members / prefix-restore
legs, each against a colocated mesh engine.
"""

import asyncio

import pytest

from quorum_tpu import faults
from quorum_tpu.analysis import budget
from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig
from quorum_tpu.parallel.mesh import (
    MeshConfig,
    disagg_meshes,
    make_mesh,
    parse_disagg,
)

TINY = resolve_spec("llama-tiny", {"n_kv_heads": "4"})
SAMPLED = SamplerConfig(temperature=0.8, top_p=0.9)
GREEDY = SamplerConfig(temperature=0.0)


def _gen(eng, prompt, seed=0, n=8, sampler=SAMPLED, **kw):
    return eng.generate(prompt, max_new_tokens=n, sampler=sampler,
                        seed=seed, **kw).token_ids


# ---- fast: parsing + config validation -------------------------------------


def test_parse_disagg():
    assert parse_disagg("4+4") == (4, 4)
    assert parse_disagg("1+7") == (1, 7)
    assert parse_disagg("2 2") == (2, 2)  # URL-decoded '+' arrives as space
    for bad in ("", "4", "4x4", "0+4", "4+0", "-1+2", "a+b"):
        with pytest.raises(ValueError):
            parse_disagg(bad)


def test_disagg_mesh_and_engine_validation():
    with pytest.raises(ValueError, match="devices"):
        disagg_meshes(9, 9)
    pm, dm = disagg_meshes(1, 1)
    # groups must be disjoint
    with pytest.raises(ValueError, match="disjoint"):
        InferenceEngine(TINY, pm, prefill_mesh=pm)
    # disagg rides chunked prefill; an engine without it must reject
    with pytest.raises(ValueError, match="chunked prefill"):
        InferenceEngine(TINY, dm, prefill_mesh=pm, prefill_chunk=0)


def test_disagg_url_knob_validation():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(
            BackendSpec(name="t", url=url, model="m"))

    for url, frag in [
        ("tpu://llama-tiny?disagg=4x4", "invalid disagg"),
        # tp= composes with disagg now (the per-group factorization), but
        # a non-factoring tp still rejects at config with the arithmetic
        ("tpu://llama-tiny?disagg=1+1&tp=2", "does not factor"),
        ("tpu://llama-tiny?disagg=1+1&dp=2", "dp= does not compose"),
        ("tpu://llama-tiny?disagg=1+1&prefill_chunk=0", "chunked prefill"),
        ("tpu://llama-tiny?disagg=9+9", "devices"),
        ("tpu://llama-tiny?disagg=1+1&spec_model=llama-tiny", "draft"),
    ]:
        with pytest.raises(ValueError, match=frag.replace("/", ".")):
            build(url)


# ---- fast: colocated cache-key pin + 1+1 smoke -----------------------------


@pytest.fixture(scope="module")
def smoke_engines():
    """One colocated + one disagg=1+1 engine over identical knobs, shared
    by the fast smoke tests (compiles once per module)."""
    pm, dm = disagg_meshes(1, 1)
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=2,
              prefill_chunk=16, seed=9300)
    eng_c = InferenceEngine(TINY, **kw)
    eng_d = InferenceEngine(TINY, dm, prefill_mesh=pm, **kw)
    yield eng_c, eng_d
    eng_c.shutdown()
    eng_d.shutdown()


def test_colocated_compiles_exact_preexisting_variants(smoke_engines):
    """disagg off = byte-for-byte the old engine: one scheduler loop, no
    prefill-group state, no handoff program variants, single-shot
    admission for short prompts, and the unconstrained decode programs
    under their exact pre-existing 3-tuple keys."""
    eng_c, _ = smoke_engines
    _gen(eng_c, [3, 4, 5], seed=1)
    assert eng_c._prefill_thread is None
    assert eng_c.prefill_params is None
    assert not eng_c.disagg
    # program families against the shared budget (classifying also pins
    # each key's exact shape — analysis/compile_budget.json)
    assert budget.admit_families(eng_c._admit_cache) == {"single_shot"}
    assert budget.decode_families(eng_c._decode_cache) == {"plain"}
    # one end-to-end literal sentinel: the plain decode key is still the
    # pre-existing (n_steps, want_lp, history) 3-tuple
    assert any(isinstance(k, tuple) and len(k) == 3
               and isinstance(k[0], int) for k in eng_c._decode_cache)
    assert eng_c.n_kv_handoffs == 0 and eng_c.kv_handoff_bytes == 0


def test_disagg_smoke_pinned_with_live_handoff(smoke_engines):
    """1+1 groups: greedy and sampled streams (short AND multi-segment
    prompts) equal the colocated engine token for token, with nonzero KV
    handoff bytes/seconds crossing the group boundary."""
    eng_c, eng_d = smoke_engines
    long_p = [(3 + 5 * i) % 500 for i in range(40)]
    legs = [([3, 4, 5], GREEDY, 0), ([7, 8, 9], SAMPLED, 11),
            (long_p, SAMPLED, 3)]
    for prompt, sampler, seed in legs:
        assert (_gen(eng_d, prompt, seed=seed, sampler=sampler)
                == _gen(eng_c, prompt, seed=seed, sampler=sampler))
    assert eng_d.n_kv_handoffs >= len(legs)
    assert eng_d.kv_handoff_bytes > 0
    assert eng_d.kv_handoff_s > 0.0
    m = eng_d.metrics()
    assert m["disagg"] == 1 and m["kv_handoff_bytes_total"] > 0
    assert m["prefill_group_devices"] == 1
    assert m["decode_group_devices"] == 1
    # never a single-shot admit program on the disagg engine; every
    # admission rides seg+handoff+register (compile_budget.json gates)
    fams = budget.admit_families(eng_d._admit_cache)
    assert "single_shot" not in fams
    assert {"seg", "register", "hslice", "hput"} <= fams, fams
    # group-aware health: both loops alive
    h = eng_d.health()
    assert h["scheduler_alive"] and h["prefill_scheduler_alive"]


def test_kv_handoff_fault_dooms_only_its_request(smoke_engines):
    """The ``engine.kv_handoff`` fault site's containment: the failed
    handoff's own request errors; a queued bystander completes unchanged
    (nothing requeued, no rebuild), and the next request matches the
    fault-free baseline."""
    eng_c, eng_d = smoke_engines
    base = _gen(eng_d, [3, 4, 5], seed=1)
    assert base == _gen(eng_c, [3, 4, 5], seed=1)
    rebuilds0 = eng_d.n_rebuilds
    faults.arm("engine.kv_handoff", times=1)
    try:
        bad = eng_d.submit([5, 6, 7], max_new_tokens=8, sampler=SAMPLED,
                           seed=2)
        bystander = eng_d.submit([3, 4, 5], max_new_tokens=8,
                                 sampler=SAMPLED, seed=1)
        with pytest.raises(faults.FaultInjected):
            list(eng_d.stream_results(bad))
        assert list(eng_d.stream_results(bystander)) == base
    finally:
        faults.disarm()
    assert _gen(eng_d, [3, 4, 5], seed=1) == base
    assert eng_d.n_rebuilds == rebuilds0  # staging survived: no rebuild
    assert eng_d.health()["prefill_scheduler_alive"]


def test_disagg_no_knob_cache_keys_unchanged(smoke_engines):
    """The no-sharding-knob disagg path keeps its exact pre-existing
    program cache keys, byte for byte (ISSUE 14 acceptance): plain
    3-tuple decode keys — never a "pp"-tagged staged variant — and only
    the pre-existing admit-cache tags."""
    eng_c, eng_d = smoke_engines
    _gen(eng_d, [3, 4, 5], seed=1)
    assert eng_d.decode_pp == 1 and eng_d.prefill_sp == 1
    for k in eng_d._decode_cache:
        assert isinstance(k, tuple) and len(k) == 3, k
        assert (isinstance(k[0], int) and isinstance(k[1], bool)
                and isinstance(k[2], int)), k
    allowed_tags = {"seg", "register", "hslice", "hput"}
    for k in eng_d._admit_cache:
        tag = k if isinstance(k, str) else k[0]
        assert tag in allowed_tags, k


# ---- slow: the 4+4 acceptance legs at K=4·C=4 ------------------------------


@pytest.fixture(scope="module")
def accept_engines():
    """disagg=4+4 vs a colocated tp=4 mesh engine, both at
    decode_pipeline=4 × decode_loop=4 (the deep-fused acceptance shape)."""
    pm, dm = disagg_meshes(4, 4)
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=4, decode_loop=4,
              prefill_chunk=16, seed=9310)
    eng_c = InferenceEngine(TINY, make_mesh(MeshConfig(tp=4)), **kw)
    eng_d = InferenceEngine(TINY, dm, prefill_mesh=pm, **kw)
    yield eng_c, eng_d
    eng_c.shutdown()
    eng_d.shutdown()


@pytest.mark.slow
def test_disagg_4p4_greedy_sampled_chunked_pin(accept_engines):
    eng_c, eng_d = accept_engines
    long_p = [(3 + 5 * i) % 500 for i in range(40)]
    for prompt, sampler, seed in [([3, 4, 5], GREEDY, 0),
                                  ([7, 8, 9], SAMPLED, 11),
                                  (long_p, SAMPLED, 3)]:
        assert (_gen(eng_d, prompt, seed=seed, n=12, sampler=sampler)
                == _gen(eng_c, prompt, seed=seed, n=12, sampler=sampler))
    assert eng_d.n_kv_handoffs > 0 and eng_d.kv_handoff_bytes > 0


@pytest.mark.slow
def test_disagg_4p4_eos_mid_chunk_pin(accept_engines):
    """A row finishing ON DEVICE mid-megachunk (EOS at a non-boundary
    position) retires identically on both engines — finish_reason stop,
    zero overrun."""
    eng_c, eng_d = accept_engines
    probe = _gen(eng_c, [5, 6, 7], seed=2, n=12)
    eos = next((t for i, t in enumerate(probe)
                if i >= 4 and i % 4 != 3 and t not in probe[:i]), None)
    assert eos is not None, probe
    over0 = eng_d.n_overrun
    r_d = eng_d.generate([5, 6, 7], max_new_tokens=12, sampler=SAMPLED,
                         seed=2, eos_id=eos)
    r_c = eng_c.generate([5, 6, 7], max_new_tokens=12, sampler=SAMPLED,
                         seed=2, eos_id=eos)
    assert r_d.token_ids == r_c.token_ids
    assert r_d.finish_reason == r_c.finish_reason == "stop"
    assert eng_d.n_overrun == over0  # on-device finish: no overrun at K·C


@pytest.mark.slow
def test_disagg_4p4_constrained_pin():
    """response_format JSON mode through the full backend: the disagg
    engine's constrained stream (DFA state riding the fused decode carry
    on the decode group, grammar placed by the decode loop) equals the
    colocated engine's byte for byte."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(BackendSpec(name="t", url=url,
                                                model="m"))

    opts = ("n_kv_heads=4&seed=9320&decode_pipeline=4&decode_loop=4"
            "&prefill_chunk=16&decode_chunk=4&slots=2")
    b_d = build(f"tpu://llama-tiny?{opts}&disagg=4+4")
    b_c = build(f"tpu://llama-tiny?{opts}")
    body = {"model": "m", "max_tokens": 24, "temperature": 0.0, "seed": 3,
            "messages": [{"role": "user", "content": "json please"}],
            "response_format": {"type": "json_object"}}

    async def run(b):
        res = await b.complete(dict(body), {}, timeout=300)
        return res.body["choices"][0]["message"]["content"]

    assert asyncio.run(run(b_d)) == asyncio.run(run(b_c))
    assert b_d.engine.n_constrained >= 1
    assert b_d.engine.n_kv_handoffs > 0


@pytest.mark.slow
def test_disagg_members_pin():
    """members=M on disagg 2+2: each member's stream equals the members=1
    engine with that member's seed — the stacked staging cache and the
    member-aware handoff slice/write address the right rows."""
    pm, dm = disagg_meshes(2, 2)
    eng_m = InferenceEngine(TINY, dm, prefill_mesh=pm, members=2,
                            decode_chunk=4, n_slots=2, decode_pipeline=4,
                            decode_loop=4, prefill_chunk=16, seed=0)
    singles = [InferenceEngine(TINY, seed=i, decode_chunk=4, n_slots=2)
               for i in range(2)]
    try:
        want = [_gen(singles[i], [3, 4, 5], seed=9, n=6) for i in range(2)]
        got = [_gen(eng_m, [3, 4, 5], seed=9, n=6, member=i)
               for i in range(2)]
        assert got == want
        assert eng_m.n_kv_handoffs > 0
    finally:
        eng_m.shutdown()
        for e in singles:
            e.shutdown()


@pytest.mark.slow
def test_disagg_prefix_restore_pin():
    """prefix_store=host on disagg: a churn-evicted conversation's
    follow-up restores host→PREFILL-staging, rides the tail prefill at an
    offset, hands the whole prefix off to the decode slot — and still
    equals a cold colocated prefill token for token."""
    pm, dm = disagg_meshes(1, 1)
    eng_d = InferenceEngine(TINY, dm, prefill_mesh=pm, decode_chunk=4,
                            n_slots=1, prefill_chunk=16,
                            prefix_store="host", prefix_store_chunk=16,
                            seed=9330)
    eng_c = InferenceEngine(TINY, decode_chunk=4, n_slots=1,
                            prefill_chunk=16, seed=9330)
    try:
        conv = [(3 + 5 * i) % 500 for i in range(33)]
        other = [(9 + 7 * i) % 500 for i in range(33)]
        out1 = _gen(eng_d, conv, seed=4, n=6)
        eng_d.drain_prefix_store()
        _gen(eng_d, other, seed=5, n=6)  # churn the single slot
        eng_d.drain_prefix_store()
        follow = conv + out1 + [17, 19]
        assert (_gen(eng_d, follow, seed=6, n=6)
                == _gen(eng_c, follow, seed=6, n=6))
        assert eng_d.prefix_store_hits >= 1
        assert eng_d.prefix_store_tokens_restored > 0
    finally:
        eng_d.shutdown()
        eng_c.shutdown()


# ---- slow: the sharded legs — disagg=2+2&tp=2 vs colocated tp=2 ------------
#
# ISSUE 14 acceptance: per-group tensor sharding under disagg is
# token-for-token identical to the colocated tp engine at the same
# intra-group tp, across every acceptance leg — the differently-laid-out
# meshes only change WHERE bytes live (the handoff reshards on the fly,
# route="reshard"), never what gets sampled.


@pytest.fixture(scope="module")
def sharded_engines():
    """disagg=2+2&tp=2 (both groups tp-sharded) vs a colocated tp=2 mesh
    engine, both at decode_pipeline=4 × decode_loop=4."""
    pm, dm = disagg_meshes(2, 2, tp=2)
    kw = dict(decode_chunk=4, n_slots=2, decode_pipeline=4, decode_loop=4,
              prefill_chunk=16, seed=9340)
    import jax

    eng_c = InferenceEngine(TINY, make_mesh(MeshConfig(tp=2),
                                            jax.devices()[:2]), **kw)
    eng_d = InferenceEngine(TINY, dm, prefill_mesh=pm, **kw)
    yield eng_c, eng_d
    eng_c.shutdown()
    eng_d.shutdown()


@pytest.mark.slow
def test_disagg_tp_greedy_sampled_chunked_pin(sharded_engines):
    eng_c, eng_d = sharded_engines
    long_p = [(3 + 5 * i) % 500 for i in range(40)]
    for prompt, sampler, seed in [([3, 4, 5], GREEDY, 0),
                                  ([7, 8, 9], SAMPLED, 11),
                                  (long_p, SAMPLED, 3)]:
        assert (_gen(eng_d, prompt, seed=seed, n=12, sampler=sampler)
                == _gen(eng_c, prompt, seed=seed, n=12, sampler=sampler))
    assert eng_d.n_kv_handoffs > 0 and eng_d.kv_handoff_bytes > 0
    # tp-sharded staging slices cross the group boundary via the on-the-
    # fly reshard route (quorum_tpu_kv_handoff_bytes_total{route=})
    from quorum_tpu import observability as obs

    assert obs.KV_HANDOFF_BYTES.value_of(route="reshard") > 0


@pytest.mark.slow
def test_disagg_tp_eos_mid_chunk_pin(sharded_engines):
    eng_c, eng_d = sharded_engines
    probe = _gen(eng_c, [5, 6, 7], seed=2, n=12)
    eos = next((t for i, t in enumerate(probe)
                if i >= 4 and i % 4 != 3 and t not in probe[:i]), None)
    assert eos is not None, probe
    over0 = eng_d.n_overrun
    r_d = eng_d.generate([5, 6, 7], max_new_tokens=12, sampler=SAMPLED,
                         seed=2, eos_id=eos)
    r_c = eng_c.generate([5, 6, 7], max_new_tokens=12, sampler=SAMPLED,
                         seed=2, eos_id=eos)
    assert r_d.token_ids == r_c.token_ids
    assert r_d.finish_reason == r_c.finish_reason == "stop"
    assert eng_d.n_overrun == over0


@pytest.mark.slow
def test_disagg_tp_constrained_pin():
    """response_format JSON mode through the full backend at
    disagg=2+2&tp=2 vs colocated tp=2 — byte for byte."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    def build(url):
        return TpuBackend.from_spec(BackendSpec(name="t", url=url,
                                                model="m"))

    opts = ("n_kv_heads=4&seed=9350&decode_pipeline=4&decode_loop=4"
            "&prefill_chunk=16&decode_chunk=4&slots=2")
    b_d = build(f"tpu://llama-tiny?{opts}&disagg=2+2&tp=2")
    b_c = build(f"tpu://llama-tiny?{opts}&tp=2")
    body = {"model": "m", "max_tokens": 24, "temperature": 0.0, "seed": 3,
            "messages": [{"role": "user", "content": "json please"}],
            "response_format": {"type": "json_object"}}

    async def run(b):
        res = await b.complete(dict(body), {}, timeout=300)
        return res.body["choices"][0]["message"]["content"]

    assert asyncio.run(run(b_d)) == asyncio.run(run(b_c))
    assert b_d.engine.n_constrained >= 1
    assert b_d.engine.n_kv_handoffs > 0


@pytest.mark.slow
def test_disagg_tp_members_pin():
    """members=2 on disagg=2+2&tp=2: each member's stream equals the
    colocated tp=2 members engine's — the stacked tp-sharded staging
    cache and the member-aware handoff address the right rows."""
    import jax

    pm, dm = disagg_meshes(2, 2, tp=2)
    kw = dict(members=2, decode_chunk=4, n_slots=2, decode_pipeline=4,
              decode_loop=4, prefill_chunk=16, seed=0)
    eng_d = InferenceEngine(TINY, dm, prefill_mesh=pm, **kw)
    eng_c = InferenceEngine(TINY, make_mesh(MeshConfig(tp=2),
                                            jax.devices()[:2]), **kw)
    try:
        for m in range(2):
            assert (_gen(eng_d, [3, 4, 5], seed=9, n=6, member=m)
                    == _gen(eng_c, [3, 4, 5], seed=9, n=6, member=m))
        assert eng_d.n_kv_handoffs > 0
    finally:
        eng_d.shutdown()
        eng_c.shutdown()


@pytest.mark.slow
def test_disagg_tp_prefix_restore_pin():
    """prefix_store=host on disagg=2+2&tp=2: the churn-evicted
    conversation's follow-up restores host→(tp-sharded) staging, rides
    the tail prefill, reshards across the handoff — and still equals a
    cold colocated tp=2 prefill token for token."""
    import jax

    pm, dm = disagg_meshes(2, 2, tp=2)
    eng_d = InferenceEngine(TINY, dm, prefill_mesh=pm, decode_chunk=4,
                            n_slots=1, prefill_chunk=16,
                            prefix_store="host", prefix_store_chunk=16,
                            seed=9360)
    eng_c = InferenceEngine(TINY, make_mesh(MeshConfig(tp=2),
                                            jax.devices()[:2]),
                            decode_chunk=4, n_slots=1, prefill_chunk=16,
                            seed=9360)
    try:
        conv = [(3 + 5 * i) % 500 for i in range(33)]
        other = [(9 + 7 * i) % 500 for i in range(33)]
        out1 = _gen(eng_d, conv, seed=4, n=6)
        eng_d.drain_prefix_store()
        _gen(eng_d, other, seed=5, n=6)  # churn the single slot
        eng_d.drain_prefix_store()
        follow = conv + out1 + [17, 19]
        assert (_gen(eng_d, follow, seed=6, n=6)
                == _gen(eng_c, follow, seed=6, n=6))
        assert eng_d.prefix_store_hits >= 1
        assert eng_d.prefix_store_tokens_restored > 0
    finally:
        eng_d.shutdown()
        eng_c.shutdown()
