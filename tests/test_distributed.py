"""Multi-host helpers (parallel/distributed.py) in their single-process
degenerate form — the multi-host branches are the same code paths with
process_count > 1 (which no test environment can provide; the helpers exist
so one binary spans laptop → chip → pod)."""

import jax
import numpy as np

from quorum_tpu.parallel import MeshConfig
from quorum_tpu.parallel.distributed import (
    assemble_global_batch,
    hybrid_mesh,
    initialize,
    local_data_shard,
)


def test_initialize_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize() is False  # no coordinator, 1 process → not distributed


def test_hybrid_mesh_single_slice_is_plain_mesh():
    mesh = hybrid_mesh(MeshConfig(dp=2, tp=2), dcn_dp=1)
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "tp": 2}


def test_local_data_shard_single_process():
    start, size = local_data_shard(8)
    assert (start, size) == (0, 8)


def test_assemble_global_batch_places_on_dp():
    mesh = hybrid_mesh(MeshConfig(dp=2, tp=2), dcn_dp=1)
    tokens = np.arange(8 * 4, dtype=np.int32).reshape(8, 4)
    arr = assemble_global_batch(tokens, mesh, global_batch=8)
    assert arr.shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(arr), tokens)
    # batch dim is sharded over dp
    assert arr.sharding.spec == jax.sharding.PartitionSpec("dp", None)


def test_train_step_on_hybrid_mesh():
    """The trainer runs unchanged on a hybrid-constructed mesh."""
    from quorum_tpu.models import resolve_spec
    from quorum_tpu.training.trainer import make_train_step, train_init

    spec = resolve_spec("llama-tiny", {"max_seq": "64"})
    mesh = hybrid_mesh(MeshConfig(dp=2, tp=2), dcn_dp=1)
    state = train_init(spec, mesh, seed=0)
    step = make_train_step(spec, mesh)
    tokens = np.ones((4, 32), np.int32) * 7
    state, loss = step(state, tokens)
    assert np.isfinite(float(loss))
