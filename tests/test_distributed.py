"""Multi-host helpers (parallel/distributed.py): the single-process
degenerate forms, plus a TRUE two-process run (test_two_process_train_step
spawns two simulated hosts that join one jax distributed runtime and train
over a hybrid DCN×ICI mesh — the process_count > 1 branches execute for
real, per-host data feeding and cross-process gradient all-reduce
included). The helpers exist so one binary spans laptop → chip → pod."""

import jax
import numpy as np

from quorum_tpu.parallel import MeshConfig
from quorum_tpu.parallel.distributed import (
    assemble_global_batch,
    hybrid_mesh,
    initialize,
    local_data_shard,
)

import pytest
# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow


def test_initialize_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize() is False  # no coordinator, 1 process → not distributed


def test_hybrid_mesh_single_slice_is_plain_mesh():
    mesh = hybrid_mesh(MeshConfig(dp=2, tp=2), dcn_dp=1)
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "tp": 2}


def test_local_data_shard_single_process():
    start, size = local_data_shard(8)
    assert (start, size) == (0, 8)


def test_assemble_global_batch_places_on_dp():
    mesh = hybrid_mesh(MeshConfig(dp=2, tp=2), dcn_dp=1)
    tokens = np.arange(8 * 4, dtype=np.int32).reshape(8, 4)
    arr = assemble_global_batch(tokens, mesh, global_batch=8)
    assert arr.shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(arr), tokens)
    # batch dim is sharded over dp
    assert arr.sharding.spec == jax.sharding.PartitionSpec("dp", None)


def test_train_step_on_hybrid_mesh():
    """The trainer runs unchanged on a hybrid-constructed mesh."""
    from quorum_tpu.models import resolve_spec
    from quorum_tpu.training.trainer import make_train_step, train_init

    spec = resolve_spec("llama-tiny", {"max_seq": "64"})
    mesh = hybrid_mesh(MeshConfig(dp=2, tp=2), dcn_dp=1)
    state = train_init(spec, mesh, seed=0)
    step = make_train_step(spec, mesh)
    tokens = np.ones((4, 32), np.int32) * 7
    state, loss = step(state, tokens)
    assert np.isfinite(float(loss))


def _spawn_pair(worker_script: str, timeout: int = 300) -> list[dict]:
    """Spawn two simulated hosts running ``worker_script`` joined into one
    jax distributed runtime (2 CPU devices each); return their JSON lines."""
    import json
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", worker_script)
    with socket.socket() as s:  # free port for the coordination service
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def spawn(pid: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env["QUORUM_TPU_COMPILE_CACHE"] = "0"
        return subprocess.Popen(
            [sys.executable, worker], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    procs = [spawn(0), spawn(1)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, err[-2000:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # One worker failing must not orphan its sibling blocked in
        # jax.distributed.initialize holding the coordinator port.
        for q in procs:
            if q.poll() is None:
                q.kill()
            q.communicate()
    assert {o["process"] for o in outs} == {0, 1}
    return outs


def test_two_process_serving():
    """TRUE multi-process validation of the SERVING path (VERDICT r3 item
    9): two simulated hosts build one engine over a global dp×tp mesh — the
    KV-cache batch axis sharded across the process (DCN) boundary, weights
    tp-sharded within each host — and serve the same request SPMD-style
    through the real TpuBackend+engine stack (the production multi-host
    serving discipline: a front-end broadcasts the request, every host runs
    the identical dispatch sequence). Both hosts must produce byte-identical
    completions, cold and warm."""
    outs = _spawn_pair("serving_worker.py")
    assert outs[0]["content"] == outs[1]["content"]
    assert outs[0]["content_warm"] == outs[1]["content_warm"]
    assert outs[0]["completion_tokens"] >= 1
    # The cache really spans all four devices of the two processes.
    assert all(o["cache_devices"] == 4 for o in outs), outs


def test_two_process_train_step():
    """TRUE multi-process validation of the multi-host helpers: two
    processes (simulated hosts), two CPU devices each, joined via
    ``initialize()`` into one 4-device runtime; ``hybrid_mesh(dcn_dp=2)``
    spans dp across the processes and one real training step runs with the
    dp gradient all-reduce crossing the process boundary — the DCN path of
    SURVEY.md §5.8, not its single-process degenerate form. Both hosts
    must compute the identical global loss."""
    import json
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "distributed_worker.py")
    with socket.socket() as s:  # free port for the coordination service
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def spawn(pid: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env["QUORUM_TPU_COMPILE_CACHE"] = "0"
        return subprocess.Popen(
            [sys.executable, worker], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    procs = [spawn(0), spawn(1)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, err[-2000:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # One worker failing must not orphan its sibling blocked in
        # jax.distributed.initialize holding the coordinator port.
        for q in procs:
            if q.poll() is None:
                q.kill()
            q.communicate()
    by_pid = {o["process"] for o in outs}
    assert by_pid == {0, 1}
    losses = [o["loss"] for o in outs]
    assert losses[0] == losses[1], f"hosts disagree on the global loss: {losses}"
    assert np.isfinite(losses[0])
