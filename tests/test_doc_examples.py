"""Every worked example in docs/concatenate_behaviour.md and
docs/aggregate_behaviour.md, executed against the real app (VERDICT r2
missing item 3 — doc depth). If a doc example and the code disagree, these
fail: the documents are contracts, not prose.

The running examples match the docs:
  LLM1 → "<think>check the docs</think>Paris."   (concatenate)
  LLM1 → "<think>easy one</think>Paris."          (aggregate)
  LLM2 → "The capital is Paris." / "It is Paris."
  AGG  → "Both sources agree: Paris."
"""

import json

import pytest

from quorum_tpu.backends.fake import FakeBackend
from tests.conftest import make_client

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

QUESTION = "What is the capital of France?"


def concat_config(**flags):
    concatenate = {
        "separator": "\n===\n",
        "hide_intermediate_think": True,
        "hide_final_think": False,
        "thinking_tags": ["think", "reason", "reasoning", "thought"],
        "skip_final_aggregation": False,
        **flags,
    }
    return {
        "settings": {"timeout": 30},
        "primary_backends": [
            {"name": "LLM1", "url": "http://one.test", "model": "m"},
            {"name": "LLM2", "url": "http://two.test", "model": "m"},
        ],
        "iterations": {"aggregation": {"strategy": "concatenate"}},
        "strategy": {"concatenate": concatenate,
                     "aggregate": {"source_backends": "all",
                                   "aggregator_backend": ""}},
    }


def agg_config(backends=3, **flags):
    aggregate = {
        "source_backends": "all",
        "aggregator_backend": "AGG" if backends == 3 else "",
        "intermediate_separator": "\n\n---\n\n",
        "include_source_names": False,
        "source_label_format": "Response from {backend_name}:\n",
        "strip_intermediate_thinking": True,
        "hide_aggregator_thinking": True,
        "thinking_tags": ["think"],
        "include_original_query": True,
        "query_format": "Original query: {query}\n\n",
        "suppress_individual_responses": False,
        **flags,
    }
    primary = [
        {"name": "LLM1", "url": "http://one.test", "model": "m"},
        {"name": "LLM2", "url": "http://two.test", "model": "m"},
    ]
    if backends == 3:
        primary.append({"name": "AGG", "url": "http://agg.test", "model": "m"})
        aggregate.setdefault("source_backends", ["LLM1", "LLM2"])
        aggregate["source_backends"] = flags.get(
            "source_backends", ["LLM1", "LLM2"])
    return {
        "settings": {"timeout": 30},
        "primary_backends": primary,
        "iterations": {"aggregation": {"strategy": "aggregate"}},
        "strategy": {"aggregate": aggregate,
                     "concatenate": {"separator": "\n===\n"}},
    }


def concat_fakes():
    return dict(
        LLM1=FakeBackend("LLM1", text="<think>check the docs</think>Paris.",
                         usage={"prompt_tokens": 9, "completion_tokens": 7,
                                "total_tokens": 16}),
        LLM2=FakeBackend("LLM2", text="The capital is Paris.",
                         usage={"prompt_tokens": 9, "completion_tokens": 5,
                                "total_tokens": 14}),
    )


def agg_fakes():
    return dict(
        LLM1=FakeBackend("LLM1", text="<think>easy one</think>Paris."),
        LLM2=FakeBackend("LLM2", text="It is Paris."),
        AGG=FakeBackend("AGG", text="Both sources agree: Paris."),
    )


async def ask(config, fakes, body_extra=None):
    body = {"model": "m", "messages": [{"role": "user", "content": QUESTION}],
            **(body_extra or {})}
    async with make_client(config, **fakes) as client:
        resp = await client.post("/v1/chat/completions", json=body,
                                 headers={"Authorization": "Bearer doc"})
    return resp


async def sse_frames(config, fakes, body_extra=None):
    body = {"model": "m", "stream": True,
            "messages": [{"role": "user", "content": QUESTION}],
            **(body_extra or {})}
    async with make_client(config, **fakes) as client:
        resp = await client.post("/v1/chat/completions", json=body,
                                 headers={"Authorization": "Bearer doc"})
    lines = [ln for ln in resp.text.splitlines() if ln.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    return [json.loads(ln[6:]) for ln in lines[:-1]]


# ---- concatenate examples --------------------------------------------------

async def test_separator_example():
    resp = await ask(concat_config(hide_final_think=True), concat_fakes())
    content = resp.json()["choices"][0]["message"]["content"]
    assert content == "Paris.\n===\nThe capital is Paris."


async def test_partial_failure_example():
    from quorum_tpu.backends.base import BackendError

    fakes = concat_fakes()
    fakes["LLM1"] = FakeBackend("LLM1", fail_with=BackendError("down"))
    resp = await ask(concat_config(), fakes)
    assert resp.json()["choices"][0]["message"]["content"] == "The capital is Paris."


async def test_hide_final_think_table():
    """Non-streaming stripping is governed by hide_final_think (quirk-6
    parity); hide_intermediate_think is streaming-only."""
    shown = await ask(concat_config(hide_final_think=False), concat_fakes())
    assert shown.json()["choices"][0]["message"]["content"] == (
        "<think>check the docs</think>Paris.\n===\nThe capital is Paris.")
    hidden = await ask(concat_config(hide_final_think=True), concat_fakes())
    assert hidden.json()["choices"][0]["message"]["content"] == (
        "Paris.\n===\nThe capital is Paris.")


async def test_hide_final_think_streaming_example():
    frames = await sse_frames(
        concat_config(hide_intermediate_think=False, hide_final_think=True),
        concat_fakes())
    backend0 = "".join(
        f["choices"][0]["delta"].get("content") or ""
        for f in frames if f["id"] == "chatcmpl-parallel-0")
    assert backend0 == "<think>check the docs</think>Paris."
    final = [f for f in frames if f["id"] == "chatcmpl-parallel-final"]
    assert final[0]["choices"][0]["delta"]["content"] == (
        "Paris.\n===\nThe capital is Paris.")


async def test_thinking_tags_example():
    fakes = concat_fakes()
    fakes["LLM1"] = FakeBackend(
        "LLM1", text="<think>a</think>b<scratch>c</scratch>d")
    resp = await ask(concat_config(thinking_tags=["scratch"],
                                   hide_final_think=True), fakes)
    content = resp.json()["choices"][0]["message"]["content"]
    assert content.startswith("<think>a</think>bd\n===\n")


async def test_skip_final_aggregation_example():
    frames = await sse_frames(concat_config(skip_final_aggregation=True),
                              concat_fakes())
    assert not any(f["id"] == "chatcmpl-parallel-final" for f in frames)
    assert frames[-1]["id"].startswith("chatcmpl-parallel-")


async def test_usage_summing_example():
    resp = await ask(concat_config(), concat_fakes())
    assert resp.json()["usage"] == {
        "prompt_tokens": 18, "completion_tokens": 12, "total_tokens": 30}


# ---- aggregate examples ----------------------------------------------------

async def test_synthesis_prompt_exactly():
    fakes = agg_fakes()
    resp = await ask(agg_config(include_source_names=True), fakes)
    assert resp.json()["choices"][0]["message"]["content"] == (
        "Both sources agree: Paris.")
    prompt = fakes["AGG"].calls[0].body["messages"][0]["content"]
    assert prompt == (
        "Original query: What is the capital of France?\n\n"
        "You have received the following responses regarding the user's query:\n\n"
        "Response from LLM1:\nParis.\n\n---\n\nResponse from LLM2:\nIt is Paris.\n\n"
        "Synthesize these responses into a single, comprehensive answer that captures\n"
        "the best information and insights from all sources. Resolve any contradictions\n"
        "and provide a coherent, unified response."
    )


async def test_fallback_join_example():
    from quorum_tpu.backends.base import BackendError

    fakes = agg_fakes()
    fakes["AGG"] = FakeBackend("AGG", fail_with=BackendError("agg down"))
    resp = await ask(agg_config(), fakes)
    assert resp.json()["choices"][0]["message"]["content"] == (
        "Paris.\n\n---\n\nIt is Paris.")


async def test_source_backends_example():
    fakes = agg_fakes()
    resp = await ask(agg_config(source_backends=["LLM2"],
                                include_source_names=False), fakes)
    assert resp.status_code == 200
    assert fakes["LLM1"].calls == []  # not called at all
    prompt = fakes["AGG"].calls[0].body["messages"][0]["content"]
    assert "It is Paris." in prompt and "Paris.\n\n---" not in prompt


async def test_intermediate_separator_example():
    from quorum_tpu.backends.base import BackendError

    fakes = agg_fakes()
    fakes["AGG"] = FakeBackend("AGG", fail_with=BackendError("agg down"))
    resp = await ask(agg_config(intermediate_separator=" | "), fakes)
    assert resp.json()["choices"][0]["message"]["content"] == (
        "Paris. | It is Paris.")


async def test_source_label_format_example():
    fakes = agg_fakes()
    await ask(agg_config(include_source_names=True,
                         source_label_format="[{backend_name}] says:\n"),
              fakes)
    prompt = fakes["AGG"].calls[0].body["messages"][0]["content"]
    assert "[LLM1] says:\nParis." in prompt


async def test_include_original_query_example():
    fakes = agg_fakes()
    body = {"messages": [
        {"role": "user", "content": QUESTION},
        {"role": "assistant", "content": "Paris."},
        {"role": "user", "content": "Are you sure?"},
    ]}
    await ask(agg_config(), fakes, body_extra=body)
    prompt = fakes["AGG"].calls[0].body["messages"][0]["content"]
    assert prompt.startswith("Original query: What is the capital of France?")
    assert "Are you sure?" not in prompt.split("\n")[0]

    fakes2 = agg_fakes()
    await ask(agg_config(include_original_query=False), fakes2)
    prompt2 = fakes2["AGG"].calls[0].body["messages"][0]["content"]
    assert not prompt2.startswith("Original query:")


async def test_suppress_individual_responses_transcripts():
    frames = await sse_frames(agg_config(suppress_individual_responses=True),
                              agg_fakes())
    ids = [f["id"] for f in frames]
    assert ids[0] == "chatcmpl-parallel"
    assert not any(i.startswith("chatcmpl-parallel-") and i[-1].isdigit()
                   for i in ids)
    final = [f for f in frames if f["id"] == "chatcmpl-parallel-final"]
    assert final[0]["choices"][0]["delta"]["content"] == (
        "Both sources agree: Paris.")

    frames2 = await sse_frames(agg_config(suppress_individual_responses=False),
                               agg_fakes())
    assert any(f["id"] == "chatcmpl-parallel-0" for f in frames2)
