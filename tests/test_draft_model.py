"""Draft-MODEL speculative decoding (``spec_model=…``, engine._DraftRuntime).

A second, small model proposes each verify turn's draft instead of the
prompt-lookup 2-gram heuristic. The acceptance rule is unchanged — a draft
token is accepted iff it equals the target's own greedy token — so output
content NEVER depends on the draft model. These tests pin:

  - exactness: draft-model engines reproduce the plain engine's greedy
    output token-for-token, for a perfect draft (same weights — the
    oracle) and for a useless one (different seed);
  - the oracle actually accelerates: near-full acceptance, strictly fewer
    verify turns than tokens emitted;
  - composition guards (members/ensemble, vocab/window mismatches) fail at
    construction, not per-request.
"""

import pytest

from quorum_tpu.engine.engine import InferenceEngine
from quorum_tpu.models.model_config import resolve_spec
from quorum_tpu.ops.sampling import SamplerConfig

# Engine-scale / compile-heavy / multi-process: slow tier (make test skips,
# make test-all and CI run everything — VERDICT r3 item 6).
pytestmark = pytest.mark.slow

GREEDY = SamplerConfig(temperature=0.0, top_p=1.0)
SPEC = {"n_kv_heads": "4", "max_seq": "256"}
PROMPT = [3, 4, 5, 6, 7, 8]


def _serve(engine, n=24, prompt=PROMPT, seed=7):
    out = engine.generate(prompt, max_new_tokens=n, sampler=GREEDY,
                          seed=seed).token_ids
    return out


def test_oracle_draft_matches_and_accelerates():
    spec = resolve_spec("llama-tiny", SPEC)
    base = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    ref = _serve(base)
    base.shutdown()

    # Same spec, same seed: the draft IS the target, so every drafted token
    # matches the target's greedy chain — maximal acceptance.
    drafted = InferenceEngine(spec, decode_chunk=4, n_slots=2,
                              spec_decode=4, draft_spec=spec, draft_seed=0)
    got = _serve(drafted)
    m = drafted.metrics()
    drafted.shutdown()
    assert got == ref, "draft-model engine changed greedy content"
    assert m["spec_turns_total"] > 0
    # 24 tokens in ≤ ceil(24/5)+1 verify dispatches at g=4 full acceptance.
    assert m["spec_turns_total"] < 24
    assert m["spec_accepted_total"] >= 2 * m["spec_turns_total"], (
        f"oracle draft barely accepted: {m}")


def test_useless_draft_is_harmless():
    spec = resolve_spec("llama-tiny", SPEC)
    base = InferenceEngine(spec, decode_chunk=4, n_slots=2)
    ref = _serve(base, n=12)
    base.shutdown()

    # Different weights: acceptance ~0, content must be identical anyway.
    drafted = InferenceEngine(spec, decode_chunk=4, n_slots=2,
                              spec_decode=4, draft_spec=spec, draft_seed=99)
    got = _serve(drafted, n=12)
    drafted.shutdown()
    assert got == ref


def test_cobatched_drafted_requests_match_serial():
    from concurrent.futures import ThreadPoolExecutor

    spec = resolve_spec("llama-tiny", SPEC)
    prompts = [PROMPT, [9, 10, 11], list(range(3, 40))]
    base = InferenceEngine(spec, decode_chunk=4, n_slots=3)
    ref = [_serve(base, n=10, prompt=p) for p in prompts]
    base.shutdown()

    drafted = InferenceEngine(spec, decode_chunk=4, n_slots=3,
                              spec_decode=4, draft_spec=spec, draft_seed=0)
    with ThreadPoolExecutor(max_workers=3) as ex:
        got = list(ex.map(lambda p: _serve(drafted, n=10, prompt=p), prompts))
    drafted.shutdown()
    assert got == ref


def test_guards_fail_at_construction():
    spec = resolve_spec("llama-tiny", SPEC)
    small_window = resolve_spec("llama-tiny", dict(SPEC, max_seq="128"))
    other_vocab = resolve_spec("gpt2-tiny", {"max_seq": "256",
                                             "vocab_size": "1024"})
    with pytest.raises(ValueError, match="max_seq"):
        InferenceEngine(spec, spec_decode=4, draft_spec=small_window)
    with pytest.raises(ValueError, match="vocab"):
        InferenceEngine(spec, spec_decode=4, draft_spec=other_vocab)
    with pytest.raises(ValueError, match="members"):
        InferenceEngine(spec, members=2, spec_decode=4, draft_spec=spec)


def test_backend_url_knob():
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    be = TpuBackend.from_spec(BackendSpec(
        name="D",
        url="tpu://llama-tiny?n_kv_heads=4&max_seq=256&slots=2"
            "&spec_model=llama-tiny&spec_decode=4&max_tokens=8",
        model="m"))
    body = {"model": "m", "temperature": 0.0, "max_tokens": 8,
            "messages": [{"role": "user", "content": "hello there"}]}
    result = asyncio.run(be.complete(body, {}, 60.0))
    assert result.ok and result.usage["completion_tokens"] >= 1
    assert be.engine.metrics()["spec_turns_total"] > 0
    assert be.engine._draft_rt is not None


def test_backend_propagates_target_window_to_draft():
    """ADVICE r3: the draft must inherit the target's sliding_window (not
    keep its preset) — the docs promise the draft runs the target's
    vocab/window, and a mismatched span only lowers acceptance silently."""
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    be = TpuBackend.from_spec(BackendSpec(
        name="DW",
        url="tpu://llama-tiny?n_kv_heads=4&max_seq=256&sliding_window=64"
            "&slots=1&spec_model=llama-tiny&spec_decode=4&max_tokens=4",
        model="m"))
    draft = be.engine._draft_rt.spec
    target = be.engine.spec
    assert draft.sliding_window == target.sliding_window == 64
    assert draft.max_seq == target.max_seq
    assert draft.vocab_size == target.vocab_size


def test_ckpt_plus_spec_model_rejected():
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    with pytest.raises(ValueError, match="spec_model"):
        TpuBackend.from_spec(BackendSpec(
            name="X", url="tpu://llama-tiny?ckpt=/nonexistent&spec_model=llama-tiny",
            model="m"))


def test_near_window_cap_sync_does_not_corrupt_draft_cache():
    """Pad writes in the sync bites must never run past max_seq: a row
    near the window cap co-batched with a freshly-admitted long prompt
    used to have its bite padded to the fresh row's 16-token stride,
    where dynamic_update_slice clamps the start BACKWARDS and silently
    corrupts already-synced draft positions. The drafts for the capped
    row must equal a clean runtime's drafts."""
    from quorum_tpu.engine.engine import _DraftRuntime

    class R:  # draft_all touches only .hist and object identity
        def __init__(self, hist):
            self.hist = list(hist)

    spec = resolve_spec("llama-tiny", SPEC)  # max_seq 256
    a = R([(i % 97) + 3 for i in range(245)])
    rt = _DraftRuntime(spec, spec, rows=2, seed=0)
    rt.draft_all([(0, a)], g=4)              # sync A to 245
    a.hist.extend([5, 6, 7, 8, 9, 10])       # A now at 251 (cap - g - 1)
    b = R([(i % 89) + 3 for i in range(120)])  # fresh row drives big bites
    drafts = rt.draft_all([(0, a), (1, b)], g=4)

    clean = _DraftRuntime(spec, spec, rows=2, seed=0)
    clean_drafts = clean.draft_all([(0, a)], g=4)
    assert drafts[0] == clean_drafts[0], (
        "near-cap row's draft diverged — its synced cache was corrupted")


def test_explicit_spec_decode_zero_with_draft_rejected():
    spec = resolve_spec("llama-tiny", SPEC)
    with pytest.raises(ValueError, match="spec_decode"):
        InferenceEngine(spec, spec_decode=0, draft_spec=spec)


def _tiny_llama_ckpt(dirpath, seed):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(seed)
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    LlamaForCausalLM(cfg).eval().save_pretrained(
        dirpath, safe_serialization=True)
    return str(dirpath)


def test_spec_ckpt_oracle_and_other_weights(tmp_path):
    """Real-checkpoint draft pairs (spec_ckpt=): the deployment story —
    a small checkpoint drafts for a checkpoint target. Oracle case (draft
    dir == target dir → identical weights) must reproduce the no-draft
    output with high acceptance; a different-weights draft must also
    reproduce it (speed-only, like every draft source)."""
    import asyncio

    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    target = _tiny_llama_ckpt(tmp_path / "target", seed=0)
    other = _tiny_llama_ckpt(tmp_path / "other", seed=1)
    body = {"model": "m", "temperature": 0.0, "max_tokens": 12,
            "messages": [{"role": "user", "content": "draft me a reply"}]}

    def text(url):
        be = TpuBackend.from_spec(BackendSpec(name="C", url=url, model="m"))
        result = asyncio.run(be.complete(body, {}, 120.0))
        assert result.ok, result.body
        return result.content, be.engine

    plain, _ = text(f"tpu://x?ckpt={target}&slots=2&max_tokens=12")
    oracle, eng = text(f"tpu://x?ckpt={target}&slots=2&max_tokens=12"
                       f"&spec_ckpt={target}")
    assert oracle == plain, "spec_ckpt oracle changed ckpt greedy content"
    m = eng.metrics()
    assert m["spec_turns_total"] > 0
    assert m["spec_accepted_total"] >= 2 * m["spec_turns_total"]

    different, _ = text(f"tpu://x?ckpt={target}&slots=2&max_tokens=12"
                        f"&spec_ckpt={other}")
    assert different == plain, "different-weights draft changed content"


def test_draft_source_knob_validation(tmp_path):
    from quorum_tpu.backends.tpu_backend import TpuBackend
    from quorum_tpu.config import BackendSpec

    with pytest.raises(ValueError, match="mutually"):
        TpuBackend.from_spec(BackendSpec(
            name="X",
            url="tpu://llama-tiny?spec_model=llama-tiny&spec_ckpt=/x",
            model="m"))
    with pytest.raises(ValueError, match="config.json"):
        TpuBackend.from_spec(BackendSpec(
            name="X",
            url=f"tpu://llama-tiny?spec_ckpt={tmp_path}/typo",
            model="m"))


def test_draft_over_int8_target_is_exact():
    """quant=int8 target + draft model: the draft (bf16 init) is no longer
    a perfect oracle for the quantized target, so acceptance drops — but
    content must still equal the draft-less int8 engine token for token
    (speed-only, like every draft configuration)."""
    spec = resolve_spec("llama-tiny", SPEC)
    plain = InferenceEngine(spec, decode_chunk=4, n_slots=2, quant="int8")
    ref = _serve(plain, n=12)
    plain.shutdown()

    drafted = InferenceEngine(spec, decode_chunk=4, n_slots=2, quant="int8",
                              spec_decode=4, draft_spec=spec, draft_seed=0)
    got = _serve(drafted, n=12)
    m = drafted.metrics()
    drafted.shutdown()
    assert got == ref
    assert m["spec_turns_total"] > 0
